"""Continuous-batching (Orca-style, iteration-level) GPT decode engine.

One **step program** per engine config — compiled exactly once — takes
a fixed-shape batch of T token rows, where each row is (token, slot,
position): running requests contribute ONE decode row each, freshly
admitted requests contribute up to ``prefill_chunk`` prompt rows
(chunked prefill), and leftover rows are dead padding aimed at the
scratch page.  The program embeds all rows, scatters every row's k/v
into the paged pools at (block_table[slot][pos//page_size],
pos%page_size), gathers each row's block-table view, and attends via
the SAME ``_attend_rows`` code the contiguous decode step uses (with
per-row positions instead of one scalar — that is the whole
continuous-batching trick at the model level).  Greedy argmax logits
are read at each slot's last live row.

Scheduling (host side, plain Python — the device never sees dynamic
shapes):

1. retire finished sequences, recycle their pages;
2. admit queued requests into free slots while the pool can cover
   their prompt (+1 decode) pages;
3. top up pages on demand as running sequences cross a page boundary —
   if the pool is exhausted, preempt the YOUNGEST running request
   (free its pages, requeue it at the front; it re-prefills its
   committed tokens on re-admission, which under greedy decode is
   recompute-exact);
4. build the row batch, run the step program, commit sampled tokens,
   check stop conditions.

Round 11 adds the two raw-decode-speed levers from ROADMAP item 2:

* ``kernel="pallas"`` routes the step program's attention through the
  fused block-table-walk kernel (``kernels/paged_attention.py``):
  online-softmax over pages streamed HBM→VMEM, int8 dequant in the
  inner loop, no materialized gather.  ``"xla"`` (default) keeps the
  gather + ``_attend_rows`` path; both are cross-checked by tests.
* ``spec_K=K`` folds speculative decode INTO the step program: each
  running decode slot feeds its pending token plus K host-drafted
  rows (``serving/drafters.py`` ngram by default), the ONE program
  verifies every row's drafts against its own per-position argmaxes
  (the batched-verify amortization that flips round-6's stand-alone
  negative result), accepted tokens commit by advancing ``n_cached``
  over k/v already written this step, and rejections roll back by
  POINTER only — stale slots are overwritten at the committed
  position before any mask exposes them (the ``_decode_block``
  argument, serving edition).

Round 14 scales the engine UP, not just out: ``tp=N`` lowers the one
step program through a ``parallel/mesh.py`` tensor-parallel mesh.
Params shard by the megatron rules the training side already uses
(``models/transformer.py param_specs``; int8 ``{"q","s"}`` specs
derived — ``models/gpt.py decode_param_specs``), the paged KV pools
shard their HEADS axis (``P(None, None, 'tp', None)``) so each device
holds 1/tp of every page, and every host-built row/table input
replicates.  The scheduler above is untouched: page ids, block
tables, free lists, and the prefix trie are host state meaning "this
slice of every device's shard".  Attention needs no cross-head
collective (softmax and int8-KV quant stats reduce over head_dim,
which stays whole); the output projection's ``P('tp', None)``
contraction is the one GSPMD-inserted reduce per layer.  Declared
shardings live in :func:`step_input_specs`, which graphlint's
sharding-readiness audit verifies against the megatron rule table
(``docs/sharding_readiness.md``, UNCOVERED = 0) and whose pool
donation stays pinned by ``graph-donation``.

Round 18 adds the tier under the pool (ROADMAP item 4): with
``tier_bytes=N`` the engine owns a ``serving/tier_store.py
HostTierStore`` — a byte-budgeted host-DRAM LRU of exact pool-layout
page bytes.  Pressure eviction of refcount-0 prefix chains SPILLS
them there instead of dropping (``PrefixCache`` warm hits re-install
on the next match), and preemption SWAPS the victim's written pages
out (``_preempt_victim``) so resume is install-exact
(``_admit``/``_swap_in``) instead of recompute-exact — preemption
cost becomes O(transfer) instead of O(prefill).  Every tier path
degrades to the pre-tier behavior when the tier refuses or the entry
was LRU-aged: exactness NEVER depends on the tier, only latency does.

Round 21 hides the host scheduler behind device execution (ROADMAP
item 4, ``overlap=True`` / ``MXNET_SERVE_OVERLAP=1``): the step
program grows a per-row ``tok_src`` selector so a decode row's input
token can come from the PREVIOUS step's device-resident argmax matrix
instead of a host-fed value — step N+1 dispatches against step N's
device output before the host has read step N back — and a planner
thread builds step N+1's admission / prefix match / page allocation /
row batch into a second preallocated buffer set while step N runs on
device.  The host consumes tokens one step behind (stop conditions,
commits, metrics); a committed stop/eos/cancel/preemption that
invalidates the speculatively dispatched step reconciles EXACTLY:
the stale row's writes land at positions beyond every committed read
range (the same argument that makes preemption recompute-exact), so
per-row skip suffices, and the only fence is speculative decode
(drafters need committed host tokens — those steps run serially).
``overlap=False`` (the default) is bit-for-bit the round-20 engine:
same compiled program, same host schedule, same commit order.

Exactness: under f32 greedy, engine outputs are token-identical to
``models/gpt.py generate`` per request, whatever the batch mix,
admission order, page reuse, preemptions, swap-outs, kernel choice,
drafter quality, or tp degree — pinned by ``tests/test_serving.py``,
``tests/test_serving_tier.py`` and ``tests/test_serving_tp.py``.

Telemetry (round 8, ``mxnet_tpu/obs``): with ``metrics=True`` (or
``MXNET_SERVING_METRICS=1``) the engine feeds a per-engine
``MetricsRegistry`` — request/step/row counters, queue-depth and
page-pool gauges, TTFT / TBT / admission-wait / step-time histograms —
and, while the profiler is recording, emits per-request lifecycle
spans (admission_wait / prefill / decode / preempt / retire) into the
profiler's chrome-trace stream on the shared ``perf_counter`` clock.
All request timestamps (``Request.submit_t`` / ``token_times``) are on
that clock.  Metrics are OFF by default; the disabled path is one
``is None`` test per call site — no instruments exist, nothing
allocates.
"""
from __future__ import annotations

import dataclasses
import itertools
import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

import numpy as np

from .. import profiler
from ..engine import Engine as _HostEngine
from ..models import gpt as G
from . import drafters
from .paged_kv import PagedKVCache
from .prefix_cache import PrefixCache
from .tier_store import HostTierStore

__all__ = ["Request", "ServingEngine", "step_input_specs",
           "step_output_specs"]


def step_input_specs(params, cfg, kv_int8, tp="tp", overlap=False):
    """The ENGINE'S DECLARED shardings: a mesh-free ``PartitionSpec``
    pytree for every input of the step program, positionally matching
    ``_make_step``'s ``(params, pools, tokens, row_slot, row_pos,
    row_live, bt, slot_rows)`` signature — plus, for the ``overlap``
    variant, the trailing ``(prev_tok, tok_src)`` pair (the previous
    step's device-resident argmax matrix and the per-row selector
    into it), both replicated like every other host-shaped input.

    * params — the megatron rules via ``models/gpt.py
      decode_param_specs`` (int8 q/s specs derived from the float
      rules);
    * pools — heads-sharded pages, ``PagedKVCache.POOL_SPEC``
      (= P(None, None, 'tp', None) on the (pages, page_size, H, 2*dh)
      layout; the f32 scale pool shards the same heads axis, which
      the round-22 tile-shaped retile moved last —
      ``PagedKVCache.S_POOL_SPEC`` = P(None, None, None, 'tp') on
      (pages, 2, page_size, H));
    * everything host-built (token rows, slot/pos/live vectors, block
      tables, sampling-row matrix) — replicated.

    graphlint's sharding-readiness audit verifies THIS table against
    the megatron rules and pins ``docs/sharding_readiness.md`` to it
    (UNCOVERED count 0); the engine binds it to its mesh.  Mesh-free
    so the FAST-tier spec test needs no devices."""
    from jax.sharding import PartitionSpec as P

    from ..models import gpt as G
    from .paged_kv import PagedKVCache

    pool_spec = P(*[tp if a == "tp" else a
                    for a in PagedKVCache.POOL_SPEC])
    pool = {"kv": pool_spec}
    if kv_int8:
        pool["s"] = P(*[tp if a == "tp" else a
                        for a in PagedKVCache.S_POOL_SPEC])
    rep = P()
    out = (G.decode_param_specs(params, cfg, tp=tp),
           [dict(pool) for _ in range(cfg.n_layers)],
           rep, rep, rep, rep, rep, rep)
    if overlap:
        out = out + (rep, rep)
    return out


def step_output_specs(cfg, kv_int8, tp="tp"):
    """Output twin of ``step_input_specs``: the (S, n_sample) argmax
    matrix replicates (the host reads it every step — the one
    intended sync), the returned pools keep the input pool sharding
    (shape/dtype AND sharding match is what keeps donation aliasing
    the buffers in place — the ``graph-donation`` gate)."""
    from jax.sharding import PartitionSpec as P

    from .paged_kv import PagedKVCache

    pool_spec = P(*[tp if a == "tp" else a
                    for a in PagedKVCache.POOL_SPEC])
    pool = {"kv": pool_spec}
    if kv_int8:
        pool["s"] = P(*[tp if a == "tp" else a
                        for a in PagedKVCache.S_POOL_SPEC])
    return (P(), [dict(pool) for _ in range(cfg.n_layers)])


def _bind(mesh, tree):
    """PartitionSpec pytree -> NamedSharding pytree on ``mesh``."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), tree,
        is_leaf=lambda x: isinstance(x, P))


@dataclasses.dataclass
class Request:
    """One generation request and its in-flight bookkeeping."""
    rid: int
    prompt: np.ndarray                    # (P,) int32, immutable
    max_new_tokens: int
    eos_id: Optional[int] = None
    generated: List[int] = dataclasses.field(default_factory=list)
    state: str = "queued"                 # queued|running|done|cancelled
    # runtime (engine-owned)
    slot: Optional[int] = None
    pages: List[int] = dataclasses.field(default_factory=list)
    n_prefilled: int = 0                  # input rows already fed
    n_cached: int = 0                     # positions written to cache
    pending: Optional[int] = None         # sampled, not yet in cache
    # shared-prefix bookkeeping (round 10; empty when the engine runs
    # without a prefix cache)
    prefix_entries: List[Any] = dataclasses.field(default_factory=list)
    shared_pages: set = dataclasses.field(default_factory=set)
    chain_upto: int = 0                   # leading pages known to cache
    prefix_hit_tokens: int = 0            # prefill rows skipped via hits
    # timestamps are time.perf_counter() seconds — the profiler's trace
    # clock (profiler.now_us() / 1e6), so lifecycle spans and op events
    # interleave in one dump
    submit_t: float = 0.0
    wait_start: float = 0.0               # submit or last preemption
    token_times: List[float] = dataclasses.field(default_factory=list)
    # edge-minted trace context (round 23): the HTTP front door's
    # X-Request-Id, stamped into lifecycle trace instants so the edge
    # access log and the engine swimlane correlate by one string
    trace_id: Optional[str] = None

    @property
    def resume_input(self):
        """Prefill source: prompt + committed tokens (after a
        preemption the whole committed sequence re-prefills)."""
        if not self.generated:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)])

    @property
    def output(self):
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)])


# one compiled step program per (cfg, shape) — shared across engines
_step_cache: Dict[Any, Any] = {}
_STEP_CACHE_MAX = 8

# one compiled COW page-copy per pool config — shared across engines
# (round 12: the interleaving explorer builds hundreds of short-lived
# clusters; a per-engine jit here recompiled the same trivial program
# for every replica of every schedule)
_copy_cache: Dict[Any, Any] = {}


def _make_copy(cfg, kv_int8, mesh=None):
    """Jitted whole-page pool copy (COW at a shared-prefix
    divergence).  Page ids are traced scalars, so one compilation per
    pool config covers every (src, dst) pair and every engine whose
    pools share that config.  With ``mesh`` the copy rides the same
    heads-sharded pool placement as the step program (donation
    preserved — the pools stay in place per device, no reshard)."""
    import jax

    key = (cfg, bool(kv_int8), mesh)
    fn = _copy_cache.get(key)
    if fn is not None:
        return fn

    def copy(pools, s, d):
        out = []
        for pool in pools:
            new = {"kv": pool["kv"].at[d].set(pool["kv"][s])}
            if "s" in pool:
                new["s"] = pool["s"].at[d].set(pool["s"][s])
            out.append(new)
        return out

    kw = {}
    if mesh is not None:
        from jax.sharding import PartitionSpec as P
        _, pool_shardings = step_output_specs(cfg, kv_int8)
        pool_shardings = _bind(mesh, pool_shardings)
        rep = _bind(mesh, P())
        kw = {"in_shardings": (pool_shardings, rep, rep),
              "out_shardings": pool_shardings}
    fn = jax.jit(copy, donate_argnums=(0,), **kw)
    if len(_copy_cache) >= _STEP_CACHE_MAX:
        _copy_cache.pop(next(iter(_copy_cache)))
    _copy_cache[key] = fn
    return fn


def _make_step(cfg, num_slots, n_rows, pages_per_slot, page_size,
               kv_int8, kernel="xla", n_sample=1, mesh=None,
               params=None, overlap=False):
    """Build (and cache) the jitted unified prefill+decode step.

    ``kernel`` selects the decode-attention implementation: ``"xla"``
    is the block-table gather + ``_attend_rows`` path (materializes
    the gathered (T*H, L, 2*dh) view), ``"pallas"`` the fused
    ``kernels/paged_attention.py`` walk (online softmax over pages,
    no gather materialization; interpreter mode off-TPU).

    ``n_sample`` is how many argmax rows each slot reads back per step
    (1 + spec_K): with in-engine speculation every decode slot feeds
    its pending token plus K draft rows and the host verifies the
    drafts against the returned per-row argmaxes.

    With ``mesh`` (round 14, tensor-parallel serving) the ONE step is
    lowered through the mesh: ``in_shardings``/``out_shardings`` from
    the engine's declared spec table (``step_input_specs`` — megatron
    rules for params, heads-sharded pools, replicated host rows), and
    donation of the sharded pools survives because every donated pool
    leaf has a shape/dtype/sharding-matched output (``params`` is
    needed for the spec tree's structure only — float vs weight-only
    int8).

    With ``overlap`` (round 21, latency-hiding scheduling) the
    program takes two extra inputs: ``prev_tok``, the PREVIOUS step's
    device-resident ``(S, n_sample)`` argmax matrix, and ``tok_src``,
    a per-row int32 selector — row r's effective input token is
    ``prev_tok[tok_src[r], 0]`` when ``tok_src[r] >= 0`` and
    ``tokens[r]`` otherwise.  That one gather is what takes the host
    readback off the dispatch critical path: step N+1 launches
    against step N's output buffer without the host ever seeing it.
    ``overlap=False`` compiles the EXACT round-20 program (the flag
    is part of the cache key; no ``where`` enters the graph).

    The compiled program is audited by graphlint
    (``tools/analysis/graphlint.py``, tier-1): pool donation is
    verified against the lowering (dropping ``donate_argnums=(1,)``
    here fails ``tests/test_static_analysis.py``), peak live bytes are
    gated by ``tools/analysis/hbm_budgets.json``, and bf16/int8→f32
    upcasts must be declared accumulation points."""
    import jax
    import jax.numpy as jnp

    key = (cfg, num_slots, n_rows, pages_per_slot, page_size,
           bool(kv_int8), kernel, n_sample, mesh, bool(overlap),
           None if mesh is None
           else jax.tree_util.tree_structure(params))
    fn = _step_cache.get(key)
    if fn is not None:
        return fn

    cdt = jnp.dtype(cfg.dtype)
    D, H = cfg.d_model, cfg.n_heads
    dh = D // H
    T = n_rows

    def _body(params, pools, tokens, row_slot, row_pos, row_live, bt,
              slot_rows):
        x = G._embed(params, tokens, cdt)              # (T, D)
        x = x + params["pos_emb"][row_pos].astype(cdt)
        x = G.T._layer_norm(x, params["emb_ln"]["g"].astype(cdt),
                            params["emb_ln"]["b"].astype(cdt))

        # dead rows write to the scratch page and read garbage the
        # host never looks at; bt carries one extra all-zero row
        # (index num_slots) that dead rows point at, so their gathers
        # touch only the scratch page instead of streaming slot 0's
        # real pages
        page_idx = row_pos // page_size                # (T,)
        page = jnp.where(row_live,
                         bt[row_slot, page_idx], 0)    # (T,)
        off = row_pos % page_size
        row_pages = bt[row_slot]                       # (T, PP)

        new_pools = []
        for layer, pool in zip(params["layers"], pools):
            def dn(w):
                return w.astype(cdt)
            qkv = G._qkv(layer, x, cdt)                # (T, 3D)
            q = qkv[:, :D].reshape(T, H, dh)
            k = qkv[:, D:2 * D].reshape(T, H, dh)
            v = qkv[:, 2 * D:].reshape(T, H, dh)

            if kv_int8:
                kvq, skv = G._kv_quantize(k, v)        # (T, H, 2dh/2)
                pool_kv = pool["kv"].at[page, off].set(kvq)
                # retiled scale planes (paged_kv.py): the (N, 2, ps,
                # H) pool takes row r's scales at [page_r, :, off_r]
                # — a (T, 2, H) update, so _kv_quantize's (T, H, 2)
                # transposes once here
                pool_s = pool["s"].at[page, :, off].set(
                    skv.transpose(0, 2, 1))
                new_pools.append({"kv": pool_kv, "s": pool_s})
            else:
                newkv = jnp.concatenate([k, v], axis=-1).astype(cdt)
                pool_kv = pool["kv"].at[page, off].set(newkv)
                pool_s = None
                new_pools.append({"kv": pool_kv})
            if kernel == "pallas":
                # fused block-table walk (kernels/paged_attention.py):
                # pages stream HBM->VMEM per grid step, online-softmax
                # accumulation, int8 dequant in the inner loop — no
                # gathered view is ever materialized.  With a mesh the
                # call shard_maps over tp: each device walks its own
                # H/tp heads slice of the pools (round 22)
                from ..kernels.paged_attention import paged_attention
                attn = paged_attention(q, pool_kv, pool_s, row_pages,
                                       row_pos, page_size=page_size,
                                       mesh=mesh)
            else:
                # block-table gather + _attend_rows — ONE copy of the
                # gather lives in kernels/paged_attention.py, shared
                # with the tests' oracle, so the engine path and the
                # kernel's comparison reference cannot drift apart
                # (scatter-before-gather so every row sees its own
                # k/v, same as the contiguous DUS order)
                from ..kernels.paged_attention import \
                    paged_attention_reference
                attn = paged_attention_reference(
                    q, pool_kv, pool_s, row_pages, row_pos,
                    page_size=page_size)
            attn = attn.reshape(T * H, dh)             # (T*H, dh) f32
            attn = attn.astype(cdt)
            attn = G._wmm(attn.reshape(T, D), layer["wo"], cdt) + \
                dn(layer["bo"])
            x = G.T._layer_norm(x + attn, dn(layer["ln1"]["g"]),
                                dn(layer["ln1"]["b"]))
            if "moe" in layer:
                from ..parallel.moe import moe_ffn
                h, _ = moe_ffn(x[:, None, :], layer["moe"],
                               n_experts=cfg.n_experts,
                               top_k=cfg.expert_top_k,
                               capacity_factor=cfg.capacity_factor,
                               dtype=cdt)
                h = h[:, 0, :]
            else:
                h = jax.nn.gelu(
                    G._wmm(x, layer["w1"], cdt) + dn(layer["b1"]),
                    approximate=True)
                h = G._wmm(h, layer["w2"], cdt) + dn(layer["b2"])
            x = G.T._layer_norm(x + h, dn(layer["ln2"]["g"]),
                                dn(layer["ln2"]["b"]))

        logits = G._lm_head(params, x, cdt)            # (T, V) f32
        # (S, n_sample) argmaxes: column 0 is the slot's sampling row
        # (the old slot_last_row), columns 1.. are its draft-verify
        # rows; dead columns point at row 0 and the host never reads
        # them
        slot_logits = logits[slot_rows]                # (S, n_s, V)
        next_tok = jnp.argmax(slot_logits, axis=-1).astype(jnp.int32)
        return next_tok, new_pools

    if overlap:
        def step(params, pools, tokens, row_slot, row_pos, row_live,
                 bt, slot_rows, prev_tok, tok_src):
            # device-carried inputs: rows with tok_src >= 0 read the
            # previous step's argmax for that slot straight off the
            # device (column 0 = the slot's sampling row); everything
            # else — prefill rows, post-fence decode rows, dead
            # padding — keeps its host-fed token.  An exact int32
            # select: carried steps compute bit-identically to the
            # serial schedule that would have fed the same token.
            eff = jnp.where(
                tok_src >= 0,
                prev_tok[jnp.clip(tok_src, 0, num_slots - 1), 0],
                tokens)
            return _body(params, pools, eff, row_slot, row_pos,
                         row_live, bt, slot_rows)
    else:
        step = _body

    kw = {}
    if mesh is not None:
        kw = {"in_shardings": _bind(
                  mesh, step_input_specs(params, cfg, kv_int8,
                                         overlap=overlap)),
              "out_shardings": _bind(
                  mesh, step_output_specs(cfg, kv_int8))}
    fn = jax.jit(step, donate_argnums=(1,), **kw)
    if len(_step_cache) >= _STEP_CACHE_MAX:
        _step_cache.pop(next(iter(_step_cache)))
    _step_cache[key] = fn
    return fn


class _StepBuffers:
    """One preallocated set of host-side step inputs.  The engine
    owns TWO and rotates: while step N (built into set A) executes on
    device, the planner builds step N+1 into set B — and even the
    serial path rotates, so no step's host buffers are ever mutated
    while a dispatch that snapshot them could still be staging
    (round-21 satellite: no fresh numpy allocations per step)."""

    __slots__ = ("tokens", "row_slot", "row_pos", "row_live",
                 "tok_src", "slot_rows", "bt")

    def __init__(self, n_rows, num_slots, spec_K, pages_per_slot):
        T, S = n_rows, num_slots
        self.tokens = np.zeros(T, np.int32)
        self.row_slot = np.full(T, S, np.int32)
        self.row_pos = np.zeros(T, np.int32)
        self.row_live = np.zeros(T, bool)
        self.tok_src = np.full(T, -1, np.int32)
        self.slot_rows = np.zeros((S, 1 + spec_K), np.int32)
        self.bt = np.zeros((S + 1, pages_per_slot), np.int32)

    def reset(self, num_slots):
        self.tokens.fill(0)
        self.row_slot.fill(num_slots)
        self.row_pos.fill(0)
        self.row_live.fill(False)
        self.tok_src.fill(-1)
        self.slot_rows.fill(0)


class _Plan:
    """One fully-built step: the row batch plus everything the commit
    needs recorded AT BUILD TIME.  Under overlap the commit runs one
    step later than the build, after the planner has already advanced
    ``n_prefilled`` for the NEXT plan — so commits must never read
    live scheduler positions; they read these records."""

    __slots__ = ("buf", "samplers", "spec_plan", "decode_pos",
                 "was_decode", "prefill_mid", "n_dec_rows",
                 "n_pre_rows", "n_rows_used", "decode_rids",
                 "prefill_spans", "carried", "fenced", "empty",
                 "pipelined")

    def __init__(self):
        self.buf = None
        self.samplers = []          # requests sampling a token
        self.spec_plan = {}         # rid -> drafts (serial plans only)
        self.decode_pos = {}        # rid -> its sampling row's pos
        self.was_decode = {}        # rid -> fed a decode row?
        self.prefill_mid = []       # (req, n_prefilled) mid-prefill
        self.n_dec_rows = 0
        self.n_pre_rows = 0
        self.n_rows_used = 0
        self.decode_rids = []       # trace
        self.prefill_spans = []     # trace: (rid, row_lo, row_hi)
        self.carried = 0            # rows fed from device prev_tok
        self.fenced = False         # spec fence: nothing built
        self.empty = True           # no live rows
        self.pipelined = False      # built for the overlap path


def _planner_main(engine_ref, ctl, go, ready):
    """Overlap planner thread body.  A module-level function holding
    only a WEAK engine reference: a bound-method target would keep
    the engine alive through the thread frame and the finalizer below
    could never fire.  Protocol: the engine thread sets ``go`` after
    each commit; the planner builds the next plan under the engine
    lock, publishes it, and sets ``ready`` (the Event pair is the
    happens-before edge for the unlocked ``_plan`` handoff)."""
    while True:
        go.wait()
        go.clear()
        if ctl["stop"]:
            return
        eng = engine_ref()
        if eng is None:
            return
        with eng._mu:
            plan = eng._build_plan(overlap=True)
        eng._plan = plan
        ready.set()
        del eng


def _stop_planner(ctl, go):
    """weakref.finalize target: unpark and retire the planner when
    the engine is collected (captures the control dict + event, never
    the engine)."""
    ctl["stop"] = True
    go.set()


_engine_seq = itertools.count()


class _EngineObs:
    """Per-engine observability bundle: a labeled ``MetricsRegistry``
    (instrument handles bound once at construction — the step path
    does attribute increments, never name lookups) plus the
    request-span trace emitter.  Constructed only when metrics are
    enabled; the engine otherwise carries ``_obs = None`` and every
    call site is a single ``is None`` branch."""

    def __init__(self, registry=None):
        from .. import obs as O
        if registry is None:
            registry = O.MetricsRegistry(
                labels={"engine": str(next(_engine_seq))})
            # self-created registries join the process-wide Prometheus
            # scrape; an explicitly passed registry stays caller-scoped
            O.register_engine_registry(registry)
        self.registry = registry
        c, g, h = registry.counter, registry.gauge, registry.histogram
        self.submitted = c("serving_requests_submitted_total",
                           "requests accepted by submit()")
        self.admitted = c("serving_requests_admitted_total",
                          "admissions into a decode slot (resumes "
                          "after preemption count again)")
        self.finished = c("serving_requests_finished_total",
                          "requests retired done")
        self.cancelled = c("serving_requests_cancelled_total",
                           "requests retired by cancel()")
        self.preemptions = c("serving_preemptions_total",
                             "youngest-victim preemptions")
        self.steps = c("serving_steps_total", "engine iterations")
        self.tokens = c("serving_tokens_total",
                        "tokens committed to requests")
        self.decode_rows = c("serving_decode_rows_total",
                             "decode rows fed to the step program")
        self.prefill_rows = c("serving_prefill_rows_total",
                              "chunked-prefill rows fed")
        self.dead_rows = c("serving_dead_rows_total",
                           "padding rows aimed at the scratch page")
        self.alloc_calls = c("serving_page_alloc_calls_total",
                             "page-allocator calls")
        self.pages_allocated = c("serving_pages_allocated_total",
                                 "pages handed out")
        self.pages_freed = c("serving_pages_freed_total",
                             "pages recycled")
        self.alloc_failures = c("serving_page_alloc_failures_total",
                                "allocations refused by a dry pool "
                                "(caller stalls or preempts)")
        # in-engine speculative decode (round 11; all-zero at spec_K=0)
        self.spec_drafted = c("serving_spec_drafted_tokens_total",
                              "draft tokens fed to the batched "
                              "verify forward")
        self.spec_accepted = c("serving_spec_accepted_tokens_total",
                               "draft tokens committed (matched the "
                               "verify argmax)")
        self.spec_rejected = c("serving_spec_rejected_tokens_total",
                               "draft tokens rolled back by pointer "
                               "(drafted - accepted)")
        self.g_spec_accept_rate = g(
            "serving_spec_accept_rate",
            "cumulative accepted / drafted draft tokens")
        # host-DRAM KV tier (round 18; all-zero when disabled)
        self.tier_spills = c("serving_tier_spills_total",
                             "KV pages spilled HBM -> host tier "
                             "(pressure-evicted prefix chains + "
                             "preemption swap-outs)")
        self.tier_installs = c("serving_tier_installs_total",
                               "KV pages installed host tier -> HBM "
                               "(warm prefix hits + swap-in resumes)")
        self.tier_bytes = c("serving_tier_bytes_total",
                            "bytes moved through the host tier, both "
                            "directions (spill + install + peer "
                            "fetches served from the tier)")
        self.tier_evicted = c("serving_tier_evicted_pages_total",
                              "pages LRU-dropped from the host tier "
                              "(its byte budget, not pool pressure)")
        self.g_tier_pages = g("serving_tier_pages",
                              "KV pages currently held by the host "
                              "tier")
        self.g_tier_bytes = g("serving_tier_bytes_held",
                              "host-DRAM bytes currently held by the "
                              "tier (vs its byte budget)")
        self.g_tier_budget = g("serving_tier_budget_bytes",
                               "the host tier's configured byte "
                               "budget")
        self.warm_hit_tokens = c(
            "serving_prefix_warm_hit_tokens_total",
            "prefill tokens served by re-installing SPILLED chain "
            "pages (the warm-hit outcome between hot-hit and miss)")
        self.swap_outs = c("serving_swap_outs_total",
                           "preemptions whose victim pages were "
                           "swapped to the host tier instead of "
                           "discarded")
        self.swap_ins = c("serving_swap_ins_total",
                          "preemption resumes served install-exact "
                          "from the host tier instead of recomputed")
        # shared-prefix cache (round 10; all-zero when disabled)
        self.prefix_hit_tokens = c("serving_prefix_hit_tokens_total",
                                   "prefill tokens skipped via "
                                   "prefix-cache hits")
        self.prefix_lookup_tokens = c(
            "serving_prefix_lookup_tokens_total",
            "prefill tokens eligible for prefix reuse (admissions)")
        self.prefix_pages_hit = c("serving_prefix_pages_hit_total",
                                  "cached pages mapped read-only into "
                                  "block tables")
        self.prefix_pages_inserted = c(
            "serving_prefix_pages_inserted_total",
            "prompt pages donated to the prefix cache")
        self.prefix_pages_evicted = c(
            "serving_prefix_pages_evicted_total",
            "refcount-0 chains evicted under pool pressure")
        self.prefix_cows = c("serving_prefix_cow_total",
                             "copy-on-write page copies at divergence")
        self.g_prefix_cached = g("serving_prefix_cached_pages",
                                 "pages owned by the prefix cache")
        self.g_prefix_hit_ratio = g(
            "serving_prefix_hit_ratio",
            "cumulative hit tokens / lookup tokens")
        self.g_running = g("serving_running", "requests holding a slot")
        self.g_queued = g("serving_queued", "requests waiting for a "
                          "slot (incl. preempted)")
        self.g_page_free = g("serving_page_free",
                             "free-list length (pages)")
        self.g_pages_in_use = g("serving_pages_in_use",
                                "allocated non-scratch pages")
        self.g_hbm_held = g("serving_hbm_held_bytes",
                            "device bytes held by allocated pages")
        self.g_step_decode = g("serving_step_decode_rows",
                               "decode rows in the latest step")
        self.g_step_prefill = g("serving_step_prefill_rows",
                                "prefill rows in the latest step")
        self.g_step_dead = g("serving_step_dead_rows",
                             "dead rows in the latest step")
        self.h_admission = h("serving_admission_wait_ms",
                             help="submit (or preemption) -> slot "
                                  "admission")
        self.h_ttft = h("serving_ttft_ms",
                        help="submit -> first committed token")
        self.h_tbt = h("serving_tbt_ms",
                       help="interval between committed tokens "
                            "(preemption gaps included)")
        self.h_step = h("serving_step_ms", help="engine step duration")
        from ..obs import RequestTraceEmitter
        self.trace = RequestTraceEmitter()
        # last-seen allocator totals, so sync_cache feeds DELTAS: with
        # a caller-shared registry two engines would otherwise assign
        # competing cumulative values and the counters would go
        # backwards (a Prometheus rate() reads that as a reset)
        self._cache_seen = [0, 0, 0, 0]
        self._prefix_seen = [0, 0, 0, 0, 0, 0]
        self._tier_seen = [0, 0, 0, 0]
        self._warm_seen = [0]             # sync_prefix: warm tokens
        self._swap_seen = [0, 0]          # sync_tier: outs, ins

    def sync_cache(self, cache):
        """Fold the allocator's plain-int telemetry into the registry
        by increment (cache totals only grow between resets).  v <
        last-seen means ``reset_telemetry()`` re-baselined the cache:
        v IS the activity since the reset, so count it rather than
        dropping everything until totals pass the stale baseline."""
        vals = (cache.alloc_calls, cache.alloc_pages_total,
                cache.freed_pages_total, cache.alloc_failures)
        seen = self._cache_seen
        for i, (ctr, v) in enumerate(zip(
                (self.alloc_calls, self.pages_allocated,
                 self.pages_freed, self.alloc_failures), vals)):
            d = v - seen[i]
            if d < 0:              # cache reset: restart from zero
                d = v
            if d > 0:
                ctr.inc(d)
            seen[i] = v
        self.g_page_free.set(cache.free_pages)
        self.g_pages_in_use.set(cache.pages_in_use)
        self.g_hbm_held.set(cache.bytes_held)

    def sync_prefix(self, prefix):
        """Fold the prefix cache's host ints in, delta-wise like
        sync_cache (same shared-registry aggregation argument)."""
        vals = (prefix.hit_tokens_total, prefix.lookup_tokens_total,
                prefix.pages_hit_total, prefix.pages_inserted_total,
                prefix.pages_evicted_total, prefix.cow_total)
        ctrs = (self.prefix_hit_tokens, self.prefix_lookup_tokens,
                self.prefix_pages_hit, self.prefix_pages_inserted,
                self.prefix_pages_evicted, self.prefix_cows)
        seen = self._prefix_seen
        for i, (ctr, v) in enumerate(zip(ctrs, vals)):
            d = v - seen[i]
            if d < 0:
                d = v
            if d > 0:
                ctr.inc(d)
            seen[i] = v
        self.g_prefix_cached.set(prefix.cached_pages)
        self.g_prefix_hit_ratio.set(
            prefix.hit_tokens_total
            / max(1, prefix.lookup_tokens_total))
        # warm-hit token delta rides the prefix sync (the counter
        # lives on the prefix cache, tier or not)
        seen = self._warm_seen
        d = prefix.warm_hit_tokens_total - seen[0]
        if d < 0:
            d = prefix.warm_hit_tokens_total
        if d > 0:
            self.warm_hit_tokens.inc(d)
        seen[0] = prefix.warm_hit_tokens_total

    def sync_tier(self, tier, swap_outs, swap_ins):
        """Fold the host tier's plain-int telemetry in, delta-wise
        like sync_cache (same shared-registry aggregation argument);
        occupancy gauges carry the current tier state."""
        vals = (tier.spilled_pages_total, tier.installed_pages_total,
                tier.bytes_moved_total, tier.evicted_pages_total)
        ctrs = (self.tier_spills, self.tier_installs, self.tier_bytes,
                self.tier_evicted)
        seen = self._tier_seen
        for i, (ctr, v) in enumerate(zip(ctrs, vals)):
            d = v - seen[i]
            if d < 0:                     # tier reset: restart from 0
                d = v
            if d > 0:
                ctr.inc(d)
            seen[i] = v
        self.g_tier_pages.set(tier.pages_held)
        self.g_tier_bytes.set(tier.bytes_held)
        self.g_tier_budget.set(tier.budget_bytes)
        seen = self._swap_seen
        for i, (ctr, v) in enumerate(zip(
                (self.swap_outs, self.swap_ins),
                (swap_outs, swap_ins))):
            d = v - seen[i]
            if d < 0:
                d = v
            if d > 0:
                ctr.inc(d)
            seen[i] = v


class ServingEngine:
    """Continuous-batching greedy decode over a ``PagedKVCache``.

    Parameters
    ----------
    params, cfg : the GPT decode params/config (float or
        ``quantize_decode_params`` weight-only int8 — same formats as
        ``generate``).
    num_slots : concurrent sequences per iteration (the decode batch).
    page_size : tokens per KV page.
    num_pages : pool capacity; default fully provisions every slot
        (``num_slots * pages_per_slot + 1``) — pass less to serve more
        slots than contiguous HBM would allow (page reuse + preemption
        absorb the tail).
    pages_per_slot : per-request length cap in pages; default covers
        ``cfg.max_len``.
    prefill_chunk : prompt tokens fed per iteration (chunked prefill
        rides the same step program; bigger chunks prefill faster but
        make every iteration's compiled batch wider).
    kv_int8 : paged int8-KV cache (the round-4 scale layout).
    prefix_cache : enable refcounted shared-prefix page reuse
        (``serving/prefix_cache.py``): prompts matching cached chains
        map those pages read-only and skip their prefill rows;
        completed prompt pages are donated back; refcount-0 chains are
        LRU-evicted under pool pressure.  Off by default — the
        ``ServingCluster`` turns it on per replica.
    kernel : ``"xla"`` (default) attends via the block-table gather +
        ``_attend_rows``; ``"pallas"`` runs the fused
        ``kernels/paged_attention.py`` block-table walk (interpreter
        mode off-TPU, so tier-1 CPU tests cover the kernel path).
        Outputs differ by 1–2 f32 ulps (online-softmax normalization
        order — the kernel module docstring); greedy token-identity
        vs ``generate`` is pinned for both by ``tests/test_serving``.
    spec_K : in-engine speculative decode — each running decode slot
        drafts K tokens per step, the step program verifies all rows'
        drafts in ONE batched forward over the paged cache, accepted
        tokens commit by pointer-only page advances and rejections
        roll back exactly (stale slots are overwritten before any
        mask exposes them — the ``_decode_block`` argument).  0 (the
        default) disables speculation; the step program then has the
        round-7 shape.  Greedy output stays token-identical to plain
        decode whatever the drafter proposes.
    spec_drafter : ``"ngram"`` (prompt-lookup over the row's committed
        tokens, zero cost — ``serving/drafters.py``) or a callable
        ``f(tokens (n,), K) -> (K,)`` proposing the next K tokens
        (tests use adversarial/oracle callables).
    spec_ngram : n-gram length for the ngram drafter.
    tp : tensor-parallel degree (round 14).  ``tp > 1`` builds (or
        accepts via ``mesh=``) a ``parallel/mesh.py`` serving mesh and
        lowers the ONE compiled step through it: params shard by the
        megatron rules (int8 q/s specs derived), the paged KV pools
        shard the HEADS axis (``P(None, None, 'tp', None)`` — each
        device holds 1/tp of every page), host state (block tables,
        free lists, the prefix-cache trie, row batches) stays
        replicated, and pool donation survives the shardings.  Per-
        device weight and KV-pool bytes drop ~1/tp, so a model ~tp×
        too big for one chip serves; f32-greedy outputs stay
        token-identical to ``tp=1`` and to ``generate`` (pinned by
        ``tests/test_serving_tp.py``).  Requires ``cfg.n_heads % tp
        == 0``.  Both kernels serve tp>1: the XLA gather shards
        through GSPMD, and (round 22) the Pallas block-table walk is
        shard_map-lowered so each device walks its own H/tp heads
        slice — speculation (``spec_K``) composes with both.
    mesh : optional pre-built mesh with a ``tp`` axis (e.g.
        ``parallel.serving_mesh(tp)``); overrides ``tp``.
    tier_bytes : host-DRAM KV tier budget in bytes (round 18).  > 0
        attaches a ``HostTierStore``: pressure-evicted refcount-0
        prefix chains spill to it (and re-install as warm hits),
        preemption victims swap out (and resume install-exact).
        None reads ``MXNET_SERVE_TIER_BYTES`` (off unless set); 0
        disables — the engine then behaves bit-identically to round
        17 (drop on pressure, recompute on resume).
    rid_start : first request id this engine assigns (a cluster gives
        each replica a disjoint block so rids — and their trace
        swimlanes — are unique cluster-wide).
    metrics : True/False enables/disables the obs layer; None (the
        default) reads ``MXNET_SERVING_METRICS`` (off unless "1").
        Disabled means NO instruments exist — the hot path pays one
        ``is None`` branch.
    registry : optional ``obs.MetricsRegistry`` to feed (tests /
        callers wanting isolation); by default the engine creates its
        own, labeled ``{engine="<n>"}``, and registers it with the
        process-wide Prometheus scrape.
    """

    def __init__(self, params, cfg, *, num_slots, page_size=16,
                 num_pages=None, pages_per_slot=None, prefill_chunk=8,
                 kv_int8=False, prefix_cache=False, metrics=None,
                 registry=None, rid_start=0, kernel="xla", spec_K=0,
                 spec_drafter="ngram", spec_ngram=2, tp=1, mesh=None,
                 tier_bytes=None, overlap=None):
        if not cfg.causal:
            cfg = dataclasses.replace(cfg, causal=True)
        if num_slots < 1:
            raise ValueError("ServingEngine: num_slots must be >= 1")
        if prefill_chunk < 1:
            raise ValueError("ServingEngine: prefill_chunk must be "
                             ">= 1")
        if kernel not in ("xla", "pallas"):
            raise ValueError("ServingEngine: kernel must be 'xla' or "
                             "'pallas', got %r" % (kernel,))
        if spec_K < 0:
            raise ValueError("ServingEngine: spec_K must be >= 0")
        if spec_drafter != "ngram" and not callable(spec_drafter):
            raise ValueError("ServingEngine: spec_drafter must be "
                             "'ngram' or a callable")
        if mesh is not None:
            if "tp" not in mesh.axis_names:
                raise ValueError("ServingEngine: mesh has no 'tp' "
                                 "axis (build one with "
                                 "parallel.serving_mesh)")
            if tp not in (1, int(mesh.shape["tp"])):
                raise ValueError(
                    "ServingEngine: tp=%d disagrees with the mesh's "
                    "tp axis (%d)" % (tp, mesh.shape["tp"]))
            tp = int(mesh.shape["tp"])
        if tp < 1:
            raise ValueError("ServingEngine: tp must be >= 1")
        if tp > 1:
            # capability check (round 22): the Pallas walk is mesh-
            # lowered — any kernel serves tp>1 provided the heads
            # axis divides (each device walks H/tp heads of the
            # heads-sharded pools; shard_map needs a whole number of
            # heads per device).  The old blanket pallas×tp>1 error
            # is gone; n_heads % tp is the one genuine requirement
            # either kernel has.
            if cfg.n_heads % tp:
                raise ValueError(
                    "ServingEngine: n_heads=%d not divisible by "
                    "tp=%d — the KV pools shard the heads axis"
                    % (cfg.n_heads, tp))
            if isinstance(params, dict) and any(
                    "moe" in layer for layer in params.get("layers",
                                                           ())):
                raise ValueError(
                    "ServingEngine: MoE decode params are tp=1-only "
                    "this round (expert dispatch is not validated "
                    "under the serving mesh; experts would replicate "
                    "with only the FFN hidden dim sharded)")
            if mesh is None:
                from ..parallel.mesh import serving_mesh
                mesh = serving_mesh(tp)
        self.tp = tp
        # a trivial tp=1 mesh takes the unsharded single-device path
        # (sharding constraints over trivial axes are not free on
        # every backend — the live_axis argument in parallel/mesh.py)
        self.mesh = mesh if tp > 1 else None
        if pages_per_slot is None:
            pages_per_slot = -(-cfg.max_len // page_size)
        # the attention view may be wider than cfg.max_len (its tail
        # is masked scratch); positions are bounded by submit()'s
        # max_len check, which keeps pos_emb indexing in range
        if num_pages is None:
            num_pages = num_slots * pages_per_slot + 1
        if num_pages < pages_per_slot + 1:
            raise ValueError(
                "ServingEngine: num_pages (%d) cannot hold one "
                "max-length request (%d pages + scratch)"
                % (num_pages, pages_per_slot))
        if self.mesh is not None:
            # commit the params into their megatron shards NOW: per-
            # device weight bytes drop ~1/tp from this point on (the
            # "model ~tp× too big for one chip" half of the claim —
            # the pools are the other half)
            import jax
            params = jax.device_put(
                params, _bind(self.mesh,
                              G.decode_param_specs(params, cfg)))
        self.params = params
        self.cfg = cfg
        self.num_slots = num_slots
        self.page_size = page_size
        self.pages_per_slot = pages_per_slot
        self.prefill_chunk = prefill_chunk
        self.kv_int8 = bool(kv_int8)
        self.kernel = kernel
        self.spec_K = int(spec_K)
        self.spec_drafter = spec_drafter
        self.spec_ngram = int(spec_ngram)
        self.max_seq = pages_per_slot * page_size
        # with speculation every decode slot may feed 1 + K rows
        # (pending + drafts); the program shape stays fixed, unused
        # draft rows are dead padding like everything else
        self.n_rows = num_slots * (1 + self.spec_K) + prefill_chunk
        self.cache = PagedKVCache(cfg, num_pages, page_size,
                                  kv_int8=self.kv_int8,
                                  mesh=self.mesh)
        # host-DRAM KV tier (round 18): explicit argument >
        # MXNET_SERVE_TIER_BYTES env > off.  0/None disables — every
        # pre-tier behavior (drop on pressure, recompute on resume)
        # is preserved bit for bit with the tier off.
        if tier_bytes is None:
            env = os.environ.get("MXNET_SERVE_TIER_BYTES", "")
            try:
                tier_bytes = int(env) if env else 0
            except ValueError:
                raise ValueError(
                    "MXNET_SERVE_TIER_BYTES=%r: expected int" % env)
        self.tier = HostTierStore(tier_bytes) if tier_bytes else None
        # shared-prefix page reuse (round 10): content-keyed trie over
        # the pool; the allocator's pressure callback evicts (round
        # 18: spills) refcount-0 chains before ever refusing a live
        # request
        self.prefix = PrefixCache(self.cache, tier=self.tier) \
            if prefix_cache else None
        if self.prefix is not None:
            self.cache.pressure_cb = self.prefix.evict
        # latency-hiding overlap (round 21): explicit argument >
        # MXNET_SERVE_OVERLAP env > off.  overlap=False is bit-for-bit
        # the round-20 serial engine (same step program, same
        # schedule); overlap=True pipelines the host scheduler with
        # device execution — see the module docstring.
        if overlap is None:
            overlap = os.environ.get("MXNET_SERVE_OVERLAP",
                                     "0") == "1"
        self.overlap = bool(overlap)
        self._copy_fn = None              # jitted COW page copy
        if self.prefix is not None:
            # pre-compile the COW program now (scratch-onto-scratch is
            # a no-op copy): the first real divergence must not stall
            # the serving loop for a compile — page ids are traced
            # scalars, so this one compilation covers every (src, dst)
            self._cow_page(0, 0)
        self._step_fn = _make_step(cfg, num_slots, self.n_rows,
                                   pages_per_slot, page_size,
                                   self.kv_int8, kernel=self.kernel,
                                   n_sample=1 + self.spec_K,
                                   mesh=self.mesh, params=self.params,
                                   overlap=self.overlap)
        self._queue: List[Request] = []
        self._slots: List[Optional[Request]] = [None] * num_slots
        # rid_start: a ServingCluster gives each replica a disjoint
        # rid block so request ids (and their trace swimlanes) stay
        # unique across the whole cluster
        self._next_rid = int(rid_start)
        self.requests: Dict[int, Request] = {}
        self.stats = {"steps": 0, "preemptions": 0, "admitted": 0,
                      "decode_rows": 0, "prefill_rows": 0,
                      "dead_rows": 0, "peak_pages": 0,
                      "prefix_hit_tokens": 0, "cow_copies": 0,
                      "spec_drafted": 0, "spec_accepted": 0,
                      "swap_outs": 0, "swap_ins": 0,
                      "slot_occupancy_sum": 0.0,
                      "host_hidden_ms": 0.0, "overlap_steps": 0,
                      "overlap_fences": 0}
        # -------- round 21: scheduler/planner shared state ---------
        # One lock (_mu) guards everything BOTH the engine thread and
        # the planner thread touch: queue/slots/pages/prefix/stats and
        # the request fields they mutate.  The plan handoff itself
        # (_plan / _plan_pending / _inflight*) is engine-thread-owned
        # or sequenced by the _plan_go/_plan_ready Event pair and
        # deliberately stays OUTSIDE the lock — pylocklint sees those
        # groups as consistently unguarded.
        self._mu = threading.Lock()
        self._bufs = (
            _StepBuffers(self.n_rows, num_slots, self.spec_K,
                         pages_per_slot),
            _StepBuffers(self.n_rows, num_slots, self.spec_K,
                         pages_per_slot))
        self._buf_idx = 0
        # canonical block table, patched incrementally at page
        # alloc/free time (satellite: no full rebuild per step); row
        # num_slots stays all-scratch for dead rows
        self._bt = np.zeros((num_slots + 1, pages_per_slot), np.int32)
        self._inflight = None        # _Plan currently on device
        self._inflight_tok = None    # its device-resident next_tok
        self._plan = None            # planner -> engine handoff slot
        self._plan_pending = False   # engine-thread-only flag
        self._plan_go = threading.Event()
        self._plan_ready = threading.Event()
        self._planner = None         # lazily spawned on first overlap
        self._planner_ctl = None
        self._finalizer = None
        self._tok0 = None            # lazy zeros for the first prev_tok
        if metrics is None:
            # an explicitly supplied registry is a request for
            # telemetry; otherwise the env var decides
            metrics = registry is not None or \
                os.environ.get("MXNET_SERVING_METRICS", "0") == "1"
        elif not metrics and registry is not None:
            raise ValueError(
                "ServingEngine: registry= given but metrics=False — "
                "the registry would be silently ignored")
        self._obs = _EngineObs(registry) if metrics else None
        # optional retire hook (round 15, disaggregated serving): step
        # frees a finished request's pages before returning, but the
        # prefill worker must export them for the handoff stream —
        # the callback runs at retire time, pages still assigned.
        # (Freed page CONTENT stays intact until the NEXT step's
        # allocations, so a post-step export of the snapshotted ids
        # is race-free on the single engine thread.)
        self.retire_cb = None

    # ------------------------------------------------------- intake --
    def submit(self, prompt, max_new_tokens, eos_id=None,
               trace_id=None):
        """Queue a request; returns its id.  prompt: (P,) ints."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("submit: empty prompt")
        if max_new_tokens < 1:
            raise ValueError("submit: max_new_tokens must be >= 1")
        total = prompt.size + max_new_tokens
        if total > self.max_seq:
            raise ValueError(
                "submit: %d tokens > engine max_seq %d (pages_per_slot"
                " * page_size)" % (total, self.max_seq))
        # the final sampled token never enters the cache, so cache
        # positions top out at total - 1 <= max_len (same contract as
        # generate: P + max_new <= cfg.max_len)
        if total > self.cfg.max_len:
            raise ValueError("submit: %d tokens > cfg.max_len=%d"
                             % (total, self.cfg.max_len))
        now = time.perf_counter()
        with self._mu:
            req = Request(rid=self._next_rid, prompt=prompt,
                          max_new_tokens=int(max_new_tokens),
                          eos_id=eos_id, submit_t=now, wait_start=now,
                          trace_id=trace_id)
            self._next_rid += 1
            self.requests[req.rid] = req
            self._queue.append(req)
            if self._obs is not None:
                self._obs.submitted.inc()
                self._obs.g_queued.set(len(self._queue))
            return req.rid

    @property
    def free_slots(self):
        """Decode slots currently unoccupied (the disaggregated decode
        worker admits handed-off requests only when one is free)."""
        return sum(r is None for r in self._slots)

    def admit_prefilled(self, prompt, generated, pages, *,
                        max_new_tokens, eos_id=None, rid=None):
        """Adopt an externally-prefilled request (disaggregated
        serving, round 15): ``pages`` were already allocated from THIS
        engine's cache and installed with the k/v content of positions
        ``[0, P + len(generated) - 1)`` (P = prompt length) — the
        prefill replica's exact pool bytes.  ``generated`` must carry
        at least the prefill side's first sampled token; the request
        resumes mid-decode exactly where a single engine would be
        after committing those tokens (``pending`` = the last one,
        ``n_cached`` = P + len(generated) - 1), so under f32 greedy
        the continuation is bit-identical to an undisturbed run.

        Raises if no slot is free — the caller (the decode worker
        loop) checks ``free_slots`` first and re-tries later rather
        than queueing device pages behind a full engine."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        generated = [int(t) for t in generated]
        if not generated:
            raise ValueError("admit_prefilled: needs >= 1 committed "
                             "token (the prefill side samples the "
                             "first before handoff)")
        if prompt.size < 1:
            raise ValueError("admit_prefilled: empty prompt")
        total = prompt.size + max_new_tokens
        if total > self.max_seq or total > self.cfg.max_len:
            raise ValueError(
                "admit_prefilled: %d tokens > max_seq %d / max_len %d"
                % (total, self.max_seq, self.cfg.max_len))
        with self._mu:
            free = [i for i, r in enumerate(self._slots)
                    if r is None]
            if not free:
                raise RuntimeError("admit_prefilled: no free slot")
            n_cached = prompt.size + len(generated) - 1
            need = -(-n_cached // self.page_size) if n_cached else 0
            if len(pages) < need:
                raise ValueError(
                    "admit_prefilled: %d pages cannot cover %d cached"
                    " positions" % (len(pages), n_cached))
            now = time.perf_counter()
            if rid is None:
                rid = self._next_rid
                self._next_rid += 1
            req = Request(rid=rid, prompt=prompt,
                          max_new_tokens=int(max_new_tokens),
                          eos_id=eos_id, submit_t=now, wait_start=now)
            req.generated = generated
            req.pending = generated[-1]
            req.n_cached = n_cached
            req.n_prefilled = n_cached
            req.pages = list(pages)
            req.slot = free[0]
            req.state = "running"
            self.requests[rid] = req
            self._slots[req.slot] = req
            self._bt_set(req.slot, req.pages)
            self.stats["admitted"] += 1
            if self._obs is not None:
                self._obs.submitted.inc()
                self._obs.admitted.inc()
                self._obs.g_running.set(
                    sum(r is not None for r in self._slots))
            return rid

    def cancel(self, rid):
        """Force-retire a request (frees its slot and pages
        immediately; queued requests are simply dropped).  A cancel
        landing after completion — the inherent client race — is a
        no-op: the finished output stays retrievable."""
        with self._mu:
            req = self.requests[rid]
            if req.state in ("done", "cancelled"):
                return
            if req.state == "queued":
                self._queue.remove(req)
                if self.tier is not None:
                    # a swapped-out victim cancelled while queued must
                    # not squat in the host tier until LRU age-out
                    self.tier.drop(("swap", rid))
            elif req.state == "running":
                self._release(req)
            req.state = "cancelled"
            if self._obs is not None:
                self._obs.cancelled.inc()
                self._obs.g_queued.set(len(self._queue))
                self._obs.g_running.set(
                    sum(r is not None for r in self._slots))
                if profiler.is_recording():
                    cargs = {"state": "cancelled"}
                    if req.trace_id:
                        cargs["trace_id"] = req.trace_id
                    self._obs.trace.add_instant(
                        rid, "retire", time.perf_counter(),
                        args=cargs)
                    self._obs.trace.flush()

    # ----------------------------------------------------- plumbing --
    # (Every helper below mutates scheduler state the planner thread
    # also reads/writes — callers hold the engine lock.)

    def _bt_set(self, slot, pages):
        """Patch the canonical block table's row for ``slot`` to
        ``pages`` (satellite: incremental patching, no per-step
        rebuild).  A local row view keeps the slice stores cheap."""
        # mxlint: requires(ServingEngine._mu)
        row = self._bt[slot]
        n = min(len(pages), row.size)
        row[:n] = pages[:n]
        row[n:] = 0

    def _bt_clear(self, slot):
        # mxlint: requires(ServingEngine._mu)
        self._bt[slot, :] = 0

    # mxlint: requires(ServingEngine._mu)
    def _release(self, req):
        if req.slot is not None:
            # clear the block-table row BEFORE nulling the slot: no
            # window where a freed page id sits in a live-looking row
            self._bt_clear(req.slot)
        if req.pages:
            if req.shared_pages:
                # cache-owned pages stay cached (their refs drop
                # below); only privately-owned pages return to the pool
                self.cache.free([p for p in req.pages
                                 if p not in req.shared_pages])
            else:
                self.cache.free(req.pages)
            req.pages = []
        if req.prefix_entries:
            self.prefix.release(req.prefix_entries)
            req.prefix_entries = []
        req.shared_pages = set()
        req.chain_upto = 0
        if req.slot is not None:
            self._slots[req.slot] = None
            req.slot = None

    # mxlint: requires(ServingEngine._mu)
    def _preempt_for(self, req):
        """Free one+ pages by preempting the youngest running request
        other than ``req``; returns True if anything was preempted."""
        victims = [r for r in self._slots
                   if r is not None and r is not req]
        if not victims:
            return False
        self._preempt_victim(max(victims, key=lambda r: r.rid))
        return True

    # mxlint: requires(ServingEngine._mu)
    def _preempt_victim(self, victim):
        """Evict ``victim`` from its slot and requeue it at the front.
        With a host tier the victim's written pages are SWAPPED OUT
        first — the exact pool bytes of positions ``[0, n_cached)``
        move to host DRAM and resume becomes install-exact instead of
        recompute-exact (O(transfer), not O(prefill)).  The export
        must precede ``_release`` so the pages are captured before
        the free list reclaims them for the very allocation that
        forced this preemption.  A refused swap (tier full/absent)
        falls back to the round-7 recompute path; either resume is
        bit-identical to an undisturbed run under f32 greedy."""
        swapped = False
        if self.tier is not None and victim.n_cached > 0:
            n = -(-victim.n_cached // self.page_size)
            if n * self.cache.bytes_per_page <= self.tier.budget_bytes:
                # budget pre-check BEFORE the device gather: a victim
                # the tier must refuse would otherwise pay a full
                # export round trip per preemption just to throw the
                # bytes away (the export layout is exactly the pool
                # layout, so bytes_per_page predicts the refusal)
                content = self.cache.export_pages(victim.pages[:n])
                swapped = self.tier.put(
                    ("swap", victim.rid), content, n,
                    meta={"n_cached": victim.n_cached,
                          "pending": victim.pending})
            if swapped:
                self.stats["swap_outs"] += 1
        self._release(victim)
        victim.state = "queued"
        victim.n_prefilled = 0
        victim.n_cached = 0
        victim.pending = None
        self._queue.insert(0, victim)
        self.stats["preemptions"] += 1
        if self._obs is not None:
            now = time.perf_counter()
            victim.wait_start = now
            self._obs.preemptions.inc()
            if profiler.is_recording():
                self._obs.trace.add_instant(
                    victim.rid, "preempt", now,
                    args={"committed": len(victim.generated),
                          "swapped": swapped})
        return swapped

    def preempt(self, rid):
        """Force-preempt one RUNNING request through the standard
        victim path (swap-out when a tier is attached) — the chaos /
        benchmark / ops lever behind the swap-vs-recompute resume
        measurement.  Returns True if the pages were swapped out,
        False for a recompute-resume preemption."""
        with self._mu:
            req = self.requests[rid]
            if req.state != "running":
                raise ValueError(
                    "preempt(%d): request is %s, not running"
                    % (rid, req.state))
            return self._preempt_victim(req)

    def _cow_page(self, src, dst):
        """Device-copy page ``src`` into ``dst`` across every layer
        pool (copy-on-write at a shared-prefix divergence) via the
        module-level keyed-cache program (``_make_copy``); pools are
        donated and update in place like the step program's."""
        if self._copy_fn is None:
            self._copy_fn = _make_copy(self.cfg, self.kv_int8,
                                       mesh=self.mesh)
        self.cache.pools = self._copy_fn(self.cache.pools, src, dst)

    # mxlint: requires(ServingEngine._mu)
    def _insert_prefix(self, req):
        """Donate req's freshly-completed, fully-prompt-covered pages
        to the prefix cache (so later requests sharing the prefix skip
        their prefill).  Pages past ``chain_upto`` whose every position
        is both written (n_cached) and prompt-derived qualify."""
        upto = min(req.prompt.size, req.n_cached) // self.page_size
        if upto <= req.chain_upto:
            return
        new = self.prefix.insert_chain(req.prompt, req.pages, upto,
                                       from_page=req.chain_upto)
        for j, entry in new:
            req.shared_pages.add(req.pages[j])
            req.prefix_entries.append(entry)
        req.chain_upto = upto

    # mxlint: requires(ServingEngine._mu)
    def _ensure_page(self, req, pos):
        """Make req's block table cover position pos (allocating, or
        preempting another request when the pool is dry)."""
        idx = pos // self.page_size
        grew = idx >= len(req.pages)
        while idx >= len(req.pages):
            got = self.cache.alloc(1)
            if got is None:
                if not self._preempt_for(req):
                    raise RuntimeError(
                        "ServingEngine: page pool exhausted by a "
                        "single request — grow num_pages")
                continue
            req.pages.extend(got)
        if grew and req.slot is not None:
            self._bt_set(req.slot, req.pages)
        return True

    # mxlint: requires(ServingEngine._mu)
    def _admit(self):
        while self._queue:
            free_slots = [i for i, r in enumerate(self._slots)
                          if r is None]
            if not free_slots:
                return
            req = self._queue[0]
            inp = req.resume_input
            if self.tier is not None:
                swapped = self._swap_in(req, inp, free_slots[0])
                if swapped == "admitted":
                    continue
                if swapped == "stall":
                    return
            total = -(-min(inp.size + 1, self.max_seq)
                      // self.page_size)
            # shared-prefix match: map cached pages read-only, skip
            # their prefill rows.  Always re-feed at least the final
            # input token — the step program needs one live row at the
            # end of the input to produce this request's logits.
            entries, hit_pages, m_tok = ([], [], 0) \
                if self.prefix is None else self.prefix.match(inp)
            skip = min(m_tok, inp.size - 1)
            cow_idx = skip // self.page_size
            cow = cow_idx < len(hit_pages)
            try:
                got = self.cache.alloc(total - len(hit_pages)
                                       + (1 if cow else 0))
            except BaseException:
                # pylocklint py-ref-leak (round 12): alloc can raise
                # through the pressure callback — the refs match()
                # just took must not leak on that edge, or the chain
                # stays pinned unevictable for the engine's lifetime
                if entries:
                    self.prefix.release(entries)
                raise
            if got is None:
                if entries:
                    self.prefix.release(entries)
                return                     # stall admission, not decode
            self._queue.pop(0)
            req.pages = list(hit_pages)
            req.shared_pages = set(hit_pages)
            req.prefix_entries = entries
            if cow:
                # the first position this request writes falls inside
                # the last mapped page (partial-page match, or a
                # whole-input match re-feeding its final token):
                # copy-on-write it into a private page before any row
                # targets it — the shared page is never written
                assert cow_idx == len(hit_pages) - 1
                priv = got.pop()
                self._cow_page(hit_pages[cow_idx], priv)
                req.pages[cow_idx] = priv
                req.shared_pages.discard(hit_pages[cow_idx])
                self.prefix.release([req.prefix_entries.pop()])
                self.prefix.note_cow()
                self.stats["cow_copies"] += 1
            req.chain_upto = len(req.prefix_entries)
            req.pages.extend(got)
            if self.prefix is not None:
                self.prefix.note_admit(skip, inp.size,
                                       len(req.shared_pages))
                self.stats["prefix_hit_tokens"] += skip
                req.prefix_hit_tokens = skip
            req.slot = free_slots[0]
            req.state = "running"
            req.n_prefilled = skip
            req.n_cached = skip
            req.pending = None
            self._slots[req.slot] = req
            self._bt_set(req.slot, req.pages)
            self.stats["admitted"] += 1
            if self._obs is not None:
                now = time.perf_counter()
                self._obs.admitted.inc()
                self._obs.h_admission.observe(
                    (now - req.wait_start) * 1e3)
                if profiler.is_recording():
                    self._obs.trace.add_span(
                        req.rid, "admission_wait", req.wait_start, now)
                    if req.generated:
                        self._obs.trace.add_instant(req.rid, "resume",
                                                    now)

    # mxlint: requires(ServingEngine._mu)
    def _swap_in(self, req, inp, slot):
        """Install-exact resume (round 18): if ``req`` was preempted
        with its pages swapped to the host tier, re-install the exact
        pool bytes and resume at the saved ``(n_cached, pending)``
        state — no re-prefill, O(transfer).  Returns ``"admitted"``
        (slot taken, caller continues), ``"stall"`` (the pool cannot
        hold the swap right now — admission stalls exactly like the
        round-7 alloc-refused path, the tier entry is kept for the
        next try), or ``"none"`` (no swap entry: the caller runs the
        normal match/alloc admission — a swap LRU-evicted from the
        tier degrades to recompute-exact, never to wrong)."""
        entry = self.tier.peek(("swap", req.rid))
        if entry is None:
            return "none"
        # same page coverage as the normal admission path: the
        # chunked-prefill plan (a mid-prefill victim resumes its
        # remaining prompt rows) assumes every input position's page
        # already exists — the swapped pages are a PREFIX of that set
        total = max(entry.n_pages,
                    -(-min(inp.size + 1, self.max_seq)
                      // self.page_size))
        got = self.cache.alloc(total)
        if got is None:
            return "stall"
        entry = self.tier.pop(("swap", req.rid))
        if entry is None:
            # evicted between peek and pop (the alloc's own pressure
            # spills insert ahead of it; peek pinned recency, so this
            # is a can't-fit-both corner): give the pages back and
            # recompute
            self.cache.free(got)
            return "none"
        self.cache.install_pages(got[:entry.n_pages], entry.content)
        self._queue.pop(0)
        req.pages = got
        req.shared_pages = set()
        req.prefix_entries = []
        req.chain_upto = 0
        req.slot = slot
        req.state = "running"
        req.n_cached = entry.meta["n_cached"]
        req.pending = entry.meta["pending"]
        # a decode-phase victim resumes fully prefilled; a victim
        # caught mid-prefill (pending is None) continues its chunked
        # prefill from the first unwritten position
        req.n_prefilled = inp.size if req.pending is not None \
            else req.n_cached
        self._slots[slot] = req
        self._bt_set(slot, req.pages)
        self.stats["admitted"] += 1
        self.stats["swap_ins"] += 1
        if self._obs is not None:
            now = time.perf_counter()
            self._obs.admitted.inc()
            self._obs.h_admission.observe((now - req.wait_start) * 1e3)
            if profiler.is_recording():
                self._obs.trace.add_span(
                    req.rid, "admission_wait", req.wait_start, now)
                self._obs.trace.add_instant(
                    req.rid, "resume", now,
                    args={"swap_in": True,
                          "pages": len(req.pages)})
        return "admitted"

    # mxlint: requires(ServingEngine._mu)
    def _plan_speculation(self):
        """Phase-A speculation planning: for every running decode row
        propose K_eff draft tokens (host-side — the drafters are
        vectorized so this prices like the rest of the per-step host
        scheduling) and secure pages through the deepest draft write
        position.  K_eff = min(spec_K, tokens this request may still
        commit) keeps every draft's cache position within the
        request's admitted budget (positions top out at
        prompt+max_new-1, the same bound submit() enforced), so no
        extra headroom is ever needed.  Returns {rid: drafts (K_eff,)
        np.int32}.  MUST run before any row is built — _ensure_page
        may preempt (the phase-A contract in step())."""
        plan = {}
        if self.spec_K < 1:
            return plan
        vmax = self.cfg.vocab_size - 1
        for req in list(self._slots):
            if req is None or req.pending is None:
                continue
            k_eff = min(self.spec_K,
                        req.max_new_tokens - len(req.generated))
            if k_eff < 1:
                continue
            buf = np.concatenate(
                [req.prompt, np.asarray(req.generated, np.int32)])
            if callable(self.spec_drafter):
                d = np.asarray(self.spec_drafter(buf, k_eff),
                               np.int32).reshape(-1)
                if d.size != k_eff:
                    raise ValueError(
                        "spec_drafter returned %d proposals, wanted "
                        "%d" % (d.size, k_eff))
                # clamp into the vocab: an out-of-range proposal would
                # index-clamp inside the program and silently verify
                # as a different token
                d = np.clip(d, 0, vmax)
            else:
                d = drafters.ngram_draft(buf, k_eff, self.spec_ngram)
            self._ensure_page(req, req.n_cached + k_eff)
            # _ensure_page never preempts req itself, but it may have
            # preempted a LATER slot this loop already planned — the
            # build phase skips slot-less requests, so a stale plan
            # entry is never fed
            plan[req.rid] = d
        return plan

    # --------------------------------------------------------- step --
    def step(self):
        """One engine iteration.  Returns the list of request ids
        whose COMMIT landed during this call (possibly empty); False
        when there is nothing left to do.  ``overlap=False`` runs the
        round-20 serial schedule; ``overlap=True`` runs the pipelined
        schedule — dispatch step N+1 against step N's device-resident
        tokens, then drain/commit step N — so a request's finish is
        reported one call after the step that produced its last
        token."""
        return self._step_overlap() if self.overlap \
            else self._step_serial()

    def _step_serial(self):
        """One fully-serial iteration — the round-20 schedule exactly:
        build (phases A+B, under the lock), dispatch, block on the
        readback, commit (phase C, under the lock)."""
        if not self._queue and all(r is None for r in self._slots):
            return False
        obs = self._obs
        t_step0 = time.perf_counter() if obs is not None else 0.0
        with self._mu:
            plan = self._build_plan(overlap=False)
        if obs is not None:
            # the step program is the serving layer's "operator": route
            # its start/stop through the host engine's op-hook choke
            # point so a recording profiler logs it as a cat-"operator"
            # event interleaved with the request spans below
            _HostEngine.get().notify("start", "serving_step")
        try:
            next_tok = self._dispatch(plan)
            # mxlint: allow(host-sync) -- intentional: the ONE device
            # sync per step; the host scheduler branches on the sampled
            # tokens (stop conditions, commits) before the next step
            next_tok = np.asarray(next_tok)
        finally:
            if obs is not None:
                _HostEngine.get().notify("stop", "serving_step")
        now = time.perf_counter()
        with self._mu:
            return self._commit(plan, next_tok, now, t_step0)

    def _step_overlap(self):
        """One pipelined iteration (round 21).  Call k: take plan k
        (planner-built while call k-1's dispatch executed, or built
        inline on a cold start), dispatch it against the in-flight
        step's device-resident tokens, THEN drain/commit step k-1 —
        the host-side commit of k-1 and the planner's build of k+1
        both hide behind step k's device execution."""
        self._ensure_planner()
        obs = self._obs
        t0 = time.perf_counter() if obs is not None else 0.0
        plan = self._take_plan()
        if plan is None:
            return False
        if plan.fenced:
            # speculation fence: drafting reads fully-committed host
            # state, so drain the pipeline and run ONE exact serial
            # step (full round-20 semantics, spec planning included),
            # then resume pipelining
            finished = []
            old, old_tok = self._inflight, self._inflight_tok
            self._inflight = None
            self._inflight_tok = None
            if old is not None:
                finished += self._drain(old, old_tok, t0)
            out = self._step_serial()
            if out is not False:
                finished += out
            self._maybe_plan_ahead()
            return finished
        old, old_tok = self._inflight, self._inflight_tok
        if not plan.empty:
            if obs is not None:
                _HostEngine.get().notify("start", "serving_step")
            try:
                tdev = self._dispatch(plan)
            finally:
                if obs is not None:
                    _HostEngine.get().notify("stop", "serving_step")
            self._inflight = plan
            self._inflight_tok = tdev
        else:
            # nothing to dispatch (every live request rides the
            # in-flight step) — just drain
            self._inflight = None
            self._inflight_tok = None
        finished = self._drain(old, old_tok, t0) if old is not None \
            else []
        self._maybe_plan_ahead()
        return finished

    def _take_plan(self):
        """Fetch the next plan: the planner's (if one was signalled —
        the ``_plan_ready`` wait is the happens-before edge for the
        unlocked handoff), else build inline under the lock (cold
        start / post-fence).  None means the engine is idle."""
        if self._plan_pending:
            self._plan_ready.wait()
            self._plan_ready.clear()
            self._plan_pending = False
            plan = self._plan
            self._plan = None
            return plan
        with self._mu:
            if self._inflight is None and not self._queue \
                    and all(r is None for r in self._slots):
                return None
            return self._build_plan(overlap=True)

    def _drain(self, plan, tok, t0):
        """Block on a dispatched step's sampled tokens and commit it.
        Under overlap this runs AFTER the next step was dispatched —
        the readback waits out step N's tail while N+1 executes."""
        # mxlint: allow(host-sync) -- intentional: the ONE device
        # sync per step — under overlap one step BEHIND dispatch (the
        # latency-hiding point); the host branches on step N's tokens
        # (stop conditions, commits) while step N+1 executes
        next_tok = np.asarray(tok)
        now = time.perf_counter()
        with self._mu:
            return self._commit(plan, next_tok, now, t0)

    def _maybe_plan_ahead(self):
        """Signal the planner to build the next plan while the
        just-dispatched step executes.  The pending flag and the go/
        ready Events sequence the handoff; the work check itself
        takes the lock (queue/slots are shared)."""
        with self._mu:
            work = bool(self._queue) or self._inflight is not None \
                or any(r is not None for r in self._slots)
        if work:
            self._plan_pending = True
            self._plan_go.set()

    def _ensure_planner(self):
        """Lazily spawn (or respawn after close()) the planner
        thread.  A fresh control dict per spawn keeps a stale
        finalizer from stopping the new thread."""
        if self._planner is not None and self._planner.is_alive():
            return
        ctl = {"stop": False}
        self._planner_ctl = ctl
        self._plan_go.clear()
        self._plan_ready.clear()
        self._plan_pending = False
        self._plan = None
        t = threading.Thread(
            target=_planner_main,
            args=(weakref.ref(self), ctl, self._plan_go,
                  self._plan_ready),
            daemon=True, name="serving-engine-planner")
        self._finalizer = weakref.finalize(self, _stop_planner, ctl,
                                           self._plan_go)
        self._planner = t
        t.start()

    def close(self):
        """Stop the planner thread (idempotent; serial engines no-op).
        Garbage collection alone also stops it via the finalizer, but
        an explicit close joins the thread out."""
        ctl = self._planner_ctl
        t = self._planner
        self._planner = None
        self._planner_ctl = None
        if ctl is not None:
            ctl["stop"] = True
            self._plan_go.set()
        if t is not None and t.is_alive():
            t.join(timeout=5)

    # mxlint: requires(ServingEngine._mu)
    def _build_plan(self, overlap=False):
        """Phases A+B of the engine step — admission, page
        allocation, speculation planning, and the fixed-shape row
        batch — built into the next rotated buffer set and recorded
        as a ``_Plan``.  ``overlap=True`` additionally plans CARRIED
        decode rows for the in-flight step's samplers: their input
        token is the in-flight step's device-resident argmax
        (``tok_src``), their position the in-flight sampling position
        + 1 — the pipelined dispatch never waits for the readback.
        Everything the later commit needs is recorded here at build
        time (the planner may build k+1 before k's commit runs)."""
        t_b0 = time.perf_counter()
        hidden = overlap and self._inflight is not None
        plan = _Plan()
        plan.pipelined = bool(overlap)
        inflight = self._inflight if overlap else None
        if overlap and self.spec_K > 0 and (
                (inflight is not None and inflight.samplers)
                or any(r is not None and r.pending is not None
                       for r in self._slots)):
            # speculation fence: the drafters read req.generated,
            # which for any in-flight sampler is one token behind the
            # device — don't build, let the caller drain and run one
            # serial step.  Pure-prefill phases (no samplers, no
            # pending) still pipeline under spec_K > 0.
            plan.fenced = True
            self.stats["overlap_fences"] += 1
            return plan
        self._admit()

        # ---- phase A: secure pages.  _ensure_page may PREEMPT the
        # youngest running request, so all allocation happens before
        # any row is built — a victim preempted here simply has no
        # rows this step (build skips slot-less requests); allocating
        # mid-build could free pages a built row already targets.
        carried = {}                   # rid -> device-carried position
        if inflight is not None:
            for req in inflight.samplers:
                if req.slot is None or req.state != "running":
                    continue           # preempted/cancelled mid-flight
                if len(req.generated) + 1 >= req.max_new_tokens:
                    # the in-flight token predictably finishes this
                    # request — its slot idles one step and retires
                    # at the drain (never decode past the budget)
                    continue
                pos = inflight.decode_pos[req.rid] + 1
                self._ensure_page(req, pos)
                carried[req.rid] = pos
        inflight_rids = set() if inflight is None else \
            {req.rid for req in inflight.samplers}
        for req in list(self._slots):
            if req is not None and req.pending is not None \
                    and req.rid not in inflight_rids:
                self._ensure_page(req, req.n_cached)
        # speculation planning (drafting + draft-depth pages) is part
        # of phase A for the same reason (pipelined builds reach here
        # only with spec_K == 0 — the fence above — so this is {})
        spec_plan = self._plan_speculation()
        plan.spec_plan = spec_plan
        budget = self.prefill_chunk
        pre = {}                           # rid -> prefill rows planned
        for req in list(self._slots):
            if req is None or req.pending is not None or budget <= 0:
                continue
            n = min(budget, req.resume_input.size - req.n_prefilled)
            # _admit allocated ceil((input+1)/page_size) pages, so
            # every prefill position is already covered — only the
            # decode-row loop and _plan_speculation above can allocate
            # (and thus preempt); keep BOTH before this point
            assert (req.n_prefilled + n - 1) // self.page_size \
                < len(req.pages)
            pre[req.rid] = n
            budget -= n

        # ---- phase B: build the fixed-shape row batch into the next
        # rotated buffer set (satellite: persistent buffers — the set
        # the in-flight step was staged from is never touched) ----
        obs = self._obs
        tracing = obs is not None and profiler.is_recording()
        buf = self._bufs[self._buf_idx]
        self._buf_idx ^= 1
        buf.reset(self.num_slots)
        np.copyto(buf.bt, self._bt)        # canonical, patched at
        plan.buf = buf                     # alloc/free — no rebuild
        T, S = self.n_rows, self.num_slots
        tokens, row_slot = buf.tokens, buf.row_slot
        row_pos, row_live = buf.row_pos, buf.row_live
        slot_rows, tok_src = buf.slot_rows, buf.tok_src
        samplers = plan.samplers
        r = 0
        # carried decode rows (overlap only): input = the in-flight
        # step's argmax for this slot, read on device via tok_src
        if inflight is not None:
            for req in inflight.samplers:
                if req.rid not in carried or req.slot is None \
                        or req.state != "running":
                    continue
                pos = carried[req.rid]
                row_slot[r] = req.slot
                row_pos[r] = pos
                row_live[r] = True
                tok_src[r] = req.slot
                slot_rows[req.slot, 0] = r
                samplers.append(req)
                plan.decode_pos[req.rid] = pos
                plan.was_decode[req.rid] = True
                plan.carried += 1
                self.stats["decode_rows"] += 1
                plan.n_dec_rows += 1
                if tracing:
                    plan.decode_rids.append(req.rid)
                r += 1
        for req in list(self._slots):      # decode (+ draft) rows
            if req is None or req.pending is None \
                    or req.rid in inflight_rids:
                continue
            tokens[r] = req.pending
            row_slot[r] = req.slot
            row_pos[r] = req.n_cached
            row_live[r] = True
            slot_rows[req.slot, 0] = r
            samplers.append(req)
            plan.decode_pos[req.rid] = req.n_cached
            plan.was_decode[req.rid] = True
            self.stats["decode_rows"] += 1
            plan.n_dec_rows += 1
            if tracing:
                plan.decode_rids.append(req.rid)
            r += 1
            # draft rows: positions n_cached+1 .. n_cached+K_eff, one
            # verify argmax read back per row.  Their k/v lands in the
            # cache like any row's; rejected tails are overwritten at
            # the committed position before any mask exposes them
            # (pointer-only rollback, the _decode_block argument).
            for i, d in enumerate(spec_plan.get(req.rid, ())):
                tokens[r] = d
                row_slot[r] = req.slot
                row_pos[r] = req.n_cached + 1 + i
                row_live[r] = True
                slot_rows[req.slot, 1 + i] = r
                r += 1
        for req in list(self._slots):      # chunked prefill rows
            if req is None or req.pending is not None \
                    or req.rid in inflight_rids:
                continue
            inp = req.resume_input
            p0 = req.n_prefilled
            sampled = False
            for _ in range(pre.get(req.rid, 0)):
                p = req.n_prefilled
                tokens[r] = inp[p]
                row_slot[r] = req.slot
                row_pos[r] = p
                row_live[r] = True
                req.n_prefilled += 1
                self.stats["prefill_rows"] += 1
                if req.n_prefilled == inp.size:
                    slot_rows[req.slot, 0] = r
                    samplers.append(req)
                    plan.decode_pos[req.rid] = p
                    plan.was_decode[req.rid] = False
                    sampled = True
                r += 1
            if not sampled:
                # still mid-prefill: the commit advances n_cached to
                # the rows THIS plan wrote (recorded now — by commit
                # time the planner may have pushed n_prefilled on)
                plan.prefill_mid.append((req, req.n_prefilled))
            if tracing and req.n_prefilled > p0:
                plan.prefill_spans.append((req.rid, p0,
                                           req.n_prefilled))

        plan.n_rows_used = r
        plan.n_pre_rows = sum(pre.values())
        plan.empty = r == 0
        if r or not overlap:
            # an empty pipelined plan is never dispatched — don't book
            # a phantom batch (the serial path dispatches dead batches
            # only when the idle check already found work)
            self.stats["dead_rows"] += T - r
            self.stats["peak_pages"] = max(self.stats["peak_pages"],
                                           self.cache.pages_in_use)
            self.stats["slot_occupancy_sum"] += \
                sum(r_ is not None for r_ in self._slots) / float(S)
        dt = time.perf_counter() - t_b0
        if hidden:
            # this build ran while a dispatched step executed on
            # device: its host time is off the critical path
            self.stats["host_hidden_ms"] += dt * 1e3
        return plan

    def _dispatch(self, plan):
        """Stage a plan's host buffers and launch the step program
        (asynchronous — the device array returns immediately).  No
        lock: the buffers are plan-owned and the pool handoff happens
        only on the engine thread."""
        import jax.numpy as jnp

        buf = plan.buf
        staged = (jnp.asarray(buf.tokens), jnp.asarray(buf.row_slot),
                  jnp.asarray(buf.row_pos), jnp.asarray(buf.row_live),
                  jnp.asarray(buf.bt), jnp.asarray(buf.slot_rows))
        if self.overlap:
            prev = self._inflight_tok
            if prev is None:
                if self._tok0 is None:
                    self._tok0 = jnp.zeros(
                        (self.num_slots, 1 + self.spec_K), jnp.int32)
                prev = self._tok0
            next_tok, self.cache.pools = self._step_fn(
                self.params, self.cache.pools, *staged,
                prev, jnp.asarray(buf.tok_src))
        else:
            next_tok, self.cache.pools = self._step_fn(
                self.params, self.cache.pools, *staged)
        return next_tok

    # mxlint: requires(ServingEngine._mu)
    def _commit(self, plan, next_tok, now, t_step0):
        """Phase C: consume a completed step's sampled tokens — stop
        conditions, retirement, metrics.  Under overlap this runs one
        step after the plan was built (and after the NEXT plan was
        already built), so it reads no live planner state: every
        position it needs was recorded on the plan at build time."""
        obs = self._obs
        tracing = obs is not None and profiler.is_recording()
        self.stats["steps"] += 1
        if plan.pipelined:
            self.stats["overlap_steps"] += 1
        finished = []
        spec_spans = []                    # trace: (rid, drafted, accepted)
        for req in plan.samplers:
            if req.slot is None or req.state != "running":
                continue                   # preempted/cancelled
            was_decode = plan.was_decode[req.rid]
            # rows written this step are now cached (the recorded
            # sampling position, NOT live scheduler state)
            req.n_cached = plan.decode_pos[req.rid] + 1
            if self.prefix is not None:
                # donate completed prompt pages BEFORE a possible
                # same-step retire releases them
                self._insert_prefix(req)
            row = next_tok[req.slot]       # (1 + spec_K,) argmaxes
            drafts = plan.spec_plan.get(req.rid) if was_decode \
                else None
            if drafts is not None and drafts.size:
                # greedy verify: row[i] is the target's own argmax
                # after pending + drafts[:i]; accept the longest
                # matching draft prefix plus the target token at the
                # first mismatch — exactly generate_speculative's
                # greedy accept rule, per row instead of batch-min
                k_eff = drafts.size
                a = 0
                while a < k_eff and int(drafts[a]) == int(row[a]):
                    a += 1
                commit = [int(row[i]) for i in range(a + 1)]
                # accepted drafts are ALREADY in the cache at
                # n_cached..n_cached+a-1 (their rows wrote this step)
                req.n_cached += a
                self.stats["spec_drafted"] += k_eff
                self.stats["spec_accepted"] += a
                if obs is not None:
                    obs.spec_drafted.inc(k_eff)
                    obs.spec_accepted.inc(a)
                    obs.spec_rejected.inc(k_eff - a)
                if tracing:
                    spec_spans.append((req.rid, k_eff, a))
            else:
                commit = [int(row[0])]
            if obs is not None:
                if req.token_times:
                    obs.h_tbt.observe(
                        (now - req.token_times[-1]) * 1e3)
                elif not req.generated:
                    obs.h_ttft.observe((now - req.submit_t) * 1e3)
                    if tracing:
                        obs.trace.add_instant(
                            req.rid, "first_token", now,
                            args={"trace_id": req.trace_id}
                            if req.trace_id else None)
            done = False
            for tok in commit:
                req.generated.append(tok)
                req.token_times.append(now)
                req.pending = tok
                if obs is not None:
                    obs.tokens.inc()
                if (len(req.generated) >= req.max_new_tokens
                        or (req.eos_id is not None
                            and tok == req.eos_id)):
                    done = True
                    break
            if done:
                req.state = "done"
                if self.retire_cb is not None:
                    self.retire_cb(req)
                self._release(req)
                finished.append(req.rid)
                if obs is not None:
                    obs.finished.inc()
                    if tracing:
                        rargs = {"tokens": len(req.generated)}
                        if req.trace_id:
                            rargs["trace_id"] = req.trace_id
                        obs.trace.add_instant(req.rid, "retire", now,
                                              args=rargs)
        # slots that fed prefill rows but did not finish their input
        # this step just advance n_cached — to the position recorded
        # at build time (by now the planner may have pushed
        # n_prefilled past what THIS step's rows actually wrote)
        for req, p1 in plan.prefill_mid:
            if req.slot is None or req.state != "running":
                continue
            req.n_cached = max(req.n_cached, p1)
            if self.prefix is not None:
                self._insert_prefix(req)

        if obs is not None:
            dead = self.n_rows - plan.n_rows_used
            obs.steps.inc()
            obs.h_step.observe((now - t_step0) * 1e3)
            # row-mix counters increment by THIS step's amounts (never
            # assigned wholesale: engines sharing a caller-supplied
            # registry must aggregate, not clobber); gauges carry the
            # step's prefill-vs-decode mix (plan rows were all fed —
            # the phase-A assert guarantees page coverage)
            obs.decode_rows.inc(plan.n_dec_rows)
            obs.prefill_rows.inc(plan.n_pre_rows)
            obs.dead_rows.inc(dead)
            obs.g_step_decode.set(plan.n_dec_rows)
            obs.g_step_prefill.set(plan.n_pre_rows)
            obs.g_step_dead.set(dead)
            obs.g_running.set(sum(r_ is not None
                                  for r_ in self._slots))
            obs.g_queued.set(len(self._queue))
            if self.stats["spec_drafted"]:
                obs.g_spec_accept_rate.set(
                    self.stats["spec_accepted"]
                    / self.stats["spec_drafted"])
            obs.sync_cache(self.cache)
            if self.prefix is not None:
                obs.sync_prefix(self.prefix)
            if self.tier is not None:
                obs.sync_tier(self.tier, self.stats["swap_outs"],
                              self.stats["swap_ins"])
            if tracing:
                for rid in plan.decode_rids:
                    obs.trace.add_span(rid, "decode", t_step0, now)
                for rid, k_eff, a in spec_spans:
                    obs.trace.add_span(rid, "spec_verify", t_step0,
                                       now, args={"drafted": k_eff,
                                                  "accepted": a})
                for rid, p0, p1 in plan.prefill_spans:
                    obs.trace.add_span(rid, "prefill[%d:%d)"
                                       % (p0, p1), t_step0, now,
                                       args={"rows": p1 - p0})
                obs.trace.flush()
        return finished

    def run(self):
        """Drain: step until every submitted request is done (or
        cancelled).  Returns {rid: (P + generated,) int32}."""
        while True:
            out = self.step()
            if out is False:
                break
        return {rid: req.output for rid, req in self.requests.items()
                if req.state == "done"}

    # --------------------------------------------------- accounting --
    @property
    def metrics_enabled(self):
        return self._obs is not None

    @property
    def registry(self):
        """The engine's ``obs.MetricsRegistry`` (None when metrics are
        disabled)."""
        return self._obs.registry if self._obs is not None else None

    def reset_metrics(self):
        """Zero this engine's telemetry in place (warmup exclusion in
        benches): registry values, the allocator's cumulative ints,
        AND the delta tracker that folds the latter into the former —
        resetting the first two but not the third would silently
        swallow the warmup's worth of post-reset allocations."""
        if self._obs is None:
            return
        self._obs.registry.reset_values()
        self.cache.reset_telemetry()
        self._obs._cache_seen = [0, 0, 0, 0]
        if self.prefix is not None:
            self.prefix.lookups_total = 0
            self.prefix.lookup_tokens_total = 0
            self.prefix.hit_tokens_total = 0
            self.prefix.pages_hit_total = 0
            self.prefix.pages_inserted_total = 0
            self.prefix.pages_evicted_total = 0
            self.prefix.cow_total = 0
            self.prefix.pages_spilled_total = 0
            self.prefix.pages_restored_total = 0
            self.prefix.warm_hits_total = 0
            self.prefix.warm_hit_tokens_total = 0
            self._obs._prefix_seen = [0, 0, 0, 0, 0, 0]
        if self.tier is not None:
            self.tier.reset_telemetry()
            with self._mu:
                self.stats["swap_outs"] = 0
                self.stats["swap_ins"] = 0
            self._obs._tier_seen = [0, 0, 0, 0]
            self._obs._swap_seen = [0, 0]
        self._obs._warm_seen = [0]

    def metrics(self):
        """JSON-able telemetry snapshot: this engine's counters/gauges,
        histogram summaries (count/sum/p50/p95/p99 ms), and — when the
        native runtime is loaded — the dependency engine's
        ``MXEngineStats``.  ``{"enabled": False}`` when metrics are
        off."""
        if self._obs is None:
            return {"enabled": False}
        snap = self._obs.registry.snapshot()
        snap["enabled"] = True
        try:
            from .. import native
            if native.available():
                snap["native_engine"] = native.engine_stats()
        except Exception:
            pass
        return snap

    @property
    def hbm_held(self):
        return self.cache.bytes_held

    @property
    def hbm_pool(self):
        return self.cache.bytes_pool

    @property
    def hbm_held_per_device(self):
        """Per-device share of the allocated page bytes (= hbm_held /
        tp — pages shard the heads axis, so the split is exact)."""
        return self.cache.bytes_held_per_device

    @property
    def hbm_pool_per_device(self):
        return self.cache.bytes_pool_per_device
