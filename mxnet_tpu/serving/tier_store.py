"""Host-DRAM KV page tier: spill instead of drop, install instead of
recompute (round 18, ROADMAP item 4).

At millions-of-users scale the useful prefix set dwarfs device memory.
Before this round two things happened when HBM ran short: a
refcount-0 prefix chain under pool pressure was simply DROPPED
(``prefix_cache.evict`` → ``PagedKVCache.free``) and re-paid as a full
prefill on the next hit, and a preemption victim's pages were
discarded and re-paid as a full recompute at resume.  Both costs are
O(prefill); the bytes they recompute already existed, byte-exact, in
the pool the moment before.

:class:`HostTierStore` is the second tier under the pool: a
byte-budgeted LRU of **exact pool-layout page content** on the host —
the same ``{"kv", ("s")}``-per-layer arrays
``PagedKVCache.export_pages`` emits and ``install_pages`` consumes,
which round 15 already made the cluster's unit of transfer.  Spilling
a page is one bucketed device gather + a host copy; restoring it is
one bucketed donated scatter — O(transfer) against O(prefill), the
whole point.  Because the wire layout IS the pool layout, a spilled
chain also stays peer-fetchable: the disaggregated fetch server
answers sibling FETCH requests for spilled chains straight from this
store, no device round trip at all (``cluster._serve_fetches``).

Two entry families share the budget:

* ``("prefix", chain_key)`` — one refcount-0 prefix-cache page,
  spilled by ``PrefixCache._drop`` under pool pressure and restored by
  ``PrefixCache.match`` as a **warm hit** (the new outcome between
  hot-hit and miss).  The trie-structure bookkeeping (which spilled
  keys are reachable) stays in ``PrefixCache``; this store only holds
  bytes.
* ``("swap", rid)`` — a preemption victim's written pages
  (positions ``[0, n_cached)``) plus the tiny resume meta
  (``n_cached``, ``pending``), swapped out by
  ``ServingEngine._preempt_victim`` and swapped back in by ``_admit``
  as an **install-exact** resume.  A swap entry LRU-evicted before the
  victim resumes merely falls back to the round-7 recompute-exact
  path — exactness never depends on the tier.

Eviction is strict LRU over both families.  ``evict_cb(key)`` fires
AFTER the entry has left the store (reentrancy-safe: the callback may
``pop`` other keys — ``PrefixCache`` drops a spilled chain's
now-unreachable descendants this way).  Everything here is plain host
state on the owning engine's scheduling thread, same single-threaded
contract as ``PrefixCache``; the only device work is in the caller's
export/install calls, never in this module.

Accounting is the allocator idiom: plain ints bumped on the host path
(``spilled_pages_total`` …), delta-folded into the engine registry by
``_EngineObs.sync_tier`` as the round-8 surface's
``serving_tier_{spills,installs,bytes}_total`` counters and the
tier-occupancy gauges.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

import numpy as np

__all__ = ["HostTierStore", "content_nbytes"]


def content_nbytes(content) -> int:
    """Host bytes of an ``export_pages``-layout content block (the
    per-layer list of ``{"kv": array, ("s": array)}`` dicts)."""
    return sum(np.asarray(a).nbytes
               for layer in content for a in layer.values())


class _TierEntry:
    __slots__ = ("content", "n_pages", "nbytes", "meta")

    def __init__(self, content, n_pages, nbytes, meta):
        self.content = content            # export_pages layout (host)
        self.n_pages = n_pages
        self.nbytes = nbytes
        self.meta: Optional[dict] = meta  # swap entries: resume state


class HostTierStore:
    """Byte-budgeted LRU of exact pool-layout page bytes in host DRAM.

    ``put`` refuses (returns False) rather than evicting the world
    when a single entry exceeds the whole budget; the caller then
    falls back to the pre-tier behavior (drop / recompute).  ``get``
    and ``peek`` touch LRU recency; ``pop`` removes.  All host-side,
    single-threaded with the owning engine.
    """

    def __init__(self, budget_bytes: int,
                 evict_cb: Optional[Callable[[Any], None]] = None):
        if budget_bytes < 1:
            raise ValueError("HostTierStore: budget_bytes must be "
                             ">= 1 (use tier_bytes=None to disable "
                             "the tier)")
        self.budget_bytes = int(budget_bytes)
        self.evict_cb = evict_cb
        self._entries: "OrderedDict[Any, _TierEntry]" = OrderedDict()
        # occupancy is maintained INCREMENTALLY at the five mutation
        # sites: the engine's per-step gauge sync reads these on the
        # hot scheduling thread, where an O(entries) scan would price
        # every step by the tier's size
        self.bytes_held = 0
        self.pages_held = 0
        # host ints, delta-folded into the obs registry (sync_tier)
        self.spilled_pages_total = 0      # pages put (device -> host)
        self.installed_pages_total = 0    # pages popped for install
        self.bytes_moved_total = 0        # bytes through, both ways
        self.evicted_pages_total = 0      # pages LRU-dropped
        self.evictions_total = 0          # entries LRU-dropped

    # ------------------------------------------------------ queries --
    def __len__(self):
        return len(self._entries)

    def __contains__(self, key):
        return key in self._entries

    # ------------------------------------------------------- put/get --
    def put(self, key, content, n_pages: int,
            meta: Optional[dict] = None) -> bool:
        """Admit one entry, LRU-evicting until it fits.  Returns False
        (nothing stored, nothing evicted) when the entry alone
        overflows the budget — the caller keeps the pre-tier drop/
        recompute behavior.  Re-putting a live key replaces it."""
        nbytes = content_nbytes(content)
        if nbytes > self.budget_bytes:
            return False
        old = self._entries.pop(key, None)
        if old is not None:
            self.bytes_held -= old.nbytes
            self.pages_held -= old.n_pages
        while self.bytes_held + nbytes > self.budget_bytes \
                and self._entries:
            self._evict_lru()
        self._entries[key] = _TierEntry(content, int(n_pages), nbytes,
                                        meta)
        self.bytes_held += nbytes
        self.pages_held += int(n_pages)
        self.spilled_pages_total += int(n_pages)
        self.bytes_moved_total += nbytes
        return True

    def peek(self, key) -> Optional[_TierEntry]:
        """Entry without install accounting; touches LRU recency (a
        peeked entry is about to be used — ``_admit`` peeks before it
        can afford the pool pages, and the pressure spills that alloc
        triggers must not evict the entry being resumed)."""
        e = self._entries.get(key)
        if e is not None:
            self._entries.move_to_end(key)
        return e

    def get(self, key) -> Optional[_TierEntry]:
        """Entry for a host-side read (peer fetch service): touches
        recency and counts the bytes as moved, entry stays stored."""
        e = self.peek(key)
        if e is not None:
            self.bytes_moved_total += e.nbytes
        return e

    def pop(self, key) -> Optional[_TierEntry]:
        """Remove and return an entry for install (host -> device);
        None if missing (evicted meanwhile — callers degrade to the
        pre-tier path)."""
        e = self._entries.pop(key, None)
        if e is None:
            return None
        self.bytes_held -= e.nbytes
        self.pages_held -= e.n_pages
        self.installed_pages_total += e.n_pages
        self.bytes_moved_total += e.nbytes
        return e

    def drop(self, key) -> bool:
        """Remove without install accounting (the content is being
        discarded, not moved: a cancelled swap, an unreachable spilled
        descendant)."""
        e = self._entries.pop(key, None)
        if e is None:
            return False
        self.bytes_held -= e.nbytes
        self.pages_held -= e.n_pages
        return True

    # ----------------------------------------------------- eviction --
    def _evict_lru(self):
        key, e = self._entries.popitem(last=False)
        self.bytes_held -= e.nbytes
        self.pages_held -= e.n_pages
        self.evicted_pages_total += e.n_pages
        self.evictions_total += 1
        if self.evict_cb is not None:
            # AFTER removal so the callback may pop()/drop() other
            # keys (a spilled chain's descendants) reentrantly
            self.evict_cb(key)

    def clear(self):
        """Drop everything without eviction callbacks (engine
        teardown; the trie bookkeeping is being dropped wholesale by
        the same caller)."""
        self._entries.clear()
        self.bytes_held = 0
        self.pages_held = 0

    def reset_telemetry(self):
        """Zero the movement counters (warmup exclusion in benches;
        held entries and occupancy are untouched)."""
        self.spilled_pages_total = 0
        self.installed_pages_total = 0
        self.bytes_moved_total = 0
        self.evicted_pages_total = 0
        self.evictions_total = 0

    def stats(self) -> Dict[str, int]:
        return {"entries": len(self._entries),
                "pages_held": self.pages_held,
                "bytes_held": self.bytes_held,
                "budget_bytes": self.budget_bytes,
                "spilled_pages_total": self.spilled_pages_total,
                "installed_pages_total": self.installed_pages_total,
                "bytes_moved_total": self.bytes_moved_total,
                "evicted_pages_total": self.evicted_pages_total,
                "evictions_total": self.evictions_total}
