"""Deterministic fault injection for the serving clusters (round 16).

"Replica death during the burst" was, until this round, a hand-run
test: somebody called ``kill_worker`` at roughly the right moment.
This module makes chaos a REPRODUCIBLE artifact, the same way the
round-12 interleaving explorer made races one: a chaos schedule is
fully identified by ``(trace, seed)`` — the same seed protocol as
``tools/analysis/interleave.py`` (``docs/static_analysis.md``) — and
events fire at TRACE-RELATIVE times from the replay loop's own
clock, so the scenario in ``MULTICHIP_r08.json`` replays from its
checked-in seed alone.

Event kinds, per cluster flavor:

====================  ===============================  =====================
kind                  ServingCluster (threads)         DisaggServingCluster
====================  ===============================  =====================
``kill``              injected raise in the victim     real ``SIGKILL`` of
                      replica's next ``step()`` (the   the worker process
                      worker-raise failover path)
``stall``             injected sleep past the          ``SIGSTOP`` (process
                      watchdog (the monitor-stall      alive, silent — the
                      failover path)                   watchdog's case)
``reset``             —                                router-side close of
                                                       the control
                                                       connection
``cancel``            ``cluster.cancel(rid)`` on a seeded live request —
                      the client-disconnect fault (round 20; both flavors)
====================  ===============================  =====================

The driver is POLLED from the replay loop (``poll(now_rel)``), not
threaded: the application point is a deterministic place in the
harness's own sequence, and the only nondeterminism left is the
victim draw — taken from the driver's seeded ``random.Random`` over
the eligible victims sorted by name/index.

A ``stall``-stopped disagg worker process cannot run signal handlers;
``close()`` SIGKILLs any still-stopped pid so a chaos run never
leaks a T-state process.
"""
from __future__ import annotations

import random
import time
from typing import List, Optional

__all__ = ["ChaosEvent", "ChaosDriver", "chaos_schedule"]


class ChaosEvent:
    """One scheduled fault.  ``target`` is None (seeded draw at fire
    time), a replica index (in-process), or a worker name / role
    prefix (disagg)."""
    __slots__ = ("t", "kind", "target")

    def __init__(self, t, kind, target=None):
        if kind not in ("kill", "stall", "reset", "cancel"):
            raise ValueError("ChaosEvent: kind must be kill/stall/"
                             "reset/cancel, got %r" % (kind,))
        self.t = float(t)
        self.kind = kind
        self.target = target

    def __repr__(self):
        return "ChaosEvent(t=%.3f, %s, target=%r)" % (
            self.t, self.kind, self.target)


def chaos_schedule(seed: int, duration_s: float, n_events: int = 1,
                   kinds=("kill",), window=(0.25, 0.75)
                   ) -> List[ChaosEvent]:
    """Seeded event schedule: ``n_events`` faults at times drawn
    uniformly inside ``window`` (fractions of ``duration_s``), kinds
    cycling through ``kinds``.  Same seed ⇒ same schedule."""
    rng = random.Random(seed)
    lo, hi = window
    times = sorted(rng.uniform(lo * duration_s, hi * duration_s)
                   for _ in range(n_events))
    return [ChaosEvent(t, kinds[i % len(kinds)])
            for i, t in enumerate(times)]


class ChaosDriver:
    """Apply a chaos schedule to a live cluster as replay time
    passes.  ``poll(now_rel)`` fires every not-yet-applied event whose
    time has come; ``applied`` is the audit log the benchmark writes
    into its result row."""

    def __init__(self, cluster, events, seed: int = 0):
        self.cluster = cluster
        self.events = sorted(events, key=lambda e: e.t)
        self.rng = random.Random(seed)
        self._next = 0
        self.applied: List[dict] = []
        self._stopped_pids: List[int] = []
        # flavor: the disagg cluster is the one with worker PROCESSES
        self._disagg = hasattr(cluster, "kill_worker")

    # ------------------------------------------------------- firing --
    def poll(self, now_rel: float):
        """Fire due events.  Returns the number fired."""
        fired = 0
        while self._next < len(self.events) \
                and self.events[self._next].t <= now_rel:
            ev = self.events[self._next]
            self._next += 1
            victim = self._apply(ev)
            self.applied.append(
                {"t": ev.t, "kind": ev.kind, "victim": victim})
            fired += 1
        return fired

    def done(self):
        return self._next >= len(self.events)

    # ------------------------------------------------------ victims --
    def _apply(self, ev):
        if ev.kind == "cancel":
            return self._apply_cancel(ev)
        if self._disagg:
            return self._apply_disagg(ev)
        return self._apply_inproc(ev)

    def _apply_cancel(self, ev):
        """Round 20: the client-disconnect fault, cluster-flavor
        agnostic — ``cancel(rid)`` is public on both.  The victim is
        a seeded draw over the live (queued/running) requests sorted
        by rid; ``target`` may pin a specific rid.  The request's
        pages/slot free immediately (the front door's disconnect
        path), and the counted outcome rides
        ``cluster_cancelled_total``."""
        with self.cluster._lock:
            live = sorted(rid for rid, cr
                          in self.cluster.requests.items()
                          if cr.state in ("queued", "running"))
        if ev.target is not None:
            live = [rid for rid in live if rid == ev.target]
        if not live:
            return None
        rid = self.rng.choice(live)
        try:
            took = self.cluster.cancel(rid)
        except KeyError:
            took = False          # purged between snapshot and cancel
        # a False cancel means the victim reached a terminal state in
        # the snapshot→cancel window — report no victim, so the
        # bench's cancel-reconciliation arithmetic stays exact
        return rid if took else None

    def _pick_replica(self, ev):
        reps = [r for r in self.cluster.replicas
                if r.alive and not r.dead and not r.draining
                and r.engine is not None]
        if ev.target is not None:
            reps = [r for r in reps if r.idx == ev.target]
        if not reps:
            return None
        return self.rng.choice(sorted(reps, key=lambda r: r.idx))

    def _pick_worker(self, ev):
        ws = [w for w in self.cluster.workers.values()
              if w.alive and not w.draining]
        if isinstance(ev.target, str):
            exact = [w for w in ws if w.name == ev.target]
            ws = exact or [w for w in ws
                           if w.role == ev.target]
        if not ws:
            return None
        return self.rng.choice(sorted(ws, key=lambda w: w.name))

    # ----------------------------------------------- in-process arm --
    def _apply_inproc(self, ev):
        if ev.kind == "reset":
            return None                   # no connections to reset
        rep = self._pick_replica(ev)
        if rep is None:
            return None
        eng = rep.engine
        orig = eng.step
        armed = [True]
        if ev.kind == "kill":
            def chaos_step():
                if armed[0]:
                    armed[0] = False
                    raise RuntimeError(
                        "chaos: injected death of replica %d"
                        % rep.idx)
                return orig()
        else:                             # stall past the watchdog
            stall_s = self.cluster.watchdog_s * 1.5

            def chaos_step():
                if armed[0]:
                    armed[0] = False
                    time.sleep(stall_s)
                return orig()
        eng.step = chaos_step
        return rep.idx

    # ---------------------------------------------------- disagg arm --
    def _apply_disagg(self, ev):
        import signal
        wh = self._pick_worker(ev)
        if wh is None:
            return None
        if ev.kind == "kill":
            self.cluster.kill_worker(wh.name)
        elif ev.kind == "stall":
            if wh.proc is None:
                return None
            self._stopped_pids.append(wh.proc.pid)
            self.cluster.kill_worker(wh.name, sig=signal.SIGSTOP)
        else:                             # reset: drop the control conn
            try:
                wh.conn.close()
            except Exception:
                pass
        return wh.name

    def close(self):
        """Reap SIGSTOPped processes (they cannot handle SIGTERM)."""
        import signal
        import os as _os
        for pid in self._stopped_pids:
            try:
                _os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
        self._stopped_pids = []
