"""Draft-token proposers for in-engine speculative decode (round 11).

One implementation serves every accept-rate number in the repo: the
``ServingEngine`` drafts with :func:`ngram_draft` (host-side numpy —
the engine's scheduler is host Python, so drafting joins the per-step
scheduling work it already does; the compare is vectorized because
this runs once per decode row per step), and
``benchmark/spec_decode_probe.py``'s engine section measures accept
rates through the engine itself, so probe and engine rates cannot
drift apart.  ``models/gpt.py _draft_ngram`` is the in-XLA twin used
by the stand-alone ``generate_speculative`` loop (drafting there must
live inside the compiled program); semantic parity between the two is
pinned by ``tests/test_paged_attention.py::test_ngram_draft_parity``.

The drafter contract the engine accepts (``spec_drafter=``):

    drafter(tokens: np.ndarray (n,), K: int) -> np.ndarray (K,)

``tokens`` is the row's committed sequence (prompt + generated, the
last element being the not-yet-cached pending token); the return is K
proposals for the positions after it.  Proposal quality only affects
the accept rate — the batched verify forward gates correctness, so an
adversarial drafter degrades to plain decode (pinned by the
forced-rejection test in ``tests/test_serving.py``).

Self-drafting (a small model proposing tokens) stays a
``generate_speculative`` feature for now: inside the engine it would
cost K sequential extra program dispatches per step, which is the
c_S-amortization the in-engine design exists to avoid.  ``ngram`` is
the zero-cost drafter whose economics the round-6 probe showed flip
positive once verify is batched across rows.
"""
from __future__ import annotations

import numpy as np

__all__ = ["ngram_draft"]


def ngram_draft(tokens, K, g=2):
    """Prompt-lookup (n-gram) draft: propose the K tokens that followed
    the most recent earlier occurrence of the final ``g`` committed
    tokens; fall back to repeating the last token for the positions no
    match covers (or when no match exists / the row is shorter than
    ``g``).  Semantically identical to ``models/gpt.py _draft_ngram``
    restricted to one row's committed region (parity-pinned)."""
    tokens = np.asarray(tokens, np.int32).reshape(-1)
    n = tokens.size
    if n < 1:
        raise ValueError("ngram_draft: empty token row")
    if K < 1:
        raise ValueError("ngram_draft: K must be >= 1")
    out = np.full(K, tokens[n - 1], np.int32)
    if n <= g:
        return out
    key = tokens[n - g:]
    # most recent usable match: the continuation must start inside the
    # committed region (s + g < n), same bound as _draft_ngram.  One
    # vectorized sliding-window compare — this runs once per decode
    # row per engine step (a jaxlint hot region), so no Python loop
    # over offsets: stride-tricks windows cost no copy.
    win = np.lib.stride_tricks.sliding_window_view(tokens[:n - 1], g)
    hits = np.nonzero((win == key).all(axis=1))[0]
    if hits.size:
        s = int(hits[-1])
        idx = s + g + np.arange(K)
        ok = idx < n
        out[ok] = tokens[idx[ok]]
    return out
