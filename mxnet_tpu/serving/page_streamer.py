"""KV-page streaming between disaggregated serving processes.

The prefill replica owns a :class:`PageStreamer`: after every engine
step it exports the pages a handoff request has **newly completed**
(``n_cached`` crossed another page boundary) and frames them for the
decode replica — so page transfer is pipelined with prefill chunks and
decode-side installation overlaps the tail of prefill instead of
starting after it.  The decode replica owns a :class:`PageReceiver`:
arriving page content is installed into the local ``PagedKVCache`` as
pool space allows, and a request is admitted the moment its final
page and handoff metadata are in.

Hold representation (round 22): a frame the pool cannot absorb yet is
held as its ``(n, bufs)`` tuple UNCHANGED — whatever buffer flavor
the transport delivered (socket bytearrays, or zero-copy
:class:`~.transport.PutBufs` views into a shared put segment).  There
is deliberately NO downgrade copy into fresh host bytes: a put-path
frame stays mapped until installed, and every exit edge (install,
abort) releases it via its ``release`` hook so segment lifetime is
bounded by staging lifetime, not by GC.

Wire layout (the ``PAGES`` frame): raw buffers in pool order — for
each layer, the ``kv`` page block then (under int8-KV) the ``s``
scale block, shapes derived from the receiver's own pool config (the
page is self-describing given the engine config both sides were built
from; byte lengths are cross-checked on install).  Content bytes are
EXACT pool bytes: under f32 the handed-off decode is bit-identical to
a single-engine run, under int8-KV the quantized pages + f32 scales
transfer losslessly.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["PageStreamer", "PageReceiver", "pages_to_bufs",
           "bufs_to_pages", "page_wire_bytes", "merge_page_content"]


def _page_shapes(cfg, page_size, kv_int8):
    H = cfg.n_heads
    dh = cfg.d_model // H
    out = [("kv", (page_size, H, 2 * dh),
            "int8" if kv_int8 else str(cfg.dtype))]
    if kv_int8:
        # round-22 tile-shaped scale planes (paged_kv.py): the wire
        # layout IS the pool layout, so the retile travels as-is
        out.append(("s", (2, page_size, H), "float32"))
    return out


def _release(bufs):
    """Release transport-owned buffers (put segments carry a
    ``release`` hook; plain socket bytearrays have none)."""
    rel = getattr(bufs, "release", None)
    if rel is not None:
        rel()


def _raw(a) -> memoryview:
    """Zero-copy byte view of an array — via a uint8 reinterpret for
    extension dtypes (bfloat16) whose buffers numpy refuses to
    export directly."""
    a = np.ascontiguousarray(a)
    try:
        return a.data
    except ValueError:
        return a.view(np.uint8).data


def pages_to_bufs(content) -> List:
    """``PagedKVCache.export_pages`` output → ordered raw buffers."""
    bufs = []
    for layer in content:
        bufs.append(_raw(layer["kv"]))
        if "s" in layer:
            bufs.append(_raw(layer["s"]))
    return bufs


def bufs_to_pages(cache, n: int, bufs: List):
    """Ordered raw buffers → the ``install_pages`` content layout for
    ``cache`` (shape/dtype derived from the cache's own pool config;
    lengths are validated there)."""
    from .transport import _np_dtype

    shapes = _page_shapes(cache.cfg, cache.page_size, cache.kv_int8)
    want = cache.cfg.n_layers * len(shapes)
    if len(bufs) != want:
        raise ValueError("page frame: %d buffers, expected %d "
                         "(n_layers x pool keys)" % (len(bufs), want))
    out, i = [], 0
    for _ in range(cache.cfg.n_layers):
        layer = {}
        for key, shape, dtype in shapes:
            # frombuffer on the received bytearray directly — bytes()
            # here would re-copy every page payload on the hot
            # install path (recv_into already landed them zero-copy)
            layer[key] = np.frombuffer(
                bufs[i], _np_dtype(dtype)).reshape((n,) + shape)
            i += 1
        out.append(layer)
    return out


def page_wire_bytes(cache, n: int) -> int:
    """Bytes ``n`` pages cost on the wire (== their pool bytes)."""
    return n * cache.bytes_per_page


def merge_page_content(parts: List) -> List:
    """Concatenate several ``export_pages``-layout content blocks
    along the page axis into one block (round 18: a fetch reply — or
    a warm-hit restore — may mix device-exported hot pages with
    host-tier pages; the consumer sees one contiguous page run
    either way)."""
    if len(parts) == 1:
        return parts[0]
    return [{k: np.concatenate([p[li][k] for p in parts])
             for k in parts[0][li]}
            for li in range(len(parts[0]))]


class PageStreamer:
    """Prefill-side per-request streaming state: which pages have
    already been sent, and which are newly ready after a step."""

    def __init__(self, engine):
        self.engine = engine
        self._sent: Dict[int, int] = {}          # rid -> pages sent
        self.pages_streamed_total = 0
        self.bytes_streamed_total = 0

    def pending(self, rid: int) -> int:
        return self._sent.get(rid, 0)

    def pump(self, rid: int, n_cached: int, pages: List[int],
             final: bool = False) -> Optional[Tuple[int, int, List]]:
        """Export the request's newly-completed pages (``pages`` /
        ``n_cached`` are passed in rather than read off the live
        request: at handoff time the engine has already retired the
        request and the ids come from the retire-time snapshot).
        Returns ``(start_page, n_pages, bufs)`` or ``None`` when
        nothing new is ready.  ``final=True`` includes the trailing
        partial page (positions beyond ``n_cached`` in it are scratch
        the decode side never reads)."""
        ps = self.engine.page_size
        ready = (n_cached + ps - 1) // ps if final \
            else n_cached // ps
        ready = min(ready, len(pages))
        start = self._sent.get(rid, 0)
        if ready <= start:
            return None
        content = self.engine.cache.export_pages(pages[start:ready])
        self._sent[rid] = ready
        n = ready - start
        self.pages_streamed_total += n
        self.bytes_streamed_total += page_wire_bytes(self.engine.cache,
                                                     n)
        return start, n, pages_to_bufs(content)

    def drop(self, rid: int):
        self._sent.pop(rid, None)


class _Staged:
    __slots__ = ("installed", "held", "next_idx", "total", "meta")

    def __init__(self):
        self.installed: List[int] = []    # local page ids, in order
        self.held: List = []              # content awaiting pool space
        self.next_idx = 0                 # next page index expected
        self.total: Optional[int] = None  # set by the handoff frame
        self.meta: Optional[dict] = None  # handoff metadata


class PageReceiver:
    """Decode-side staging: install arriving pages eagerly (pipelined
    with the prefill tail), hold content host-side when the pool is
    dry, admit when complete."""

    def __init__(self, engine):
        self.engine = engine
        self._staged: Dict[int, _Staged] = {}
        self.pages_installed_total = 0

    def on_pages(self, rid: int, start: int, n: int, bufs: List):
        """A ``PAGES`` frame arrived: stage (and, pool permitting,
        install) its content.  Out-of-order frames are a protocol
        error — pages ride one in-order TCP stream."""
        st = self._staged.setdefault(rid, _Staged())
        expect = st.next_idx + sum(h[0] for h in st.held)
        if start != expect:
            raise RuntimeError(
                "page stream for rid %r out of order: got start %d, "
                "expected %d" % (rid, start, expect))
        st.held.append((n, bufs))
        self._try_install(st)

    def on_handoff(self, rid: int, total_pages: int, meta: dict):
        st = self._staged.setdefault(rid, _Staged())
        st.total = total_pages
        st.meta = meta
        self._try_install(st)

    def _try_install(self, st: _Staged):
        while st.held:
            n, bufs = st.held[0]
            ids = self.engine.cache.alloc(n)
            if ids is None:
                return                    # pool dry: hold as received
            content = bufs_to_pages(self.engine.cache, n, bufs)
            self.engine.cache.install_pages(ids, content)
            del content                   # last array refs before release
            st.installed.extend(ids)
            st.next_idx += n
            st.held.pop(0)
            self.pages_installed_total += n
            _release(bufs)

    def ready(self, rid: int) -> bool:
        """All pages installed + handoff metadata present?"""
        st = self._staged.get(rid)
        return (st is not None and st.total is not None
                and not st.held and st.next_idx == st.total)

    def retry_installs(self):
        """Pool pressure may have eased (a request retired): drain
        held content."""
        for st in self._staged.values():
            self._try_install(st)

    def take(self, rid: int) -> Tuple[List[int], dict]:
        """Claim a ready request's installed pages + handoff meta (the
        caller passes them to ``engine.admit_prefilled``); the staging
        record is dropped — pages now belong to the engine request."""
        st = self._staged.pop(rid)
        return st.installed, st.meta

    def abort(self, rid: int) -> int:
        """Drop a partially-streamed request (its prefill replica
        died, or the router resubmitted it): free installed pages,
        discard held content.  Returns pages freed."""
        st = self._staged.pop(rid, None)
        if st is None:
            return 0
        if st.installed:
            self.engine.cache.free(st.installed)
        for _, bufs in st.held:           # put segments: unmap now
            _release(bufs)
        return len(st.installed)

    @property
    def staged_rids(self):
        return list(self._staged)
