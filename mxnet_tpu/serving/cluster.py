"""Multi-replica serving cluster: SLO-aware router over N engines.

After round 7 the serving stack topped out at ONE ``ServingEngine``
fed directly by a benchmark loop.  This module is the cluster/front-end
layer the Orca/vLLM lineage assumes above the engine: it owns N
replicas (threads in-process, one engine + one prefix cache each) and
gives clients a single async ``submit()/result()`` API.

* **Routing** — least-loaded, with **prefix affinity**: the router
  keys each prompt's full-page prefix chains
  (``prefix_cache.chain_keys``) and sends a request whose prefix was
  recently routed somewhere back to that replica, as long as that
  replica's load is within ``affinity_slack`` of the minimum — so a
  shared system prompt is prefetched once per replica it actually
  lands on, not once per request.  Affinity never overrides health or
  a drained replica.
* **Admission** — the waiting set (router inboxes + engine queues) is
  bounded by ``max_queue``; ``submit()`` raises
  :class:`ClusterOverloaded` past it (backpressure, not buffering).
  A per-request ``ttl_s`` expires requests still WAITING past their
  deadline (:class:`RequestExpired` from ``result()``); requests that
  started decoding are never expired mid-flight.
* **Failover** — a replica whose worker raises fails itself over; a
  replica that stalls past ``watchdog_s`` while holding work is
  failed over by the monitor thread.  Either way its waiting and
  in-flight requests are resubmitted to survivors with their
  committed tokens as prompt extension — the engine's
  recompute-exact resume path, so under f32 greedy the final output
  is token-identical to an undisturbed run (pinned by
  ``tests/test_serving_cluster.py``).  The zombie worker of a stalled
  replica is fenced: completions are matched against the request's
  current (replica, engine-rid) assignment under the cluster lock,
  so a late step can never deliver into a resubmitted request.
* **Drain / scale-down** — ``drain_replica(i)`` stops routing to a
  replica, reroutes its waiting requests, lets in-flight requests
  finish, and parks the worker; ``close()`` drains everything.

Clock: ``time.perf_counter`` throughout — the serving trace clock
(mxlint ``clock-mix`` enforces this for the whole package).
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import profiler
from .engine import ServingEngine
from .prefix_cache import chain_keys

__all__ = ["ServingCluster", "ClusterRequest", "ClusterOverloaded",
           "RequestExpired", "ClusterClosed", "ClusterFailed"]

# rid blocks: replica i assigns engine rids in [i*RID_BLOCK, ...), so
# request ids and trace swimlanes stay unique across the cluster
RID_BLOCK = 1 << 20


class ClusterOverloaded(RuntimeError):
    """submit() refused: the bounded admission queue is full."""


class RequestExpired(RuntimeError):
    """The request's TTL elapsed before it started decoding."""


class ClusterClosed(RuntimeError):
    """The cluster is closed (or lost every replica)."""


class ClusterFailed(RuntimeError):
    """No healthy replica remained to finish the request."""


class ClusterRequest:
    """Front-end request record.  ``committed`` accumulates tokens
    from failed-over incarnations; the live incarnation's engine
    request holds the rest."""
    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_id",
                 "deadline", "state", "replica", "engine_rid",
                 "committed", "output", "error", "done_evt",
                 "submit_t", "first_token_t", "affinity_keys",
                 "failovers", "delivered")

    def __init__(self, rid, prompt, max_new_tokens, eos_id, deadline,
                 affinity_keys):
        self.rid = rid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.deadline = deadline
        self.state = "queued"   # queued|running|done|expired|failed
        self.replica: Optional[int] = None
        self.engine_rid: Optional[int] = None
        self.committed: List[int] = []
        self.output: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.done_evt = threading.Event()
        self.submit_t = time.perf_counter()
        self.first_token_t: Optional[float] = None
        self.affinity_keys = affinity_keys
        self.failovers = 0
        self.delivered = False


class _Replica:
    __slots__ = ("idx", "engine", "thread", "inbox", "wake", "lock",
                 "in_flight", "heartbeat", "alive", "draining", "dead",
                 "error", "drained_evt")

    def __init__(self, idx, engine):
        self.idx = idx
        self.engine = engine
        self.thread: Optional[threading.Thread] = None
        self.inbox: "collections.deque[ClusterRequest]" = \
            collections.deque()
        self.wake = threading.Event()
        self.in_flight: Dict[int, ClusterRequest] = {}
        self.heartbeat = time.perf_counter()
        self.alive = True
        self.draining = False
        self.dead = False
        self.error: Optional[BaseException] = None
        self.drained_evt = threading.Event()

    @property
    def load(self):
        return len(self.inbox) + len(self.in_flight)

    @property
    def waiting(self):
        # inbox + engine-queued (len() reads are GIL-atomic; the value
        # is advisory — admission control, not correctness).  A dead
        # replica's abandoned engine queue must not count against the
        # cluster's admission budget.
        if self.dead:
            return 0
        return len(self.inbox) + len(self.engine._queue)


class _ClusterObs:
    """Router-level instrument bundle (mirrors ``_EngineObs``)."""

    _seq = [0]

    def __init__(self, registry=None):
        from .. import obs as O
        if registry is None:
            registry = O.MetricsRegistry(
                labels={"cluster": str(self._seq[0])})
            self._seq[0] += 1
            O.register_engine_registry(registry)
        self.registry = registry
        c, g, h = registry.counter, registry.gauge, registry.histogram
        self.submitted = c("cluster_requests_submitted_total",
                           "requests accepted by cluster submit()")
        self.rejected = c("cluster_requests_rejected_total",
                          "submissions refused by backpressure")
        self.expired = c("cluster_requests_expired_total",
                         "requests whose TTL elapsed while waiting")
        self.completed = c("cluster_requests_completed_total",
                           "requests finished across all replicas")
        self.failovers = c("cluster_failovers_total",
                           "replica failures (raise or watchdog "
                           "stall) drained to survivors")
        self.resubmitted = c("cluster_requests_resubmitted_total",
                             "requests resubmitted after a replica "
                             "failure (recompute-exact resume)")
        self.routed_affinity = c("cluster_routed_affinity_total",
                                 "routing decisions won by prefix "
                                 "affinity")
        self.routed_least = c("cluster_routed_least_loaded_total",
                              "routing decisions by least-loaded")
        self.g_healthy = g("cluster_replicas_healthy",
                           "replicas accepting traffic")
        self.g_waiting = g("cluster_queue_depth",
                           "waiting requests (inboxes + engine "
                           "queues)")
        self.g_in_flight = g("cluster_in_flight",
                             "requests holding an engine slot or "
                             "engine queue entry")
        self.h_ttft = h("cluster_ttft_ms",
                        help="cluster submit() -> first committed "
                             "token (any incarnation)")
        from ..obs import RequestTraceEmitter
        self.trace = RequestTraceEmitter()


class ServingCluster:
    """N in-process ``ServingEngine`` replicas behind one router.

    Engine sizing kwargs (``num_slots``, ``page_size`` …) apply to
    EVERY replica.  ``prefix_cache`` defaults ON here (it is what
    prefix-affinity routing exists for); each replica has its own
    cache, so shared-prefix prefill is paid once per replica.  The
    round-11 decode levers pass straight through: ``kernel`` selects
    each replica's attention path (xla gather vs fused pallas walk)
    and ``spec_K``/``spec_drafter``/``spec_ngram`` arm in-engine
    speculative decode per replica — failover/resubmit semantics are
    unchanged because committed tokens are committed tokens however
    many a step produced (recompute-exact resume replays them as
    prompt extension, pinned by ``tests/test_serving_cluster.py``).
    ``tp=N``/``mesh=`` (round 14) likewise: every replica lowers its
    step through the same tensor-parallel mesh, and the whole engine
    config is captured ONCE (``_engine_kwargs``) so a failover
    resubmission always lands on a survivor with identical tp/mesh
    setup (``tests/test_serving_tp.py`` pins failover-under-tp).
    On one host the replicas time-share the same tp devices — the
    scale-out story across hosts is ROADMAP item 3.
    """

    def __init__(self, params, cfg, *, replicas=2, num_slots,
                 page_size=16, num_pages=None, pages_per_slot=None,
                 prefill_chunk=8, kv_int8=False, prefix_cache=True,
                 metrics=None, registry=None, max_queue=256,
                 watchdog_s=30.0, affinity_slack=None,
                 affinity_capacity=4096, retain_results=4096,
                 kernel="xla", spec_K=0, spec_drafter="ngram",
                 spec_ngram=2, tp=1, mesh=None):
        if replicas < 1:
            raise ValueError("ServingCluster: replicas must be >= 1")
        self.num_slots = num_slots
        self.page_size = page_size
        self.max_queue = int(max_queue)
        self.watchdog_s = float(watchdog_s)
        self.prefix_enabled = bool(prefix_cache)
        # affinity may leave the favored replica at most this many
        # WAITING requests deeper than the shallowest queue: the cache
        # hit saves prefill steps, but letting a hot prefix build an
        # unbounded queue behind one replica while others idle trades
        # TTFT SLO for hit ratio — exactly the wrong direction
        self.affinity_slack = (max(1, num_slots // 4)
                               if affinity_slack is None
                               else int(affinity_slack))
        self._lock = threading.RLock()
        self._closed = False
        self._next_rid = 0
        self.requests: Dict[int, ClusterRequest] = {}
        # terminal requests are retained (rid order) up to this many,
        # then dropped — a long-running cluster must not grow its
        # request table with total traffic served
        self._retain = int(retain_results)
        self._terminal: "collections.deque[int]" = collections.deque()
        # prefix-chain key -> replica idx (LRU-capped)
        self._affinity: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._affinity_cap = int(affinity_capacity)
        if metrics is None:
            import os
            metrics = registry is not None or \
                os.environ.get("MXNET_SERVING_METRICS", "0") == "1"
        self._obs = _ClusterObs(registry) if metrics else None
        # ONE captured engine config (round 14): every replica — and
        # any future re-admission target — is built from this dict, so
        # a request resubmitted to a survivor after failover lands on
        # an engine with the SAME tp/mesh/kernel/spec setup as the one
        # that died.  Previously the kwargs were splatted ad hoc at
        # the construction site only; adding an engine knob meant
        # remembering to thread it here by hand.
        if tp > 1 or mesh is not None:
            # build the mesh and commit the params into their megatron
            # shards ONCE, cluster-wide: every replica's engine then
            # sees already-correctly-placed arrays and its device_put
            # is a no-op — without this, R replicas would each retain
            # an independent sharded copy of the weights on the same
            # tp devices (R× the per-device weight bytes the tp story
            # exists to divide)
            import jax
            from ..models import gpt as G
            from ..parallel.mesh import serving_mesh
            from .engine import _bind
            if mesh is None:
                mesh = serving_mesh(tp)
            if int(mesh.shape.get("tp", 1)) > 1:
                params = jax.device_put(
                    params, _bind(mesh,
                                  G.decode_param_specs(params, cfg)))
        self._engine_kwargs = dict(
            num_slots=num_slots, page_size=page_size,
            num_pages=num_pages, pages_per_slot=pages_per_slot,
            prefill_chunk=prefill_chunk, kv_int8=kv_int8,
            prefix_cache=prefix_cache, metrics=bool(metrics),
            kernel=kernel, spec_K=spec_K, spec_drafter=spec_drafter,
            spec_ngram=spec_ngram, tp=tp, mesh=mesh)
        self.replicas: List[_Replica] = []
        for i in range(replicas):
            eng = ServingEngine(params, cfg, rid_start=i * RID_BLOCK,
                                **self._engine_kwargs)
            self.replicas.append(_Replica(i, eng))
        # pre-warm the (shared) step program BEFORE workers and the
        # watchdog start: a first-step compile longer than watchdog_s
        # would otherwise read as a stall and cascade failovers across
        # equally-cold survivors.  One compile covers every replica —
        # the step cache keys on config, not engine.
        eng0 = self.replicas[0].engine
        wid = eng0.submit(np.ones(1, np.int32), 1)
        eng0.run()
        del eng0.requests[wid]
        for k in eng0.stats:
            eng0.stats[k] = type(eng0.stats[k])()
        if metrics:
            eng0.reset_metrics()
        for rep in self.replicas:
            rep.thread = threading.Thread(
                target=self._worker, args=(rep,), daemon=True,
                name="serving-replica-%d" % rep.idx)
            rep.thread.start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="serving-cluster-monitor")
        self._monitor.start()

    # ------------------------------------------------------- intake --
    def submit(self, prompt, max_new_tokens, eos_id=None, ttl_s=None):
        """Queue a request; returns its cluster rid immediately.
        Raises :class:`ClusterOverloaded` when the bounded admission
        queue is full and :class:`ClusterClosed` after close()."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        # validate NOW, in the caller's thread, with the engine's own
        # rules: a request the engines would reject must fail the
        # submit() call, not poison a replica worker later
        eng0 = self.replicas[0].engine
        if prompt.size < 1:
            raise ValueError("submit: empty prompt")
        if max_new_tokens < 1:
            raise ValueError("submit: max_new_tokens must be >= 1")
        total = prompt.size + int(max_new_tokens)
        if total > eng0.max_seq:
            raise ValueError(
                "submit: %d tokens > replica max_seq %d"
                % (total, eng0.max_seq))
        if total > eng0.cfg.max_len:
            raise ValueError("submit: %d tokens > cfg.max_len=%d"
                             % (total, eng0.cfg.max_len))
        keys = chain_keys(prompt, self.page_size) \
            if self.prefix_enabled else []
        with self._lock:
            if self._closed:
                raise ClusterClosed("submit() after close()")
            if not self._healthy():
                raise ClusterClosed("no healthy replicas")
            if sum(r.waiting for r in self.replicas) >= self.max_queue:
                if self._obs is not None:
                    self._obs.rejected.inc()
                raise ClusterOverloaded(
                    "admission queue full (%d waiting >= max_queue "
                    "%d)" % (sum(r.waiting for r in self.replicas),
                             self.max_queue))
            deadline = None if ttl_s is None \
                else time.perf_counter() + float(ttl_s)
            cr = ClusterRequest(self._next_rid, prompt,
                                int(max_new_tokens), eos_id, deadline,
                                keys)
            self._next_rid += 1
            self.requests[cr.rid] = cr
            rep = self._route_locked(cr)
            rep.inbox.append(cr)
            cr.replica = rep.idx
            if self._obs is not None:
                self._obs.submitted.inc()
                self._sync_gauges_locked()
            rep.wake.set()
        return cr.rid

    def result(self, rid, timeout=None):
        """Block until the request finishes; returns the full token
        array (prompt + generated).  Raises :class:`RequestExpired` /
        :class:`ClusterFailed` per the terminal state, TimeoutError
        on timeout."""
        cr = self.requests.get(rid)
        if cr is None:
            raise KeyError(
                "result(%d): unknown rid (already collected and "
                "purged past retain_results?)" % rid)
        if not cr.done_evt.wait(timeout):
            raise TimeoutError("result(%d): still running" % rid)
        with self._lock:
            cr.delivered = True
            self._purge_locked()
        if cr.state == "done":
            return cr.output
        if cr.state == "expired":
            raise RequestExpired("request %d expired before "
                                 "admission" % rid)
        raise ClusterFailed("request %d: %r" % (rid, cr.error))

    def drain(self, timeout=None):
        """Wait until every submitted request reaches a terminal
        state.  Returns True if fully drained."""
        deadline = None if timeout is None \
            else time.perf_counter() + timeout
        for cr in list(self.requests.values()):
            left = None if deadline is None \
                else max(0.0, deadline - time.perf_counter())
            if not cr.done_evt.wait(left):
                return False
        return True

    # ------------------------------------------------------ routing --
    def _healthy(self):
        return [r for r in self.replicas
                if r.alive and not r.draining]

    def _route_locked(self, cr):
        healthy = self._healthy()
        if not healthy:
            raise ClusterClosed("no healthy replicas")
        min_wait = min(r.waiting for r in healthy)
        target = None
        # longest registered prefix wins (iterate deepest-first)
        for key in reversed(cr.affinity_keys):
            idx = self._affinity.get(key)
            if idx is None:
                continue
            rep = self.replicas[idx]
            if rep.alive and not rep.draining \
                    and rep.waiting <= min_wait + self.affinity_slack:
                target = rep
                self._affinity.move_to_end(key)
                if self._obs is not None:
                    self._obs.routed_affinity.inc()
                break
        if target is None:
            target = min(healthy, key=lambda r: (r.load, r.idx))
            if self._obs is not None:
                self._obs.routed_least.inc()
        for key in cr.affinity_keys:
            self._affinity[key] = target.idx
            self._affinity.move_to_end(key)
        while len(self._affinity) > self._affinity_cap:
            self._affinity.popitem(last=False)
        return target

    def _retire_locked(self, cr):
        """Bound the request table: remember terminal rids in order
        and drop the oldest DELIVERED ones past ``retain_results`` — a
        long-running cluster must not grow memory with total traffic
        served, but a finished result the client has not yet collected
        is never purged out from under its pending result() call."""
        self._terminal.append(cr.rid)
        self._purge_locked()

    def _purge_locked(self):
        excess = len(self._terminal) - self._retain
        if excess <= 0:
            return
        kept: "collections.deque[int]" = collections.deque()
        for rid in self._terminal:
            req = self.requests.get(rid)
            if excess > 0 and (req is None or req.delivered):
                excess -= 1
                if req is not None:
                    del self.requests[rid]
            else:
                kept.append(rid)
        self._terminal = kept

    def _sync_gauges_locked(self):
        obs = self._obs
        if obs is None:
            return
        obs.g_healthy.set(len(self._healthy()))
        obs.g_waiting.set(sum(r.waiting for r in self.replicas))
        obs.g_in_flight.set(
            sum(len(r.in_flight) for r in self.replicas))

    # ------------------------------------------------------- worker --
    def _worker(self, rep):
        eng = rep.engine
        while True:
            rep.heartbeat = time.perf_counter()
            if rep.dead:
                return
            try:
                self._pump_inbox(rep)
                finished = eng.step()
            except Exception as e:                  # replica death
                self._fail_replica(rep, e)
                return
            rep.heartbeat = time.perf_counter()
            if finished is False:
                with self._lock:
                    idle = not rep.inbox and not rep.in_flight
                    if idle and (rep.draining or self._closed):
                        rep.alive = False
                        rep.drained_evt.set()
                        self._sync_gauges_locked()
                        return
                rep.wake.wait(timeout=0.02)
                rep.wake.clear()
            elif finished:
                for erid in finished:
                    self._complete(rep, erid)

    def _pump_inbox(self, rep):
        """Move waiting requests into the engine, bounded to one
        engine-queue's worth of backlog so TTL expiry keeps meaning
        (a request buried in an unbounded engine queue could never be
        expired — the engine queue is this thread's, the inbox is the
        cluster's)."""
        eng = rep.engine
        while True:
            with self._lock:
                if not rep.inbox or rep.dead:
                    return
                if len(eng._queue) >= self.num_slots:
                    return
                cr = rep.inbox.popleft()
                now = time.perf_counter()
                if cr.deadline is not None and now > cr.deadline \
                        and not cr.committed:
                    cr.state = "expired"
                    self._retire_locked(cr)
                    if self._obs is not None:
                        self._obs.expired.inc()
                        self._sync_gauges_locked()
                    cr.done_evt.set()
                    continue
                prompt = cr.prompt if not cr.committed else \
                    np.concatenate([cr.prompt,
                                    np.asarray(cr.committed,
                                               np.int32)])
                try:
                    erid = eng.submit(
                        prompt, cr.max_new_tokens - len(cr.committed),
                        eos_id=cr.eos_id)
                except Exception as e:
                    # a request THIS engine rejects (submit() already
                    # pre-validated, so this is belt-and-braces) fails
                    # alone — it must not take the worker down
                    cr.state = "failed"
                    cr.error = e
                    self._retire_locked(cr)
                    cr.done_evt.set()
                    continue
                cr.state = "running"
                cr.replica = rep.idx
                cr.engine_rid = erid
                rep.in_flight[erid] = cr
                if self._obs is not None:
                    self._sync_gauges_locked()

    def _complete(self, rep, erid):
        with self._lock:
            cr = rep.in_flight.pop(erid, None)
            if cr is None or rep.dead:
                return                      # fenced zombie completion
            if cr.state != "running" or cr.replica != rep.idx \
                    or cr.engine_rid != erid:
                return
            ereq = rep.engine.requests[erid]
            cr.output = ereq.output
            cr.state = "done"
            if cr.first_token_t is None and ereq.token_times:
                cr.first_token_t = ereq.token_times[0]
            # the engine-side record (prompt/generated/output arrays)
            # is fully copied out — drop it so a long-running replica
            # does not accumulate one Request per request ever served
            del rep.engine.requests[erid]
            self._retire_locked(cr)
            if self._obs is not None:
                self._obs.completed.inc()
                if cr.first_token_t is not None:
                    self._obs.h_ttft.observe(
                        (cr.first_token_t - cr.submit_t) * 1e3)
                self._sync_gauges_locked()
            cr.done_evt.set()

    # ----------------------------------------------------- failover --
    def _fail_replica(self, rep, error):
        """Drain a dead/stalled replica: mark it out of rotation and
        resubmit its waiting + in-flight requests to survivors via the
        recompute-exact resume path.  Idempotent under the lock (the
        worker's own exception path and the monitor's watchdog can
        race here)."""
        with self._lock:
            if rep.dead:
                return
            rep.dead = True
            rep.alive = False
            rep.error = error
            strays = list(rep.inbox)
            rep.inbox.clear()
            in_flight = list(rep.in_flight.items())
            rep.in_flight.clear()
            obs = self._obs
            if obs is not None:
                obs.failovers.inc()
            tracing = obs is not None and profiler.is_recording()
            now = time.perf_counter()
            survivors = self._healthy()
            for erid, cr in in_flight:
                # snapshot committed tokens (greedy determinism makes
                # any snapshot point exact: the resumed run regenerates
                # the continuation identically)
                ereq = rep.engine.requests.get(erid)
                if ereq is not None:
                    cr.committed.extend(int(t)
                                        for t in list(ereq.generated))
                    if cr.first_token_t is None and ereq.token_times:
                        cr.first_token_t = ereq.token_times[0]
                cr.failovers += 1
                if tracing:
                    obs.trace.add_instant(
                        cr.rid, "failover", now,
                        args={"replica": rep.idx,
                              "committed": len(cr.committed)})
            for cr in strays + [cr for _, cr in in_flight]:
                if cr.state not in ("queued", "running"):
                    continue
                done = (cr.eos_id is not None
                        and cr.eos_id in cr.committed) or \
                    len(cr.committed) >= cr.max_new_tokens
                if done:
                    cr.output = np.concatenate(
                        [cr.prompt,
                         np.asarray(cr.committed, np.int32)])
                    cr.state = "done"
                    self._retire_locked(cr)
                    if obs is not None:
                        obs.completed.inc()
                    cr.done_evt.set()
                    continue
                cr.state = "queued"
                cr.engine_rid = None
                if not survivors:
                    cr.state = "failed"
                    cr.error = error
                    self._retire_locked(cr)
                    cr.done_evt.set()
                    continue
                target = self._route_locked(cr)
                target.inbox.append(cr)
                cr.replica = target.idx
                target.wake.set()
                if obs is not None:
                    obs.resubmitted.inc()
                    if tracing:
                        obs.trace.add_instant(
                            cr.rid, "resubmit", now,
                            args={"replica": target.idx})
            if tracing:
                obs.trace.flush()
            if obs is not None:
                self._sync_gauges_locked()

    def _monitor_loop(self):
        period = max(0.01, min(0.25, self.watchdog_s / 4.0))
        while True:
            time.sleep(period)
            with self._lock:
                if self._closed and all(not r.alive
                                        for r in self.replicas):
                    return
                now = time.perf_counter()
                stalled = [
                    r for r in self.replicas
                    if r.alive and not r.dead
                    and (r.in_flight or r.inbox)
                    and now - r.heartbeat > self.watchdog_s]
            for rep in stalled:
                self._fail_replica(
                    rep, RuntimeError(
                        "replica %d stalled past watchdog %.3fs"
                        % (rep.idx, self.watchdog_s)))

    # ---------------------------------------------- drain/scale-down --
    def drain_replica(self, idx, timeout=None):
        """Graceful scale-down of one replica: stop routing to it,
        reroute its waiting requests, let in-flight requests finish,
        park the worker.  Returns True once drained."""
        rep = self.replicas[idx]
        with self._lock:
            rep.draining = True
            strays = list(rep.inbox)
            rep.inbox.clear()
            for cr in strays:
                if cr.state != "queued":
                    continue
                target = self._route_locked(cr)
                target.inbox.append(cr)
                cr.replica = target.idx
                target.wake.set()
            if self._obs is not None:
                self._sync_gauges_locked()
        rep.wake.set()
        return rep.drained_evt.wait(timeout)

    def close(self, timeout=None):
        """Drain every replica and stop the monitor.  In-flight work
        finishes first (the watchdog still covers a replica that
        stalls during shutdown)."""
        with self._lock:
            self._closed = True
        for rep in self.replicas:
            rep.wake.set()
        for rep in self.replicas:
            if rep.thread is not None:
                rep.thread.join(timeout)
        self._monitor.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    # --------------------------------------------------- accounting --
    def health(self):
        """Per-replica health snapshot (the health-check surface)."""
        now = time.perf_counter()
        with self._lock:
            return [{"replica": r.idx, "alive": r.alive,
                     "draining": r.draining, "dead": r.dead,
                     "load": r.load, "waiting": r.waiting,
                     "in_flight": len(r.in_flight),
                     "heartbeat_age_s": now - r.heartbeat,
                     "error": repr(r.error) if r.error else None}
                    for r in self.replicas]

    @property
    def registry(self):
        return self._obs.registry if self._obs is not None else None

    def metrics(self):
        """JSON-able snapshot: router counters + per-replica engine
        snapshots."""
        if self._obs is None:
            return {"enabled": False}
        snap = self._obs.registry.snapshot()
        snap["enabled"] = True
        snap["replicas"] = [r.engine.metrics() for r in self.replicas]
        return snap
