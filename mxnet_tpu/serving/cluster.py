"""Multi-replica serving cluster: SLO-aware router over N engines.

After round 7 the serving stack topped out at ONE ``ServingEngine``
fed directly by a benchmark loop.  This module is the cluster/front-end
layer the Orca/vLLM lineage assumes above the engine: it owns N
replicas (threads in-process, one engine + one prefix cache each) and
gives clients a single async ``submit()/result()`` API.

* **Routing** — least-loaded, with **prefix affinity**: the router
  keys each prompt's full-page prefix chains
  (``prefix_cache.chain_keys``) and sends a request whose prefix was
  recently routed somewhere back to that replica, as long as that
  replica's load is within ``affinity_slack`` of the minimum — so a
  shared system prompt is prefetched once per replica it actually
  lands on, not once per request.  Affinity never overrides health or
  a drained replica.
* **Admission** — the waiting set (router inboxes + engine queues) is
  bounded by ``max_queue``; ``submit()`` raises
  :class:`ClusterOverloaded` past it (backpressure, not buffering).
  A per-request ``ttl_s`` expires requests still WAITING past their
  deadline (:class:`RequestExpired` from ``result()``); requests that
  started decoding are never expired mid-flight.
* **Failover** — a replica whose worker raises fails itself over; a
  replica that stalls past ``watchdog_s`` while holding work is
  failed over by the monitor thread.  Either way its waiting and
  in-flight requests are resubmitted to survivors with their
  committed tokens as prompt extension — the engine's
  recompute-exact resume path, so under f32 greedy the final output
  is token-identical to an undisturbed run (pinned by
  ``tests/test_serving_cluster.py``).  The zombie worker of a stalled
  replica is fenced: completions are matched against the request's
  current (replica, engine-rid) assignment under the cluster lock,
  so a late step can never deliver into a resubmitted request.
* **Drain / scale-down** — ``drain_replica(i)`` stops routing to a
  replica, reroutes its waiting requests, lets in-flight requests
  finish, and parks the worker; ``close()`` drains everything.

Clock: ``time.perf_counter`` throughout — the serving trace clock
(mxlint ``clock-mix`` enforces this for the whole package).

Round 15 promotes replicas to **processes** and splits roles:
:class:`DisaggServingCluster` (bottom of this module) runs a router in
THIS process and N prefill + M decode workers as spawned OS processes,
wired by ``serving/transport.py`` over the ``parallel/dist.py`` raw
frames.  A prefill worker runs chunked prefill only and streams
finished int8/f32 KV pages to its request's decode worker
(``serving/page_streamer.py`` — pipelined with the prefill chunks);
the decode worker installs them and picks the request up at
``n_cached = prompt_len``.  The prefix-cache trie's knowledge lives in
the router's :class:`prefix_cache.ClusterPrefixIndex`; a replica
matching another replica's chain fetches the page bytes peer-to-peer
instead of re-prefilling — once per cluster, not once per replica.
SIGKILL of any worker process triggers the router's watchdog: its
requests resubmit to survivors with their streamed committed tokens
as prompt extension — the same recompute-exact resume contract as the
in-process cluster, now across a process boundary.
"""
from __future__ import annotations

import collections
import itertools
import os
import queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import profiler
from .engine import ServingEngine
from .prefix_cache import chain_keys, ClusterPrefixIndex

__all__ = ["ServingCluster", "ClusterRequest", "ClusterOverloaded",
           "RequestExpired", "RequestCancelled", "ClusterClosed",
           "ClusterFailed", "DisaggServingCluster", "run_worker"]

# rid blocks: replica i assigns engine rids in [i*RID_BLOCK, ...), so
# request ids and trace swimlanes stay unique across the cluster
RID_BLOCK = 1 << 20


def _env_default(name, fallback, cast=float):
    """Operational limits default from ``MXNET_SERVE_*`` env vars
    (round 16): the watchdog/TTL/admission bounds were hard-coded
    construction defaults, but the autoscaler and chaos tests need
    tighter timeouts than production wants, and ops wants to retune
    a deployment without editing call sites (docs/env_vars.md).  An
    explicit constructor argument always wins; the env var only
    replaces the built-in default."""
    v = os.environ.get(name)
    if v is None or v == "":
        return fallback
    try:
        return cast(v)
    except ValueError:
        raise ValueError("%s=%r: expected %s"
                         % (name, v, cast.__name__))


class ClusterOverloaded(RuntimeError):
    """submit() refused: the bounded admission queue is full.

    Carries a structured ``retry_after_s`` hint — the estimated time
    until the queue drains below the admission bound at the cluster's
    recent completion rate (groundwork for the HTTP front door's
    429 + Retry-After, ROADMAP item 6).  Also surfaced on the
    ``cluster_retry_after_s`` gauge at each rejection."""

    def __init__(self, msg, retry_after_s=None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class RequestExpired(RuntimeError):
    """The request's TTL elapsed before it started decoding."""


class RequestCancelled(RuntimeError):
    """The request was cancelled via ``cancel(rid)`` (round 20: the
    HTTP front door's client-disconnect propagation) before it
    finished; its slot and pages were released immediately."""


class ClusterClosed(RuntimeError):
    """The cluster is closed (or lost every replica)."""


class ClusterFailed(RuntimeError):
    """No healthy replica remained to finish the request."""


class ClusterRequest:
    """Front-end request record.  ``committed`` accumulates tokens
    from failed-over incarnations; the live incarnation's engine
    request holds the rest."""
    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_id",
                 "deadline", "state", "replica", "engine_rid",
                 "committed", "output", "error", "done_evt",
                 "submit_t", "first_token_t", "token_times",
                 "affinity_keys", "failovers", "delivered",
                 "stream", "listeners", "cancel_req", "trace_id")

    def __init__(self, rid, prompt, max_new_tokens, eos_id, deadline,
                 affinity_keys, trace_id=None):
        self.rid = rid
        # edge-minted trace context (round 23): defaults to a
        # rid-derived id so direct submit() callers trace too
        self.trace_id = trace_id if trace_id is not None \
            else "rid%d" % rid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.deadline = deadline
        self.state = "queued"   # queued|running|done|expired|failed
        self.replica: Optional[int] = None
        self.engine_rid: Optional[int] = None
        self.committed: List[int] = []
        self.output: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.done_evt = threading.Event()
        self.submit_t = time.perf_counter()
        self.first_token_t: Optional[float] = None
        # per-token commit timestamps across ALL incarnations — the
        # goodput classifier's input (worst inter-token gap = the
        # stall a streaming client saw, failovers included)
        self.token_times: List[float] = []
        self.affinity_keys = affinity_keys
        self.failovers = 0
        self.delivered = False
        # the canonical PUBLISHED token stream (round 20): what every
        # attach_stream listener has been handed so far, across
        # incarnations — always a prefix of committed + the live
        # engine request's generated tokens, so a failover resumes
        # the stream without a gap or a repeat
        self.stream: List[int] = []
        self.listeners: List = []
        self.cancel_req = False


class _Replica:
    __slots__ = ("idx", "engine", "thread", "inbox", "wake", "lock",
                 "in_flight", "heartbeat", "alive", "draining", "dead",
                 "error", "drained_evt")

    def __init__(self, idx, engine):
        self.idx = idx
        self.engine = engine
        self.thread: Optional[threading.Thread] = None
        self.inbox: "collections.deque[ClusterRequest]" = \
            collections.deque()
        self.wake = threading.Event()
        self.in_flight: Dict[int, ClusterRequest] = {}
        self.heartbeat = time.perf_counter()
        self.alive = True
        self.draining = False
        self.dead = False
        self.error: Optional[BaseException] = None
        self.drained_evt = threading.Event()

    @property
    def load(self):
        return len(self.inbox) + len(self.in_flight)

    @property
    def waiting(self):
        # inbox + engine-queued (len() reads are GIL-atomic; the value
        # is advisory — admission control, not correctness).  A dead
        # replica's abandoned engine queue must not count against the
        # cluster's admission budget.
        if self.dead:
            return 0
        return len(self.inbox) + len(self.engine._queue)


class _ClusterObs:
    """Router-level instrument bundle (mirrors ``_EngineObs``)."""

    _seq = [0]

    def __init__(self, registry=None):
        from .. import obs as O
        if registry is None:
            registry = O.MetricsRegistry(
                labels={"cluster": str(self._seq[0])})
            self._seq[0] += 1
            O.register_engine_registry(registry)
        self.registry = registry
        c, g, h = registry.counter, registry.gauge, registry.histogram
        self.submitted = c("cluster_requests_submitted_total",
                           "requests accepted by cluster submit()")
        self.rejected = c("cluster_requests_rejected_total",
                          "submissions refused by backpressure")
        self.expired = c("cluster_requests_expired_total",
                         "requests whose TTL elapsed while waiting")
        self.cancelled = c("cluster_cancelled_total",
                           "requests cancelled via cancel(rid) — "
                           "client disconnects propagated by the "
                           "HTTP front door, plus chaos 'cancel' "
                           "actions")
        self.completed = c("cluster_requests_completed_total",
                           "requests finished across all replicas")
        self.failovers = c("cluster_failovers_total",
                           "replica failures (raise or watchdog "
                           "stall) drained to survivors")
        self.resubmitted = c("cluster_requests_resubmitted_total",
                             "requests resubmitted after a replica "
                             "failure (recompute-exact resume)")
        self.routed_affinity = c("cluster_routed_affinity_total",
                                 "routing decisions won by prefix "
                                 "affinity")
        self.routed_least = c("cluster_routed_least_loaded_total",
                              "routing decisions by least-loaded")
        self.g_healthy = g("cluster_replicas_healthy",
                           "replicas accepting traffic")
        self.g_waiting = g("cluster_queue_depth",
                           "waiting requests (inboxes + engine "
                           "queues)")
        self.g_in_flight = g("cluster_in_flight",
                             "requests holding an engine slot or "
                             "engine queue entry")
        self.g_retry_after = g("cluster_retry_after_s",
                               "last Retry-After hint handed to a "
                               "rejected submit() (queue excess / "
                               "recent drain rate)")
        self.scale_ups = c("cluster_scale_ups_total",
                           "replicas added (add_replica)")
        self.scale_downs = c("cluster_scale_downs_total",
                             "replicas drained and released "
                             "(remove_replica)")
        self.h_ttft = h("cluster_ttft_ms",
                        help="cluster submit() -> first committed "
                             "token (any incarnation)")
        from ..obs import RequestTraceEmitter
        self.trace = RequestTraceEmitter()


class ServingCluster:
    """N in-process ``ServingEngine`` replicas behind one router.

    Engine sizing kwargs (``num_slots``, ``page_size`` …) apply to
    EVERY replica.  ``prefix_cache`` defaults ON here (it is what
    prefix-affinity routing exists for); each replica has its own
    cache, so shared-prefix prefill is paid once per replica.  The
    round-11 decode levers pass straight through: ``kernel`` selects
    each replica's attention path (xla gather vs fused pallas walk)
    and ``spec_K``/``spec_drafter``/``spec_ngram`` arm in-engine
    speculative decode per replica — failover/resubmit semantics are
    unchanged because committed tokens are committed tokens however
    many a step produced (recompute-exact resume replays them as
    prompt extension, pinned by ``tests/test_serving_cluster.py``).
    ``tp=N``/``mesh=`` (round 14) likewise: every replica lowers its
    step through the same tensor-parallel mesh, and the whole engine
    config is captured ONCE (``_engine_kwargs``) so a failover
    resubmission always lands on a survivor with identical tp/mesh
    setup (``tests/test_serving_tp.py`` pins failover-under-tp).
    On one host the replicas time-share the same tp devices — the
    scale-out story across hosts is ROADMAP item 3.
    """

    def __init__(self, params, cfg, *, replicas=2, num_slots,
                 page_size=16, num_pages=None, pages_per_slot=None,
                 prefill_chunk=8, kv_int8=False, prefix_cache=True,
                 metrics=None, registry=None, max_queue=None,
                 watchdog_s=None, default_ttl_s=None,
                 affinity_slack=None,
                 affinity_capacity=4096, retain_results=4096,
                 kernel="xla", spec_K=0, spec_drafter="ngram",
                 spec_ngram=2, tp=1, mesh=None, tier_bytes=None,
                 overlap=None):
        if replicas < 1:
            raise ValueError("ServingCluster: replicas must be >= 1")
        self.num_slots = num_slots
        self.page_size = page_size
        # operational limits: explicit argument > MXNET_SERVE_* env >
        # built-in default (docs/env_vars.md "Serving cluster limits")
        if max_queue is None:
            max_queue = _env_default("MXNET_SERVE_MAX_QUEUE", 256,
                                     int)
        if watchdog_s is None:
            watchdog_s = _env_default("MXNET_SERVE_WATCHDOG_S", 30.0)
        if default_ttl_s is None:
            default_ttl_s = _env_default("MXNET_SERVE_TTL_S", None)
        self.max_queue = int(max_queue)
        self.watchdog_s = float(watchdog_s)
        self.default_ttl_s = default_ttl_s
        self.prefix_enabled = bool(prefix_cache)
        # affinity may leave the favored replica at most this many
        # WAITING requests deeper than the shallowest queue: the cache
        # hit saves prefill steps, but letting a hot prefix build an
        # unbounded queue behind one replica while others idle trades
        # TTFT SLO for hit ratio — exactly the wrong direction
        self.affinity_slack = (max(1, num_slots // 4)
                               if affinity_slack is None
                               else int(affinity_slack))
        self._lock = threading.RLock()
        self._closed = False
        self._next_rid = 0
        self.requests: Dict[int, ClusterRequest] = {}
        # terminal requests are retained (rid order) up to this many,
        # then dropped — a long-running cluster must not grow its
        # request table with total traffic served
        self._retain = int(retain_results)
        self._terminal: "collections.deque[int]" = collections.deque()
        # prefix-chain key -> replica idx (LRU-capped)
        self._affinity: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._affinity_cap = int(affinity_capacity)
        if metrics is None:
            import os
            metrics = registry is not None or \
                os.environ.get("MXNET_SERVING_METRICS", "0") == "1"
        self._obs = _ClusterObs(registry) if metrics else None
        # ONE captured engine config (round 14): every replica — and
        # any future re-admission target — is built from this dict, so
        # a request resubmitted to a survivor after failover lands on
        # an engine with the SAME tp/mesh/kernel/spec setup as the one
        # that died.  Previously the kwargs were splatted ad hoc at
        # the construction site only; adding an engine knob meant
        # remembering to thread it here by hand.
        if tp > 1 or mesh is not None:
            # build the mesh and commit the params into their megatron
            # shards ONCE, cluster-wide: every replica's engine then
            # sees already-correctly-placed arrays and its device_put
            # is a no-op — without this, R replicas would each retain
            # an independent sharded copy of the weights on the same
            # tp devices (R× the per-device weight bytes the tp story
            # exists to divide)
            import jax
            from ..models import gpt as G
            from ..parallel.mesh import serving_mesh
            from .engine import _bind
            if mesh is None:
                mesh = serving_mesh(tp)
            if int(mesh.shape.get("tp", 1)) > 1:
                params = jax.device_put(
                    params, _bind(mesh,
                                  G.decode_param_specs(params, cfg)))
        self._engine_kwargs = dict(
            num_slots=num_slots, page_size=page_size,
            num_pages=num_pages, pages_per_slot=pages_per_slot,
            prefill_chunk=prefill_chunk, kv_int8=kv_int8,
            prefix_cache=prefix_cache, metrics=bool(metrics),
            kernel=kernel, spec_K=spec_K, spec_drafter=spec_drafter,
            spec_ngram=spec_ngram, tp=tp, mesh=mesh,
            tier_bytes=tier_bytes, overlap=overlap)
        # kept for add_replica (autoscaler scale-up): a replica added
        # mid-run must be built from the SAME params/config as the
        # originals (references only — params are already placed)
        self._params, self._cfg = params, cfg
        self._rid_blocks = replicas       # next replica's rid block
        # recent completion timestamps — the drain-rate estimate
        # behind ClusterOverloaded.retry_after_s
        self._completions: "collections.deque[float]" = \
            collections.deque(maxlen=256)
        # set True by an attaching Autoscaler: the zero-replica state
        # is then RECOVERABLE (tick self-heals below min_size), so
        # requests stranded by the last replica's death PARK here
        # instead of failing; add_replica reroutes them.  Without a
        # scaler the round-10 fail-fast contract stands.
        self.scaler_attached = False
        self._orphans: "collections.deque[ClusterRequest]" = \
            collections.deque()
        self.replicas: List[_Replica] = []
        for i in range(replicas):
            eng = ServingEngine(params, cfg, rid_start=i * RID_BLOCK,
                                **self._engine_kwargs)
            self.replicas.append(_Replica(i, eng))
        # submit()-side validation limits, captured once (replica 0's
        # engine may be released by a later scale-down)
        self._max_seq = self.replicas[0].engine.max_seq
        # pre-warm the (shared) step program BEFORE workers and the
        # watchdog start: a first-step compile longer than watchdog_s
        # would otherwise read as a stall and cascade failovers across
        # equally-cold survivors.  One compile covers every replica —
        # the step cache keys on config, not engine.
        self._warm_engine(self.replicas[0].engine)
        for rep in self.replicas:
            rep.thread = threading.Thread(
                target=self._worker, args=(rep,), daemon=True,
                name="serving-replica-%d" % rep.idx)
            rep.thread.start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="serving-cluster-monitor")
        self._monitor.start()
        # publish the healthy count NOW: the gauges are otherwise
        # first written on traffic, and an autoscaler attached to an
        # idle fresh cluster would read healthy=0 and fire a spurious
        # self-heal scale-up
        if self._obs is not None:
            with self._lock:
                self._sync_gauges_locked()

    @staticmethod
    def _warm_engine(eng):
        """Compile + first-dispatch an engine outside the serving
        clock, then zero the warmup's footprint from its stats."""
        wid = eng.submit(np.ones(1, np.int32), 1)
        eng.run()
        del eng.requests[wid]
        for k in eng.stats:
            eng.stats[k] = type(eng.stats[k])()
        if eng.metrics_enabled:
            eng.reset_metrics()

    # ------------------------------------------------------- intake --
    def submit(self, prompt, max_new_tokens, eos_id=None, ttl_s=None,
               trace_id=None):
        """Queue a request; returns its cluster rid immediately.
        Raises :class:`ClusterOverloaded` when the bounded admission
        queue is full and :class:`ClusterClosed` after close()."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        # validate NOW, in the caller's thread, with the engine's own
        # rules: a request the engines would reject must fail the
        # submit() call, not poison a replica worker later.  Limits
        # are read from the captured spec, not replicas[0] — replica
        # 0 may have been scale-downed (engine released) by now.
        if prompt.size < 1:
            raise ValueError("submit: empty prompt")
        if max_new_tokens < 1:
            raise ValueError("submit: max_new_tokens must be >= 1")
        total = prompt.size + int(max_new_tokens)
        if total > self._max_seq:
            raise ValueError(
                "submit: %d tokens > replica max_seq %d"
                % (total, self._max_seq))
        if total > self._cfg.max_len:
            raise ValueError("submit: %d tokens > cfg.max_len=%d"
                             % (total, self._cfg.max_len))
        keys = chain_keys(prompt, self.page_size) \
            if self.prefix_enabled else []
        with self._lock:
            if self._closed:
                raise ClusterClosed("submit() after close()")
            if not self._healthy():
                if self.scaler_attached:
                    # the autoscaler will restore min capacity —
                    # refuse RETRYABLY, not terminally.  The hint is
                    # RECOVERY-based (the queue-drain formula reads
                    # ~1 ms whenever the queue is shallow, which
                    # would tell clients to hammer a cluster whose
                    # self-heal takes seconds)
                    hint = max(0.05, self.watchdog_s / 4.0)
                    if self._obs is not None:
                        self._obs.rejected.inc()
                        self._obs.g_retry_after.set(hint)
                    raise ClusterOverloaded(
                        "no healthy replicas (self-heal pending); "
                        "retry after %.3fs" % hint,
                        retry_after_s=hint)
                raise ClusterClosed("no healthy replicas")
            waiting = sum(r.waiting for r in self.replicas)
            if waiting >= self.max_queue:
                hint = self._retry_after_locked(waiting)
                if self._obs is not None:
                    self._obs.rejected.inc()
                    self._obs.g_retry_after.set(hint)
                raise ClusterOverloaded(
                    "admission queue full (%d waiting >= max_queue "
                    "%d); retry after %.3fs"
                    % (waiting, self.max_queue, hint),
                    retry_after_s=hint)
            if ttl_s is None:
                ttl_s = self.default_ttl_s
            deadline = None if ttl_s is None \
                else time.perf_counter() + float(ttl_s)
            cr = ClusterRequest(self._next_rid, prompt,
                                int(max_new_tokens), eos_id, deadline,
                                keys, trace_id=trace_id)
            self._next_rid += 1
            self.requests[cr.rid] = cr
            rep = self._route_locked(cr)
            rep.inbox.append(cr)
            cr.replica = rep.idx
            if self._obs is not None:
                self._obs.submitted.inc()
                self._sync_gauges_locked()
            rep.wake.set()
        return cr.rid

    def result(self, rid, timeout=None):
        """Block until the request finishes; returns the full token
        array (prompt + generated).  Raises :class:`RequestExpired` /
        :class:`ClusterFailed` per the terminal state, TimeoutError
        on timeout."""
        cr = self.requests.get(rid)
        if cr is None:
            raise KeyError(
                "result(%d): unknown rid (already collected and "
                "purged past retain_results?)" % rid)
        if not cr.done_evt.wait(timeout):
            raise TimeoutError("result(%d): still running" % rid)
        with self._lock:
            cr.delivered = True
            self._purge_locked()
        if cr.state == "done":
            return cr.output
        if cr.state == "expired":
            raise RequestExpired("request %d expired before "
                                 "admission" % rid)
        if cr.state == "cancelled":
            raise RequestCancelled("request %d was cancelled" % rid)
        raise ClusterFailed("request %d: %r" % (rid, cr.error))

    def drain(self, timeout=None):
        """Wait until every submitted request reaches a terminal
        state.  Returns True if fully drained."""
        deadline = None if timeout is None \
            else time.perf_counter() + timeout
        for cr in list(self.requests.values()):
            left = None if deadline is None \
                else max(0.0, deadline - time.perf_counter())
            if not cr.done_evt.wait(left):
                return False
        return True

    # -------------------------------------------- streaming (rnd 20) --
    def attach_stream(self, rid, cb):
        """Register a per-request token-stream listener (the HTTP
        front door's SSE feed).  ``cb`` receives, in order:
        ``("tokens", [int, ...])`` for each batch of newly committed
        tokens (the backlog is delivered immediately on attach, so a
        late attach never misses tokens), then exactly one terminal
        event — ``("done", output_array)`` or ``("error", exc)``.
        Callbacks run on cluster worker threads under the cluster
        lock: they must be quick and non-blocking (the HTTP bridge
        is one ``call_soon_threadsafe`` enqueue)."""
        with self._lock:
            cr = self.requests.get(rid)
            if cr is None:
                raise KeyError("attach_stream(%d): unknown rid" % rid)
            if cr.stream:
                cb(("tokens", list(cr.stream)))
            if cr.state in ("queued", "running"):
                cr.listeners.append(cb)
            else:
                cr.delivered = True        # terminal event handed out
                cb(self._terminal_event(cr))

    @staticmethod
    def _terminal_event(cr):
        if cr.state == "done":
            return ("done", cr.output)
        if cr.state == "expired":
            return ("error", RequestExpired(
                "request %d expired before admission" % cr.rid))
        if cr.state == "cancelled":
            return ("error", RequestCancelled(
                "request %d was cancelled" % cr.rid))
        return ("error", cr.error if cr.error is not None else
                ClusterFailed("request %d failed" % cr.rid))

    def _publish_tokens_locked(self, cr, ereq=None):
        """Hand listeners every not-yet-published token.  The full
        stream so far is ``committed`` (tokens snapshotted across
        failovers) plus the LIVE incarnation's ``generated`` — the
        published prefix is tracked in ``cr.stream``, so failover
        snapshots (which fold generated into committed) never repeat
        or drop a token."""
        full = list(cr.committed)
        if ereq is not None:
            full.extend(int(t) for t in ereq.generated)
        new = full[len(cr.stream):]
        if new:
            cr.stream.extend(new)
            for cb in cr.listeners:
                cb(("tokens", new))

    def _finish_locked(self, cr):
        """Terminal transition tail shared by every path that ends a
        request: flush any unpublished committed tokens, deliver the
        one terminal stream event, wake ``result()`` waiters.  A
        stream listener receiving the terminal event IS the delivery
        — mark the request delivered so ``_purge_locked`` can bound
        the table (the HTTP path never calls ``result()``; without
        this a long-running front door would grow ``requests`` with
        total traffic served)."""
        self._publish_tokens_locked(cr)
        if cr.listeners:
            cr.delivered = True
        for cb in cr.listeners:
            cb(self._terminal_event(cr))
        cr.listeners = []
        cr.done_evt.set()

    def _publish_running(self, rep):
        """Per-step token publication for this replica's in-flight
        requests (the SSE hot path) — a no-op when nobody listens."""
        with self._lock:
            for erid, cr in rep.in_flight.items():
                if not cr.listeners or cr.state != "running":
                    continue
                self._publish_tokens_locked(
                    cr, rep.engine.requests.get(erid))

    # ---------------------------------------------- cancel (rnd 20) --
    def cancel(self, rid):
        """Cancel a request end-to-end (the HTTP front door's client-
        disconnect propagation; also a chaos action).  A WAITING
        request is dropped immediately; a RUNNING one is flagged and
        its replica worker releases the slot and pages on its own
        thread BEFORE its next engine step (the engine is single-
        threaded state — freeing from here would race the step).
        Returns True if the cancel took (or will take) effect, False
        if the request already reached a terminal state — the
        inherent client race; the finished output stays retrievable."""
        with self._lock:
            cr = self.requests.get(rid)
            if cr is None:
                raise KeyError("cancel(%d): unknown rid" % rid)
            if cr.state not in ("queued", "running"):
                return False
            if cr.state == "queued":
                for rep in self.replicas:
                    try:
                        rep.inbox.remove(cr)
                        break
                    except ValueError:
                        pass
                try:
                    self._orphans.remove(cr)
                except ValueError:
                    pass
                self._cancel_now_locked(cr)
                return True
            cr.cancel_req = True
            rep = self.replicas[cr.replica]
            rep.wake.set()
            return True

    def _cancel_now_locked(self, cr):
        cr.state = "cancelled"
        self._retire_locked(cr)
        if self._obs is not None:
            self._obs.cancelled.inc()
            self._sync_gauges_locked()
        self._finish_locked(cr)

    def _sweep_cancels(self, rep):
        """Apply pending cancels on THIS replica's worker thread,
        between steps: ``engine.cancel`` frees the slot and recycles
        the pages immediately, so a disconnected client's pages are
        back in the pool before the engine's next step completes
        (the round-20 acceptance criterion, asserted via pool gauges
        in ``tests/test_http_frontend.py``)."""
        with self._lock:
            pend = [(erid, cr) for erid, cr in rep.in_flight.items()
                    if cr.cancel_req and cr.state == "running"]
            for erid, cr in pend:
                del rep.in_flight[erid]
                ereq = rep.engine.requests.get(erid)
                if ereq is not None:
                    # fold the live incarnation's tokens into the
                    # committed log (the failover snapshot fold) so
                    # the cancelled request's partial output is
                    # checkable against the oracle as a strict
                    # prefix, not an empty list
                    cr.committed.extend(int(t)
                                        for t in list(ereq.generated))
                    cr.token_times.extend(ereq.token_times)
                    rep.engine.cancel(erid)
                    del rep.engine.requests[erid]
                self._cancel_now_locked(cr)

    # ------------------------------------------------------ routing --
    def _healthy(self):
        return [r for r in self.replicas
                if r.alive and not r.draining]

    def _route_locked(self, cr):
        healthy = self._healthy()
        if not healthy:
            raise ClusterClosed("no healthy replicas")
        min_wait = min(r.waiting for r in healthy)
        target = None
        # longest registered prefix wins (iterate deepest-first)
        for key in reversed(cr.affinity_keys):
            idx = self._affinity.get(key)
            if idx is None:
                continue
            rep = self.replicas[idx]
            if rep.alive and not rep.draining \
                    and rep.waiting <= min_wait + self.affinity_slack:
                target = rep
                self._affinity.move_to_end(key)
                if self._obs is not None:
                    self._obs.routed_affinity.inc()
                break
        if target is None:
            target = min(healthy, key=lambda r: (r.load, r.idx))
            if self._obs is not None:
                self._obs.routed_least.inc()
        for key in cr.affinity_keys:
            self._affinity[key] = target.idx
            self._affinity.move_to_end(key)
        while len(self._affinity) > self._affinity_cap:
            self._affinity.popitem(last=False)
        return target

    def _retry_after_locked(self, waiting):
        """Retry-After hint for a rejected submit(): the time for the
        queue excess over the admission bound (plus one average
        request) to drain at the cluster's recent completion rate.
        With no completions observed yet the hint falls back to one
        watchdog quarter — short enough to retry soon, long enough to
        not hammer a cluster that is still compiling."""
        now = time.perf_counter()
        comp = self._completions
        # age out stale samples: a rate computed across an idle gap
        # would hand a busy-again cluster an hours-long hint
        horizon = now - max(5.0, self.watchdog_s)
        while comp and comp[0] < horizon:
            comp.popleft()
        if len(comp) >= 2 and now > comp[0]:
            # len-1 completion INTERVALS over the observed span —
            # conservatively low rate, conservatively long hint.
            # Clamped ABOVE by the watchdog (round-20 small fix): a
            # stalled or barely-completing cluster must not advertise
            # a multi-hour hint — within one watchdog the cluster has
            # either failed over and drained or the client should
            # probe again regardless
            rate = (len(comp) - 1) / (now - comp[0])
            excess = waiting - self.max_queue + 1
            return min(self.watchdog_s,
                       max(0.001, excess / max(rate, 1e-6)))
        return max(0.001, self.watchdog_s / 4.0)

    def _retire_locked(self, cr):
        """Bound the request table: remember terminal rids in order
        and drop the oldest DELIVERED ones past ``retain_results`` — a
        long-running cluster must not grow memory with total traffic
        served, but a finished result the client has not yet collected
        is never purged out from under its pending result() call."""
        self._terminal.append(cr.rid)
        self._purge_locked()

    def _purge_locked(self):
        excess = len(self._terminal) - self._retain
        if excess <= 0:
            return
        kept: "collections.deque[int]" = collections.deque()
        for rid in self._terminal:
            req = self.requests.get(rid)
            if excess > 0 and (req is None or req.delivered):
                excess -= 1
                if req is not None:
                    del self.requests[rid]
            else:
                kept.append(rid)
        self._terminal = kept

    def _sync_gauges_locked(self):
        obs = self._obs
        if obs is None:
            return
        obs.g_healthy.set(len(self._healthy()))
        obs.g_waiting.set(sum(r.waiting for r in self.replicas))
        obs.g_in_flight.set(
            sum(len(r.in_flight) for r in self.replicas))

    # ------------------------------------------------------- worker --
    def _worker(self, rep):
        eng = rep.engine
        while True:
            rep.heartbeat = time.perf_counter()
            if rep.dead:
                return
            try:
                self._pump_inbox(rep)
                self._sweep_cancels(rep)
                finished = eng.step()
            except Exception as e:                  # replica death
                self._fail_replica(rep, e)
                return
            rep.heartbeat = time.perf_counter()
            if finished is not False:
                self._publish_running(rep)
            if finished is False:
                with self._lock:
                    idle = not rep.inbox and not rep.in_flight
                    if idle and (rep.draining or self._closed):
                        rep.alive = False
                        rep.drained_evt.set()
                        self._sync_gauges_locked()
                        return
                rep.wake.wait(timeout=0.02)
                rep.wake.clear()
            elif finished:
                for erid in finished:
                    self._complete(rep, erid)

    def _pump_inbox(self, rep):
        """Move waiting requests into the engine, bounded to one
        engine-queue's worth of backlog so TTL expiry keeps meaning
        (a request buried in an unbounded engine queue could never be
        expired — the engine queue is this thread's, the inbox is the
        cluster's)."""
        eng = rep.engine
        while True:
            with self._lock:
                if not rep.inbox or rep.dead:
                    return
                if len(eng._queue) >= self.num_slots:
                    return
                cr = rep.inbox.popleft()
                if cr.cancel_req:
                    # cancelled while queued on a failover/drain
                    # reroute path (a directly-queued cancel leaves
                    # the inbox inside cancel() itself)
                    self._cancel_now_locked(cr)
                    continue
                now = time.perf_counter()
                if cr.deadline is not None and now > cr.deadline \
                        and not cr.committed:
                    cr.state = "expired"
                    self._retire_locked(cr)
                    if self._obs is not None:
                        self._obs.expired.inc()
                        self._sync_gauges_locked()
                    self._finish_locked(cr)
                    continue
                prompt = cr.prompt if not cr.committed else \
                    np.concatenate([cr.prompt,
                                    np.asarray(cr.committed,
                                               np.int32)])
                try:
                    erid = eng.submit(
                        prompt, cr.max_new_tokens - len(cr.committed),
                        eos_id=cr.eos_id, trace_id=cr.trace_id)
                except Exception as e:
                    # a request THIS engine rejects (submit() already
                    # pre-validated, so this is belt-and-braces) fails
                    # alone — it must not take the worker down
                    cr.state = "failed"
                    cr.error = e
                    self._retire_locked(cr)
                    self._finish_locked(cr)
                    continue
                cr.state = "running"
                cr.replica = rep.idx
                cr.engine_rid = erid
                rep.in_flight[erid] = cr
                if self._obs is not None:
                    self._sync_gauges_locked()

    def _complete(self, rep, erid):
        with self._lock:
            cr = rep.in_flight.pop(erid, None)
            if cr is None or rep.dead:
                return                      # fenced zombie completion
            if cr.state != "running" or cr.replica != rep.idx \
                    or cr.engine_rid != erid:
                return
            ereq = rep.engine.requests[erid]
            if cr.cancel_req:
                # a cancel raced the finishing step: cancel() already
                # returned True, so cancel WINS (the same rule the
                # failover path applies — the client is gone and the
                # finished output has no collector).  Fold the
                # generated tokens so the oracle prefix checks and a
                # late stream attach see the truth, then retire as
                # cancelled — cluster_cancelled_total must agree with
                # every True cancel() or the bench reconciliation
                # breaks
                cr.committed.extend(int(t)
                                    for t in list(ereq.generated))
                cr.token_times.extend(ereq.token_times)
                del rep.engine.requests[erid]
                self._cancel_now_locked(cr)
                return
            self._publish_tokens_locked(cr, ereq)
            cr.output = ereq.output
            cr.state = "done"
            cr.token_times.extend(ereq.token_times)
            self._completions.append(time.perf_counter())
            if cr.first_token_t is None and ereq.token_times:
                cr.first_token_t = ereq.token_times[0]
            # the engine-side record (prompt/generated/output arrays)
            # is fully copied out — drop it so a long-running replica
            # does not accumulate one Request per request ever served
            del rep.engine.requests[erid]
            self._retire_locked(cr)
            if self._obs is not None:
                self._obs.completed.inc()
                if cr.first_token_t is not None:
                    self._obs.h_ttft.observe(
                        (cr.first_token_t - cr.submit_t) * 1e3)
                self._sync_gauges_locked()
            self._finish_locked(cr)

    # ----------------------------------------------------- failover --
    def _fail_replica(self, rep, error):
        """Drain a dead/stalled replica: mark it out of rotation and
        resubmit its waiting + in-flight requests to survivors via the
        recompute-exact resume path.  Idempotent under the lock (the
        worker's own exception path and the monitor's watchdog can
        race here)."""
        with self._lock:
            if rep.dead:
                return
            rep.dead = True
            rep.alive = False
            rep.error = error
            strays = list(rep.inbox)
            rep.inbox.clear()
            in_flight = list(rep.in_flight.items())
            rep.in_flight.clear()
            obs = self._obs
            if obs is not None:
                obs.failovers.inc()
            tracing = obs is not None and profiler.is_recording()
            now = time.perf_counter()
            survivors = self._healthy()
            for erid, cr in in_flight:
                # snapshot committed tokens (greedy determinism makes
                # any snapshot point exact: the resumed run regenerates
                # the continuation identically)
                ereq = rep.engine.requests.get(erid)
                if ereq is not None:
                    cr.committed.extend(int(t)
                                        for t in list(ereq.generated))
                    cr.token_times.extend(ereq.token_times)
                    if cr.first_token_t is None and ereq.token_times:
                        cr.first_token_t = ereq.token_times[0]
                cr.failovers += 1
                if tracing:
                    obs.trace.add_instant(
                        cr.rid, "failover", now,
                        args={"replica": rep.idx,
                              "committed": len(cr.committed)})
            for cr in strays + [cr for _, cr in in_flight]:
                if cr.state not in ("queued", "running"):
                    continue
                if cr.cancel_req:
                    # a cancel raced the failover: the client is gone
                    # — cancel beats resubmission (recomputing a
                    # disconnected request's tokens on a survivor
                    # would be pure waste)
                    self._cancel_now_locked(cr)
                    continue
                done = (cr.eos_id is not None
                        and cr.eos_id in cr.committed) or \
                    len(cr.committed) >= cr.max_new_tokens
                if done:
                    cr.output = np.concatenate(
                        [cr.prompt,
                         np.asarray(cr.committed, np.int32)])
                    cr.state = "done"
                    self._completions.append(now)
                    self._retire_locked(cr)
                    if obs is not None:
                        obs.completed.inc()
                    self._finish_locked(cr)
                    continue
                cr.state = "queued"
                cr.engine_rid = None
                if not survivors:
                    if self.scaler_attached and not self._closed:
                        # round 16: the zero-replica state is
                        # recoverable (the autoscaler self-heals
                        # below min_size) — PARK the request;
                        # add_replica reroutes it when capacity
                        # returns, close() fails it if none ever does
                        self._orphans.append(cr)
                        continue
                    cr.state = "failed"
                    cr.error = error
                    self._retire_locked(cr)
                    self._finish_locked(cr)
                    continue
                target = self._route_locked(cr)
                target.inbox.append(cr)
                cr.replica = target.idx
                target.wake.set()
                if obs is not None:
                    obs.resubmitted.inc()
                    if tracing:
                        obs.trace.add_instant(
                            cr.rid, "resubmit", now,
                            args={"replica": target.idx})
            if tracing:
                obs.trace.flush()
            if obs is not None:
                self._sync_gauges_locked()

    def _monitor_loop(self):
        period = max(0.01, min(0.25, self.watchdog_s / 4.0))
        while True:
            time.sleep(period)
            with self._lock:
                if self._closed and all(not r.alive
                                        for r in self.replicas):
                    return
                now = time.perf_counter()
                stalled = [
                    r for r in self.replicas
                    if r.alive and not r.dead
                    and (r.in_flight or r.inbox)
                    and now - r.heartbeat > self.watchdog_s]
            for rep in stalled:
                self._fail_replica(
                    rep, RuntimeError(
                        "replica %d stalled past watchdog %.3fs"
                        % (rep.idx, self.watchdog_s)))

    # ---------------------------------------------- drain/scale-down --
    def drain_replica(self, idx, timeout=None):
        """Graceful scale-down of one replica: stop routing to it,
        reroute its waiting requests, let in-flight requests finish,
        park the worker.  Returns True once drained."""
        rep = self.replicas[idx]
        with self._lock:
            rep.draining = True
            strays = list(rep.inbox)
            rep.inbox.clear()
            for cr in strays:
                if cr.state != "queued":
                    continue
                target = self._route_locked(cr)
                target.inbox.append(cr)
                cr.replica = target.idx
                target.wake.set()
            if self._obs is not None:
                self._sync_gauges_locked()
        rep.wake.set()
        return rep.drained_evt.wait(timeout)

    # ------------------------------------------------- scale-up/down --
    def add_replica(self):
        """Scale-up actuation (round 16, driven by
        ``serving/autoscaler.py``): build ONE more engine replica from
        the captured ``_engine_kwargs`` and put it in rotation.
        Engine construction + pre-warm run OUTSIDE the cluster lock
        (the step program is already compiled — the cost is params
        placement and one cached-program dispatch); only the rid-block
        reservation and the rotation append hold it.  Returns the new
        replica index."""
        with self._lock:
            if self._closed:
                raise ClusterClosed("add_replica() after close()")
            block = self._rid_blocks
            self._rid_blocks += 1
        eng = ServingEngine(self._params, self._cfg,
                            rid_start=block * RID_BLOCK,
                            **self._engine_kwargs)
        self._warm_engine(eng)
        with self._lock:
            idx = None if self._closed else len(self.replicas)
            if idx is not None:
                rep = _Replica(idx, eng)
                self.replicas.append(rep)
                rep.thread = threading.Thread(
                    target=self._worker, args=(rep,), daemon=True,
                    name="serving-replica-%d" % idx)
                rep.thread.start()
                # requests stranded by a total-loss failover ride the
                # new capacity (recompute-exact resume, committed
                # tokens already snapshotted by _fail_replica)
                while self._orphans:
                    cr = self._orphans.popleft()
                    if cr.state != "queued":
                        continue
                    target = self._route_locked(cr)
                    target.inbox.append(cr)
                    cr.replica = target.idx
                    target.wake.set()
                    if self._obs is not None:
                        self._obs.resubmitted.inc()
                if self._obs is not None:
                    self._obs.scale_ups.inc()
                    self._sync_gauges_locked()
        if idx is None:
            # lost the race with close(): release the freshly built,
            # never-published engine's cache-owned state before
            # abandoning it to GC (the rid block is just a counter)
            if eng.prefix is not None:
                eng.prefix.clear()
            raise ClusterClosed("add_replica() after close()")
        return idx

    def remove_replica(self, idx=None, timeout=None):
        """Scale-down actuation: gracefully drain one replica (the
        least-loaded healthy one unless ``idx`` names it), verify it
        leaked nothing, and release its KV pool.  Never removes the
        last healthy replica.  Returns the removed index, or None if
        no replica was eligible / the drain timed out.

        The zero-leak contract is CHECKED, not assumed: after the
        drain the replica's prefix cache must hold zero refs, and
        clearing it must return the pool to zero pages in use —
        anything else raises RuntimeError (a page leak found at
        scale-down is a bug, not an operational event)."""
        with self._lock:
            healthy = self._healthy()
            if len(healthy) <= 1:
                return None
            if idx is None:
                idx = min(healthy, key=lambda r: (r.load, -r.idx)).idx
            elif not any(r.idx == idx for r in healthy):
                return None
        if not self.drain_replica(idx, timeout) \
                and not self.replicas[idx].drained_evt.is_set():
            # timed out with work still in flight: back in rotation
            # (mirrors drain_worker) — leaving draining set would
            # silently shrink capacity without ever releasing the
            # replica
            with self._lock:
                rep = self.replicas[idx]
                if rep.alive and not rep.dead:
                    rep.draining = False
                    rep.wake.set()
            return None
        rep = self.replicas[idx]
        eng = rep.engine
        leaked_refs = 0 if eng.prefix is None else eng.prefix.refs_total
        if eng.prefix is not None:
            eng.prefix.clear()
        in_use = eng.cache.pages_in_use
        if leaked_refs or in_use:
            raise RuntimeError(
                "remove_replica(%d): %d prefix refs / %d pages still "
                "held after drain — scale-down would leak" %
                (idx, leaked_refs, in_use))
        eng.close()                       # retire any planner thread
        with self._lock:
            rep.dead = True               # waiting -> 0, never routed
            rep.engine = None             # release pools/params refs
            if self._obs is not None:
                self._obs.scale_downs.inc()
                self._sync_gauges_locked()
        return idx

    def detach_scaler(self):
        """The attached autoscaler is going away: requests parked for
        a self-heal that will now never come must fail loudly instead
        of hanging their result() waiters forever."""
        with self._lock:
            self.scaler_attached = False
            while self._orphans:
                cr = self._orphans.popleft()
                if cr.state != "queued":
                    continue
                cr.state = "failed"
                cr.error = ClusterFailed(
                    "request %d: parked for scale-up but the "
                    "autoscaler detached" % cr.rid)
                self._retire_locked(cr)
                self._finish_locked(cr)

    # the autoscaler's actuation protocol (shared with
    # DisaggServingCluster): scale_up() -> bool, scale_down() -> bool
    def scale_up(self):
        self.add_replica()
        return True

    def scale_down(self, timeout=60.0):
        return self.remove_replica(timeout=timeout) is not None

    @property
    def slots_per_replica(self):
        return self.num_slots

    def close(self, timeout=None):
        """Drain every replica and stop the monitor.  In-flight work
        finishes first (the watchdog still covers a replica that
        stalls during shutdown)."""
        with self._lock:
            self._closed = True
            # parked orphans will never see new capacity now
            while self._orphans:
                cr = self._orphans.popleft()
                if cr.state != "queued":
                    continue
                cr.state = "failed"
                cr.error = ClusterClosed(
                    "cluster closed with the request parked for "
                    "scale-up")
                self._retire_locked(cr)
                self._finish_locked(cr)
        for rep in self.replicas:
            rep.wake.set()
        for rep in self.replicas:
            if rep.thread is not None:
                rep.thread.join(timeout)
        for rep in self.replicas:
            if rep.engine is not None:
                # overlap engines carry a planner thread; join it out
                rep.engine.close()
        self._monitor.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    # --------------------------------------------------- accounting --
    def health(self):
        """Per-replica health snapshot (the health-check surface)."""
        now = time.perf_counter()
        with self._lock:
            return [{"replica": r.idx, "alive": r.alive,
                     "draining": r.draining, "dead": r.dead,
                     "load": r.load, "waiting": r.waiting,
                     "in_flight": len(r.in_flight),
                     "heartbeat_age_s": now - r.heartbeat,
                     "error": repr(r.error) if r.error else None}
                    for r in self.replicas]

    def debug_status(self):
        """Ops introspection snapshot for ``GET /debug/statusz``
        (round 23) — the in-process flavor's counterpart of
        :meth:`DisaggServingCluster.debug_status`: live topology plus
        in-flight request states.  JSON-able, read-only."""
        now = time.perf_counter()
        with self._lock:
            reqs = []
            for cr in self.requests.values():
                if cr.state not in ("queued", "running"):
                    continue
                reqs.append({
                    "rid": cr.rid, "trace_id": cr.trace_id,
                    "state": cr.state, "replica": cr.replica,
                    # the canonical PUBLISHED stream length — survives
                    # failover (committed snapshots + live tokens)
                    "tokens": len(cr.stream),
                    "failovers": cr.failovers,
                    "ttft_ms": None if cr.first_token_t is None
                    else (cr.first_token_t - cr.submit_t) * 1e3,
                    "age_s": now - cr.submit_t})
            return {"kind": "inproc", "closed": self._closed,
                    "replicas": self.health(), "requests": reqs}

    @property
    def registry(self):
        return self._obs.registry if self._obs is not None else None

    def metrics(self):
        """JSON-able snapshot: router counters + per-replica engine
        snapshots."""
        if self._obs is None:
            return {"enabled": False}
        snap = self._obs.registry.snapshot()
        snap["enabled"] = True
        snap["replicas"] = [r.engine.metrics() for r in self.replicas
                            if r.engine is not None]
        return snap


# ===========================================================================
# Disaggregated prefill/decode serving (round 15): cross-PROCESS
# replicas streaming int8 KV pages, with a cluster-level prefix index.
# ===========================================================================

class _DisaggObs:
    """Router-side instrument bundle for the disaggregated cluster."""

    _seq = [0]

    def __init__(self, registry=None):
        from .. import obs as O
        if registry is None:
            registry = O.MetricsRegistry(
                labels={"disagg": str(self._seq[0])})
            self._seq[0] += 1
            O.register_engine_registry(registry)
        self.registry = registry
        c, g, h = registry.counter, registry.gauge, registry.histogram
        self.submitted = c("cluster_requests_submitted_total",
                           "requests accepted by cluster submit()")
        self.cancelled = c("cluster_cancelled_total",
                           "requests cancelled via cancel(rid) — "
                           "client disconnects propagated by the "
                           "HTTP front door, plus chaos 'cancel' "
                           "actions")
        self.completed = c("cluster_requests_completed_total",
                           "requests finished across all workers")
        self.failovers = c("cluster_failovers_total",
                           "worker-process failures (SIGKILL, crash, "
                           "or watchdog stall) failed over")
        self.resubmitted = c("cluster_requests_resubmitted_total",
                             "requests resubmitted after a worker "
                             "death (recompute-exact resume)")
        self.page_bytes = c("cluster_page_bytes_streamed_total",
                            "KV page bytes moved between worker "
                            "processes (prefill->decode streams + "
                            "peer prefix fetches)")
        self.pages_streamed = c("cluster_pages_streamed_total",
                                "KV pages moved between worker "
                                "processes")
        self.remote_hits = c("serving_prefix_remote_hits_total",
                             "prefix chains fetched from another "
                             "replica instead of re-prefilled")
        self.remote_hit_tokens = c(
            "serving_prefix_remote_hit_tokens_total",
            "prompt tokens whose prefill was skipped via a REMOTE "
            "prefix fetch")
        self.g_workers = g("cluster_workers_healthy",
                           "worker processes accepting traffic")
        self.g_in_flight = g("cluster_in_flight",
                             "requests not yet terminal")
        self.h_ttft = h("cluster_ttft_ms",
                        help="cluster submit() -> first committed "
                             "token seen at the router")
        self.h_transfer = h("cluster_page_transfer_ms",
                            help="page-frame send -> installed in the "
                                 "decode pool (same-host monotonic "
                                 "clock)")
        # round 23: router-lane request spans (submit instant, TTFT
        # span) in the same merged chrome trace the worker spans land
        # in — the router process IS the recording process
        from ..obs.trace import RequestTraceEmitter
        self.trace = RequestTraceEmitter()


class _WorkerHandle:
    """Router-side record of one worker process."""
    __slots__ = ("name", "role", "proc", "conn", "data_host",
                 "data_port", "last_seen", "dead", "draining",
                 "outstanding", "stats", "stats_evt", "stats_sid",
                 "error", "recv_thread", "pid", "clock_offset",
                 "clock_rtt", "flight_tail")

    def __init__(self, name, role):
        self.name = name
        self.role = role
        self.proc = None
        self.pid = None                   # from hello (put-segment sweep)
        self.conn = None
        self.data_host = None
        self.data_port = None
        self.last_seen = time.perf_counter()
        self.dead = False
        self.draining = False
        self.outstanding = set()          # rids currently assigned
        self.stats: Dict = {}
        self.stats_evt = threading.Event()
        self.stats_sid = None             # awaited stats_req id
        self.error = None
        self.recv_thread = None
        # round 23: ping-pong clock model (worker perf_counter minus
        # router perf_counter, min-RTT sample) — corrects this
        # worker's shipped span times onto the router timeline
        self.clock_offset = 0.0
        self.clock_rtt = None             # best (lowest) RTT seen, s
        # round 23: recovered flight-recorder tail after this worker
        # died (the post-mortem evidence _fail_worker pulled from its
        # crash-durable ring)
        self.flight_tail = None

    @property
    def alive(self):
        return not self.dead and self.conn is not None


class DisaggRequest:
    """Router-side request record for the disaggregated cluster.
    ``committed`` is fed by the token stream from whichever worker is
    running the request — it is the failover snapshot (a SIGKILLed
    worker's memory is gone; only streamed tokens survive)."""
    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_id", "state",
                 "phase", "prefill", "decode", "gen", "committed",
                 "output", "error", "done_evt", "submit_t",
                 "first_token_t", "token_times", "failovers",
                 "delivered", "listeners", "trace_id")

    def __init__(self, rid, prompt, max_new_tokens, eos_id,
                 trace_id=None):
        self.rid = rid
        # round 23 trace context: minted at the HTTP edge (the
        # X-Request-Id) or defaulted here; carried in the meta of
        # every request-bearing wire kind and stamped on every span
        self.trace_id = trace_id if trace_id is not None \
            else "rid%d" % rid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.state = "running"            # running|done|failed
        self.phase = "prefill"            # prefill|decode
        self.prefill: Optional[str] = None
        self.decode: Optional[str] = None
        self.gen = 0                      # incarnation fence
        self.committed: List[int] = []
        self.output: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.done_evt = threading.Event()
        self.submit_t = time.perf_counter()
        self.first_token_t: Optional[float] = None
        # router-side arrival time of each streamed token (tokens in
        # one frame share a timestamp) — the goodput classifier's view
        self.token_times: List[float] = []
        self.failovers = 0
        self.delivered = False
        # round 20: attach_stream listeners.  ``committed`` IS the
        # canonical stream here (it only grows, at the router, under
        # the router lock) — no separate published-prefix bookkeeping
        self.listeners: List = []


class DisaggServingCluster:
    """Disaggregated prefill/decode serving across OS processes.

    The router (this object, in the calling process) spawns
    ``prefill`` + ``decode`` worker processes (``multiprocessing``
    spawn — real pids, SIGKILL-able), ships each the model params and
    engine config over the transport at handshake, and then routes:
    every request runs chunked prefill on a prefill worker (its
    engine capped at one sampled token), whose finished KV pages
    stream to the request's decode worker pipelined with the prefill
    chunks; the decode worker installs the pages, admits the request
    at ``n_cached = prompt_len`` via ``engine.admit_prefilled``, and
    streams committed tokens back to the router.

    * **Cluster-level prefix reuse** — the router owns a
      :class:`prefix_cache.ClusterPrefixIndex`; submit() attaches a
      hint naming the replica holding the longest cached chain, and
      the prefill worker fetches those pages peer-to-peer (raw int8
      page bytes) instead of recomputing them.  A hot prefix is
      prefilled once per CLUSTER; ``serving_prefix_remote_hits_total``
      / ``cluster_page_bytes_streamed_total`` measure it.
    * **Failover** — a worker that dies (SIGKILL, crash, socket loss)
      or stalls past ``watchdog_s`` is failed over: its requests
      resubmit to survivors with the router's streamed ``committed``
      tokens as prompt extension (recompute-exact; f32-greedy output
      is token-identical to an undisturbed run), fenced by per-request
      incarnation numbers so a zombie's late frames never land.
    * **Exactness** — prefill and decode run the SAME compiled step
      program config; pages transfer as exact pool bytes.  Under f32
      greedy the cluster output is bit-identical to single-engine
      ``generate`` (pinned by ``tests/test_serving_disagg.py``).

    Off-host scale-out uses the same protocol: pass ``spawn=False``
    and start workers via ``tools/launch.py --launcher serve`` (or
    ``run_worker()`` with ``MXNET_SERVE_*`` env) on any reachable
    host.
    """

    def __init__(self, params, cfg, *, prefill=1, decode=1,
                 num_slots, page_size=16, num_pages=None,
                 pages_per_slot=None, prefill_chunk=8, kv_int8=False,
                 kernel="xla", spec_K=0, metrics=None, registry=None,
                 watchdog_s=None, spawn=True, host="127.0.0.1",
                 port=0, ready_timeout=None, tier_bytes=None,
                 overlap=None):
        if prefill < 1 or decode < 1:
            raise ValueError("DisaggServingCluster: needs >= 1 "
                             "prefill and >= 1 decode worker")
        if watchdog_s is None:
            watchdog_s = _env_default("MXNET_SERVE_WATCHDOG_S", 30.0)
        if ready_timeout is None:
            ready_timeout = _env_default(
                "MXNET_SERVE_READY_TIMEOUT_S", 120.0)
        self.cfg = cfg
        self.page_size = page_size
        self.watchdog_s = float(watchdog_s)
        self._spawn = bool(spawn)
        self._engine_kwargs = dict(
            num_slots=num_slots, page_size=page_size,
            num_pages=num_pages, pages_per_slot=pages_per_slot,
            prefill_chunk=prefill_chunk, kv_int8=kv_int8,
            kernel=kernel, spec_K=spec_K, tier_bytes=tier_bytes,
            overlap=overlap)
        # mirror of the workers' engine limits, so an invalid request
        # fails the submit() call instead of poisoning a worker
        pps = pages_per_slot if pages_per_slot is not None \
            else -(-cfg.max_len // page_size)
        self._max_seq = min(pps * page_size, cfg.max_len)
        if metrics is None:
            metrics = registry is not None or \
                os.environ.get("MXNET_SERVING_METRICS", "0") == "1"
        self._obs = _DisaggObs(registry) if metrics else None
        self._lock = threading.RLock()
        self._closed = False
        self._next_rid = 0
        self.requests: Dict[int, DisaggRequest] = {}
        # terminal requests are retained up to this many, then the
        # oldest DELIVERED ones drop — a long-running router must not
        # grow its request table with total traffic served (the same
        # contract as ServingCluster.retain_results)
        self._retain = 4096
        self._terminal: "collections.deque[int]" = collections.deque()
        self.index = ClusterPrefixIndex()
        # hellos from workers that connected while another worker's
        # add_worker handshake was draining the accept queue
        self._early_hellos: Dict[str, object] = {}
        self._rr = [0, 0]                 # round-robin cursors
        # worker-reported cumulative stats, delta-folded into the
        # router registry (same idiom as _EngineObs.sync_cache)
        self._stat_seen: Dict[str, Dict[str, float]] = {}
        # -- round 23 observability state ---------------------------
        # router-side crash-durable flight ring (workers get their
        # own in-process), merged cross-process trace emitter, the
        # per-rid span store behind GET /debug/trace/<rid>, and the
        # TTFT sliding window behind the statusz SLO burn gauges
        from ..obs.flight import FlightRecorder
        from ..obs.trace import MergedTraceEmitter
        self._flight = FlightRecorder()
        self._merged = MergedTraceEmitter()   # internally locked
        self._span_store: "collections.OrderedDict[int, list]" = \
            collections.OrderedDict()
        self._span_store_cap = 512
        self._flight_tails: Dict[str, list] = {}
        self._clock_seq = itertools.count(1)
        self._ttft_window: "collections.deque" = collections.deque()
        self._slo_ttft_ms = _env_default(
            "MXNET_SERVE_SLO_TTFT_MS", 1000.0)
        self.workers: Dict[str, _WorkerHandle] = {}
        # pre-provisioned standby workers (round 18): fully handshaken
        # (engine built + pre-warmed) but held out of routing AND out
        # of the healthy-capacity gauge until scale_up() adopts them —
        # burst capacity priced at a peer-map flip, not at
        # process-spawn + jax import + compile
        self._standby: set = set()
        for i in range(prefill):
            self.workers["prefill%d" % i] = _WorkerHandle(
                "prefill%d" % i, "prefill")
        for i in range(decode):
            self.workers["decode%d" % i] = _WorkerHandle(
                "decode%d" % i, "decode")

        from .transport import Listener, tree_to_frames
        import jax
        # port: 0 lets the OS pick (the spawned-worker path); an
        # external launcher (tools/launch.py --launcher serve) picks
        # the port up front and hands it to both sides via env
        self._listener = Listener(host=host, port=port)
        self._pending_conns: "queue.Queue" = queue.Queue()
        self._listener.start(self._pending_conns.put)
        host_params = jax.device_get(params)
        self._params_frames = tree_to_frames(host_params)
        if spawn:
            import multiprocessing as mp
            ctx = mp.get_context("spawn")
            for name, wh in self.workers.items():
                wh.proc = ctx.Process(
                    target=_disagg_worker_entry,
                    args=(name, wh.role, self._listener.host,
                          self._listener.port),
                    daemon=True, name="serving-" + name)
                wh.proc.start()
        try:
            self._handshake_all(ready_timeout)
        except BaseException:
            # a failed construction must not strand live worker
            # processes (each holding an engine) or the bound
            # listener — the caller never gets an object to close()
            for wh in self.workers.values():
                if wh.proc is not None and wh.proc.is_alive():
                    wh.proc.terminate()
                    # reap: a SIGTERMed child stays a zombie pid
                    # until joined (py-resource-lifecycle)
                    wh.proc.join(timeout=5)
                if wh.conn is not None:
                    wh.conn.close()
            self._listener.close()
            raise
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="disagg-monitor")
        self._monitor.start()

    # --------------------------------------------------- handshake ---
    def _handshake_all(self, timeout):
        deadline = time.perf_counter() + timeout
        need = {n for n in self.workers}
        while need:
            left = deadline - time.perf_counter()
            if left <= 0:
                raise RuntimeError(
                    "DisaggServingCluster: workers %s never connected"
                    % sorted(need))
            try:
                conn = self._pending_conns.get(timeout=min(left, 1.0))
            except queue.Empty:
                continue
            got = conn.recv(timeout=left)
            if got in (None, "timeout"):
                conn.close()
                continue
            kind, meta, _ = got
            if kind != "hello" or meta.get("name") not in need:
                conn.close()
                continue
            name = meta["name"]
            wh = self.workers[name]
            wh.conn = conn
            wh.pid = meta.get("pid")
            pm, pb = self._params_frames
            conn.send("config",
                      {"cfg": self.cfg, "role": wh.role,
                       "engine_kwargs": self._engine_kwargs,
                       "params_meta": pm,
                       "watchdog_s": self.watchdog_s}, pb)
            need.discard(name)
        # collect READY (with data ports) from everyone
        for name, wh in self.workers.items():
            got = wh.conn.recv(timeout=max(
                1.0, deadline - time.perf_counter()))
            if got in (None, "timeout") or got[0] != "ready":
                raise RuntimeError(
                    "DisaggServingCluster: worker %s failed to build "
                    "its engine (%r)" % (name, got))
            _, meta, _ = got
            wh.data_host = meta["data_host"]
            wh.data_port = meta["data_port"]
            wh.last_seen = time.perf_counter()
        peers = {n: {"role": w.role, "host": w.data_host,
                     "port": w.data_port}
                 for n, w in self.workers.items()}
        for wh in self.workers.values():
            wh.conn.send("peers", {"peers": peers})
            wh.recv_thread = threading.Thread(
                target=self._recv_loop, args=(wh,), daemon=True,
                name="disagg-recv-" + wh.name)
            wh.recv_thread.start()
        # clock-offset ping burst AFTER recv threads start: the
        # worker is in its run() loop by now, so replies ride the
        # normal inbox->_handle->send path and land in _recv_loop
        for wh in self.workers.values():
            self._clock_ping(wh)
        if self._obs is not None:
            self._obs.g_workers.set(self._serving_count())

    def _clock_ping(self, wh, n=5):
        """Ping-pong clock-offset burst (round 23): each ``clock_req``
        echoes back with the worker's ``perf_counter`` read; the
        min-RTT sample (``_on_clock``) estimates this worker's clock
        offset from the router.  Same-host workers share
        CLOCK_MONOTONIC, so the estimate validates at ~0 there and
        becomes load-bearing for off-host workers."""
        for _ in range(n):
            try:
                wh.conn.send("clock_req",
                             {"seq": next(self._clock_seq),
                              "t0": time.perf_counter()})
            except OSError:
                return                    # monitor will fail it over

    def _serving_count(self):
        """Workers counted as serving capacity: alive and not parked
        as standby (a standby worker is warm but deliberately invisible
        to the autoscaler's healthy gauge — counting it would tell the
        scaler the capacity is already deployed)."""
        return sum(w.alive and w.name not in self._standby
                   for w in self.workers.values())

    # ------------------------------------------------- router recv ---
    def _recv_loop(self, wh):
        while True:
            got = wh.conn.recv()
            if got is None:
                with self._lock:
                    closed = self._closed or wh.dead
                if not closed:
                    self._fail_worker(wh, RuntimeError(
                        "worker %s: connection lost (process died?)"
                        % wh.name))
                return
            kind, meta, bufs = got
            wh.last_seen = time.perf_counter()
            if kind == "tokens":
                self._on_tokens(wh, meta)
            elif kind == "handed":
                self._on_handed(wh, meta)
            elif kind == "done":
                self._on_done(wh, meta)
            elif kind == "lost":
                self._on_lost(wh, meta)
            elif kind == "insert":
                self.index.report_insert(wh.name, meta["keys"])
            elif kind == "evict":
                self.index.report_evict(wh.name, meta["keys"])
            elif kind == "tier":
                # round 18: chains moved between the worker's tiers
                # (spill hbm->host / warm restore host->hbm) — re-tag,
                # never forget: a spilled chain is still fetchable
                self.index.report_tier(wh.name, meta["keys"],
                                       meta["tier"])
            elif kind == "stats":
                self._on_stats(wh, meta)
            elif kind == "spans":
                self._on_spans(wh, meta)
            elif kind == "clock":
                self._on_clock(wh, meta)
            elif kind == "reqfail":
                with self._lock:
                    cr = self.requests.get(meta["rid"])
                    if cr is not None and cr.gen == meta["gen"] \
                            and cr.state == "running":
                        cr.state = "failed"
                        cr.error = RuntimeError(meta.get("msg", ""))
                        for side in (cr.prefill, cr.decode):
                            w = self.workers.get(side)
                            if w is not None:
                                w.outstanding.discard(cr.rid)
                        self._terminal.append(cr.rid)
                        self._finish_locked(cr)
            elif kind == "error":
                self._fail_worker(wh, RuntimeError(
                    "worker %s: %s" % (wh.name, meta.get("msg"))))
                return

    def _commit_tokens_locked(self, cr, toks, now):
        """Append newly streamed tokens (router lock held)."""
        if toks and cr.first_token_t is None:
            cr.first_token_t = now
            ttft_ms = (now - cr.submit_t) * 1e3
            if self._obs is not None:
                self._obs.h_ttft.observe(ttft_ms)
                if profiler.is_recording():
                    # router-lane TTFT span: the worker/transport
                    # spans shipped for this rid nest inside it in
                    # the merged dump (flushed by the caller outside
                    # the router lock)
                    self._obs.trace.add_span(
                        cr.rid, "ttft", cr.submit_t, now,
                        args={"trace_id": cr.trace_id,
                              "prefill": cr.prefill,
                              "decode": cr.decode})
            # SLO burn window (round 23 statusz): (arrival, ttft_ms)
            # samples pruned to the longest burn window
            self._ttft_window.append((now, ttft_ms))
            while self._ttft_window and \
                    now - self._ttft_window[0][0] > 300.0:
                self._ttft_window.popleft()
        new = [int(t) for t in toks]
        cr.committed.extend(new)
        cr.token_times.extend(now for _ in toks)
        if new:
            # round 20: the per-token failover log IS the SSE feed —
            # every listener sees exactly the tokens a resubmission
            # would replay, so streams survive worker death
            for cb in cr.listeners:
                cb(("tokens", new))

    def _terminal_event(self, cr):
        if cr.state == "done":
            return ("done", cr.output)
        if cr.state == "cancelled":
            return ("error", RequestCancelled(
                "request %d was cancelled" % cr.rid))
        return ("error", cr.error if cr.error is not None else
                ClusterFailed("request %d failed" % cr.rid))

    def _finish_locked(self, cr):
        """Terminal transition tail (router lock held): one terminal
        stream event per request, then wake ``result()`` waiters.  A
        listener receiving the terminal event IS the delivery — mark
        delivered so ``_purge_locked`` bounds the table under pure
        HTTP traffic (same contract as ``ServingCluster``)."""
        if cr.listeners:
            cr.delivered = True
        for cb in cr.listeners:
            cb(self._terminal_event(cr))
        cr.listeners = []
        cr.done_evt.set()

    def attach_stream(self, rid, cb):
        """Register a per-request token-stream listener — the same
        contract as ``ServingCluster.attach_stream`` (backlog
        delivered on attach, then ``("tokens", [...])`` batches and
        one terminal ``("done", output)`` / ``("error", exc)``).
        Callbacks run on the router's receive threads under the
        router lock: keep them to an enqueue."""
        with self._lock:
            cr = self.requests.get(rid)
            if cr is None:
                raise KeyError("attach_stream(%d): unknown rid" % rid)
            if cr.committed:
                cb(("tokens", list(cr.committed)))
            if cr.state == "running":
                cr.listeners.append(cb)
            else:
                cr.delivered = True        # terminal event handed out
                cb(self._terminal_event(cr))

    def cancel(self, rid):
        """Cancel a running request end-to-end (round 20): bump the
        incarnation gen (fencing every late frame of the old one) and
        send the gen-fenced ``cancel`` wire kind to BOTH assigned
        workers, which drop staged pages and force-retire the engine
        request — pages and slot are recycled without waiting for the
        generation to finish.  A cancel landing after completion is a
        no-op returning False (the inherent client race); a repeat
        cancel, or one for a gen that already died, is likewise
        harmless — the worker-side fence makes it a no-op."""
        sends = []
        with self._lock:
            cr = self.requests.get(rid)
            if cr is None:
                raise KeyError("cancel(%d): unknown rid" % rid)
            if cr.state != "running":
                return False
            cr.gen += 1
            cr.state = "cancelled"
            for side in set((cr.prefill, cr.decode)):
                w = self.workers.get(side)
                if w is not None:
                    w.outstanding.discard(cr.rid)
                    if w.alive:
                        sends.append((w.conn, (
                            "cancel", {"rid": cr.rid,
                                       "below_gen": cr.gen,
                                       "trace_id": cr.trace_id},
                            [])))
            if self._obs is not None:
                self._obs.cancelled.inc()
                self._obs.g_in_flight.set(
                    sum(r.state == "running"
                        for r in self.requests.values()))
            self._terminal.append(cr.rid)
            self._purge_locked()
            self._finish_locked(cr)
        self._do_sends(sends)
        self._flight.record("cancel", rid=rid, trace_id=cr.trace_id)
        return True

    def _on_tokens(self, wh, meta):
        with self._lock:
            cr = self.requests.get(meta["rid"])
            if cr is None or cr.gen != meta["gen"] \
                    or cr.state != "running":
                return
            self._commit_tokens_locked(cr, meta["toks"], time.perf_counter())
        if self._obs is not None:
            self._obs.trace.flush()       # outside the router lock

    def _on_handed(self, wh, meta):
        """Prefill finished and handed off to the decode worker.
        Carries NO tokens: the decode worker reports the whole
        committed stream (handoff tokens included) on its own FIFO
        connection — splitting the stream across the two workers'
        independent router connections would race, and a decode
        'done' overtaking the prefill 'handed' would silently drop
        (or reorder) the prefill-sampled token."""
        with self._lock:
            cr = self.requests.get(meta["rid"])
            if cr is None or cr.gen != meta["gen"] \
                    or cr.state != "running":
                return
            cr.phase = "decode"
            wh.outstanding.discard(cr.rid)

    def _on_done(self, wh, meta):
        sends = []
        with self._lock:
            cr = self.requests.get(meta["rid"])
            if cr is None or cr.gen != meta["gen"] \
                    or cr.state != "running":
                return
            self._commit_tokens_locked(cr, meta.get("toks", ()),
                                       time.perf_counter())
            cr.output = np.concatenate(
                [cr.prompt, np.asarray(cr.committed, np.int32)])
            cr.state = "done"
            for side in (cr.prefill, cr.decode):
                w = self.workers.get(side)
                if w is not None:
                    w.outstanding.discard(cr.rid)
            if cr.phase == "prefill" and cr.decode != wh.name:
                # the request completed AT PREFILL: the decode side
                # may hold staged pages from the stream — fence it
                # authoritatively from here (the prefill worker's
                # courtesy 'drop' is best-effort; a failed send would
                # leak decode pool pages forever)
                w = self.workers.get(cr.decode)
                if w is not None and w.alive:
                    sends.append((w.conn, (
                        "abort", {"rid": cr.rid,
                                  "below_gen": cr.gen + 1}, [])))
            if self._obs is not None:
                self._obs.completed.inc()
                self._obs.g_in_flight.set(
                    sum(r.state == "running"
                        for r in self.requests.values()))
            self._terminal.append(cr.rid)
            self._purge_locked()
            self._finish_locked(cr)
        self._do_sends(sends)
        self._flight.record("done", rid=meta.get("rid"),
                            worker=wh.name)
        if self._obs is not None:
            self._obs.trace.flush()       # outside the router lock

    def _purge_locked(self):
        excess = len(self._terminal) - self._retain
        if excess <= 0:
            return
        kept: "collections.deque[int]" = collections.deque()
        for rid in self._terminal:
            req = self.requests.get(rid)
            if excess > 0 and (req is None or req.delivered):
                excess -= 1
                self.requests.pop(rid, None)
            else:
                kept.append(rid)
        self._terminal = kept

    def _on_lost(self, wh, meta):
        """A prefill worker abandoned a request because its decode
        peer was unreachable (peer data-plane failure with the peer
        PROCESS possibly still alive — the watchdog cannot see it):
        reassign, with any streamed state fenced out."""
        sends = []
        with self._lock:
            cr = self.requests.get(meta["rid"])
            if cr is None or cr.gen != meta["gen"] \
                    or cr.state != "running":
                return
            cr.gen += 1
            cr.failovers += 1
            for side in (cr.prefill, cr.decode):
                w = self.workers.get(side)
                if w is not None:
                    w.outstanding.discard(cr.rid)
                    if w.alive:
                        sends.append((w.conn, (
                            "abort", {"rid": cr.rid,
                                      "below_gen": cr.gen}, [])))
            if cr.failovers > 5:
                # a persistently broken data plane must not ping-pong
                # the request between worker pairs forever
                cr.state = "failed"
                cr.error = ClusterFailed(
                    "request %d: abandoned %d times (worker data "
                    "plane unreachable)" % (cr.rid, cr.failovers))
                self._terminal.append(cr.rid)
                self._finish_locked(cr)
            else:
                sends.extend(self._dispatch_locked(cr))
                if cr.state == "running" and self._obs is not None:
                    self._obs.resubmitted.inc()
        self._do_sends(sends)

    def _on_stats(self, wh, meta):
        wh.stats = meta["stats"]
        obs = self._obs
        if obs is not None:
            seen = self._stat_seen.setdefault(wh.name, {})
            for key, ctr in (("bytes_streamed", obs.page_bytes),
                             ("pages_streamed", obs.pages_streamed),
                             ("remote_hits", obs.remote_hits),
                             ("remote_hit_tokens",
                              obs.remote_hit_tokens)):
                v = wh.stats.get(key, 0)
                d = v - seen.get(key, 0)
                if d > 0:
                    ctr.inc(d)
                seen[key] = v
            for ms in wh.stats.get("transfer_ms", ()):
                obs.h_transfer.observe(ms)
        # set LAST, and only for the awaited stats_req reply: an
        # unsolicited periodic frame serialized before the request
        # must not satisfy the wait with a stale snapshot (a
        # cluster_stats() caller reading the registry right after the
        # event must see the REQUESTED message's deltas folded in)
        if meta.get("sid") is not None \
                and meta["sid"] == wh.stats_sid:
            wh.stats_evt.set()

    def _on_clock(self, wh, meta):
        """One ``clock_req`` -> ``clock`` ping-pong sample (round 23):
        ``offset = t_worker - (t0 + rtt/2)`` — the worker's clock
        read, centered on the round trip.  Min-RTT filtering keeps
        the sample least contaminated by queueing delay; correction
        is ``t_router = t_worker - offset``."""
        now = time.perf_counter()
        try:
            t0 = float(meta["t0"])
            tw = float(meta["t_worker"])
        except (KeyError, TypeError, ValueError):
            return
        rtt = max(0.0, now - t0)
        if wh.clock_rtt is None or rtt < wh.clock_rtt:
            wh.clock_rtt = rtt
            wh.clock_offset = tw - (t0 + rtt / 2.0)

    def _on_spans(self, wh, meta):
        """Fold a worker's shipped span batch (the ``spans`` wire
        kind, riding its stats tick) onto the router timeline: times
        corrected by the worker's clock offset, stored per-rid for
        ``GET /debug/trace/<rid>``, and — while a profiler session is
        recording — emitted into the ONE merged chrome trace under
        the worker's (or the shared ``transport``) swimlane."""
        spans = meta.get("spans") or ()
        if not spans:
            return
        off = wh.clock_offset
        with self._lock:
            for s in spans:
                if not isinstance(s, dict):
                    continue
                rec = dict(s, worker=wh.name, offset_s=off)
                lst = self._span_store.get(rec.get("rid"))
                if lst is None:
                    self._span_store[rec.get("rid")] = lst = []
                    while len(self._span_store) > \
                            self._span_store_cap:
                        self._span_store.popitem(last=False)
                lst.append(rec)
        if profiler.is_recording():
            # outside the router lock — the merged emitter carries
            # its own lock, and a profiler flush must never extend a
            # critical section every recv thread contends on
            for s in spans:
                if not isinstance(s, dict):
                    continue
                lane = "transport" \
                    if s.get("cat") == "transport" else wh.name
                self._merged.add(lane, s, off)
            self._merged.flush()

    # ------------------------------------------------------ intake ---
    def _pick(self, role, exclude=()):
        """Least-outstanding over healthy workers of ``role``, ties
        broken round-robin — back-to-back submits spread across
        replicas (the cluster prefix index, not affinity stickiness,
        is what makes spreading cheap here: the second replica fetches
        the pages instead of recomputing them)."""
        cands = sorted((w for w in self.workers.values()
                        if w.role == role and w.alive
                        and not w.draining
                        and w.name not in exclude),
                       key=lambda w: w.name)
        if not cands:
            return None
        i = 0 if role == "prefill" else 1
        cur = self._rr[i]
        self._rr[i] = cur + 1
        lo = min(len(w.outstanding) for w in cands)
        tied = [w for w in cands if len(w.outstanding) == lo]
        return tied[cur % len(tied)]

    def submit(self, prompt, max_new_tokens, eos_id=None,
               trace_id=None):
        """Queue a request; returns its rid immediately.
        ``trace_id`` (round 23) is the cross-process trace context —
        the HTTP front door passes its ``X-Request-Id`` so edge,
        router, worker, and transport spans correlate; unset, the
        request traces under a ``rid<N>`` default."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("submit: empty prompt")
        if max_new_tokens < 1:
            raise ValueError("submit: max_new_tokens must be >= 1")
        if prompt.size + int(max_new_tokens) > self._max_seq:
            raise ValueError(
                "submit: %d tokens > worker max_seq/max_len %d"
                % (prompt.size + int(max_new_tokens), self._max_seq))
        with self._lock:
            if self._closed:
                raise ClusterClosed("submit() after close()")
            cr = DisaggRequest(self._next_rid, prompt,
                               int(max_new_tokens), eos_id,
                               trace_id=trace_id)
            self._next_rid += 1
            self.requests[cr.rid] = cr
            if self._obs is not None:
                self._obs.submitted.inc()
                self._obs.g_in_flight.set(
                    sum(r.state == "running"
                        for r in self.requests.values()))
            sends = self._dispatch_locked(cr)
        self._do_sends(sends)
        self._flight.record("submit", rid=cr.rid,
                            trace_id=cr.trace_id, prefill=cr.prefill,
                            decode=cr.decode)
        if self._obs is not None and profiler.is_recording():
            self._obs.trace.add_instant(
                cr.rid, "submit", cr.submit_t,
                args={"trace_id": cr.trace_id})
            self._obs.trace.flush()
        return cr.rid

    def _dispatch_locked(self, cr):
        """Assign (or reassign) a request; returns the (conn, frame)
        sends to perform OUTSIDE the lock."""
        pre = self._pick("prefill")
        dec = self._pick("decode")
        if pre is None or dec is None:
            cr.state = "failed"
            cr.error = ClusterFailed(
                "no healthy %s worker" %
                ("prefill" if pre is None else "decode"))
            self._terminal.append(cr.rid)
            self._finish_locked(cr)
            return []
        cr.prefill, cr.decode = pre.name, dec.name
        cr.phase = "prefill"
        pre.outstanding.add(cr.rid)
        dec.outstanding.add(cr.rid)
        inp = cr.prompt if not cr.committed else np.concatenate(
            [cr.prompt, np.asarray(cr.committed, np.int32)])
        owner, depth, tier = self.index.match(
            chain_keys(inp, self.page_size))
        hint = None
        if owner is not None and owner != pre.name:
            wo = self.workers.get(owner)
            if wo is not None and wo.alive:
                hint = owner
        # hint_tier (round 18): where the owner's copy lives —
        # "hbm" (device pool, a gather away) or "host" (spilled to
        # the owner's host tier, served without any device work).
        # The prefill worker weighs the peer fetch against its OWN
        # hot + warm local depth (probe_depth), so a peer copy only
        # wins when it covers strictly more than local HBM + local
        # host DRAM together — transfer must beat transfer, not just
        # prefill.
        meta = {"rid": cr.rid, "gen": cr.gen,
                "max_new": cr.max_new_tokens - len(cr.committed),
                "eos": cr.eos_id, "decode": dec.name,
                "hint": hint, "hint_depth": depth,
                "hint_tier": tier if hint is not None else None,
                "trace_id": cr.trace_id}
        return [(pre.conn, ("submit", meta,
                            [np.ascontiguousarray(inp).data]))]

    def _do_sends(self, sends):
        for conn, (kind, meta, bufs) in sends:
            try:
                conn.send(kind, meta, bufs)
            except OSError:
                pass                      # the monitor will fail it over

    def drain(self, timeout=None):
        """Wait until every submitted request reaches a terminal
        state.  Returns True if fully drained (the same contract as
        ``ServingCluster.drain`` — the trace-replay harness drives
        both flavors through it)."""
        deadline = None if timeout is None \
            else time.perf_counter() + timeout
        for cr in list(self.requests.values()):
            left = None if deadline is None \
                else max(0.0, deadline - time.perf_counter())
            if not cr.done_evt.wait(left):
                return False
        return True

    def result(self, rid, timeout=None):
        """Block until the request finishes; returns prompt +
        generated tokens.  Raises :class:`ClusterFailed` if no healthy
        worker could finish it."""
        cr = self.requests.get(rid)
        if cr is None:
            raise KeyError("result(%d): unknown rid (already "
                           "collected and purged?)" % rid)
        if not cr.done_evt.wait(timeout):
            raise TimeoutError("result(%d): still running" % rid)
        with self._lock:
            cr.delivered = True
            self._purge_locked()
        if cr.state == "done":
            return cr.output
        if cr.state == "cancelled":
            raise RequestCancelled("request %d was cancelled" % rid)
        raise ClusterFailed("request %d: %r" % (rid, cr.error))

    # ---------------------------------------------------- failover ---
    def _fail_worker(self, wh, error):
        """A worker process died or stalled: fence it, drop its index
        entries, resubmit its requests to survivors with the streamed
        committed tokens as prompt extension (recompute-exact)."""
        sends = []
        with self._lock:
            if wh.dead:
                return
            wh.dead = True
            wh.error = error
            self._standby.discard(wh.name)
            self.index.drop_owner(wh.name)
            # a SIGKILLed worker cannot sweep its own unreceived put
            # segments (its orderly-exit sweep never ran) — reclaim
            # them by its pid; a receiver mid-open just sees ENOENT,
            # which reads as the sender's death (it IS dead)
            pid = wh.pid or (wh.proc.pid if wh.proc is not None
                             else None)
            if pid is not None:
                from .transport import put_sweep
                put_sweep(pid)
                # round 23 forensics: the victim's span buffer died
                # with it, but its flight-recorder ring is
                # crash-durable (mmap, page cache) — recover the tail
                # by the same pid key the put sweep uses
                from ..obs.flight import flight_recover
                tail = flight_recover(pid, unlink=True)
                if tail:
                    wh.flight_tail = tail
                    self._flight_tails[wh.name] = tail
            if self._obs is not None:
                self._obs.failovers.inc()
                self._obs.g_workers.set(self._serving_count())
            # a request in the prefill phase dies with either of its
            # assigned workers (pages may already be streaming to the
            # decode side); one that completed handoff only dies with
            # its DECODE worker — the prefill side is out of the loop
            victims = [
                cr for cr in self.requests.values()
                if cr.state == "running"
                and ((cr.phase == "prefill"
                      and wh.name in (cr.prefill, cr.decode))
                     or (cr.phase == "decode"
                         and wh.name == cr.decode))]
            for cr in victims:
                cr.gen += 1
                cr.failovers += 1
                for side in (cr.prefill, cr.decode):
                    w = self.workers.get(side)
                    if w is not None:
                        w.outstanding.discard(cr.rid)
                # fence + free whatever the surviving side holds
                for side in set((cr.prefill, cr.decode)):
                    w = self.workers.get(side)
                    if w is not None and w.alive:
                        sends.append((w.conn, ("abort",
                                               {"rid": cr.rid,
                                                "below_gen":
                                                cr.gen}, [])))
                # already satisfiable from streamed tokens?
                done = (len(cr.committed) >= cr.max_new_tokens
                        or (cr.eos_id is not None
                            and cr.eos_id in cr.committed))
                if done:
                    cr.output = np.concatenate(
                        [cr.prompt,
                         np.asarray(cr.committed, np.int32)])
                    cr.state = "done"
                    if self._obs is not None:
                        self._obs.completed.inc()
                    self._terminal.append(cr.rid)
                    self._finish_locked(cr)
                    continue
                sends.extend(self._dispatch_locked(cr))
                if cr.state == "running" and self._obs is not None:
                    self._obs.resubmitted.inc()
        try:
            wh.conn.close()
        except Exception:
            pass
        self._do_sends(sends)
        self._flight.record("worker_dead", worker=wh.name,
                            error=repr(error))
        tail = wh.flight_tail
        if tail and profiler.is_recording():
            # fold the victim's final events into the live merged
            # trace as instants on its swimlane — the chaos test's
            # checked artifact
            for ev in tail:
                self._merged.add_flight(wh.name, ev,
                                        wh.clock_offset)
            self._merged.flush()

    def flight_tail(self, name):
        """The recovered flight-recorder tail of a dead worker
        (seq-ordered event dicts), or ``None`` — post-mortem
        debugging surface, also summarized in ``debug_status()``."""
        with self._lock:
            return self._flight_tails.get(name)

    def _monitor_loop(self):
        period = max(0.05, min(0.5, self.watchdog_s / 4.0))
        while True:
            time.sleep(period)
            with self._lock:
                if self._closed:
                    return
                suspects = []
                now = time.perf_counter()
                for wh in self.workers.values():
                    if wh.dead:
                        continue
                    if wh.proc is not None and not wh.proc.is_alive():
                        suspects.append((wh, "process exited"))
                    elif wh.outstanding and \
                            now - wh.last_seen > self.watchdog_s:
                        suspects.append((wh, "stalled past watchdog "
                                         "%.1fs" % self.watchdog_s))
            for wh, why in suspects:
                self._fail_worker(wh, RuntimeError(
                    "worker %s: %s" % (wh.name, why)))

    # --------------------------------------------------- accounting --
    _stats_seq = itertools.count(1)

    def cluster_stats(self, timeout=5.0):
        """Fresh per-worker stats snapshot (stats-request round
        trip, correlated by sequence id): {name: {..engine/prefix/
        streamer counters..}} for LIVE workers."""
        sid = next(self._stats_seq)
        live = [w for w in self.workers.values() if w.alive]
        for wh in live:
            wh.stats_sid = sid
            wh.stats_evt.clear()
        for wh in live:
            try:
                wh.conn.send("stats_req", {"sid": sid})
            except OSError:
                pass
        deadline = time.perf_counter() + timeout
        for wh in live:
            wh.stats_evt.wait(max(0.0,
                                  deadline - time.perf_counter()))
        return {wh.name: dict(wh.stats) for wh in live}

    def health(self):
        now = time.perf_counter()
        with self._lock:
            return [{"worker": w.name, "role": w.role,
                     "alive": w.alive, "dead": w.dead,
                     "standby": w.name in self._standby,
                     "draining": w.draining,
                     "outstanding": len(w.outstanding),
                     "heartbeat_age_s": now - w.last_seen,
                     "pid": None if w.proc is None else w.proc.pid,
                     "error": repr(w.error) if w.error else None}
                    for w in self.workers.values()]

    # --------------------------------------- ops introspection (23) --
    def _slo_locked(self, now):
        """SLO burn-rate gauges from the router's TTFT window: the
        fraction of recent requests over the
        ``MXNET_SERVE_SLO_TTFT_MS`` budget, expressed as a burn rate
        against the 1% error budget of a 99% objective (>1.0 means
        the window is eating budget faster than it refills)."""
        budget_ms = self._slo_ttft_ms
        windows = {}
        # zip, not ((label, win), …): a 2-tuple whose second element
        # is a ("str", …) tuple reads as a queued wire send to
        # protolint's model — keep ops plumbing out of the protocol
        for label, win_s in zip(("1m", "5m"), (60.0, 300.0)):
            n = bad = 0
            for t, ms in self._ttft_window:
                if now - t <= win_s:
                    n += 1
                    bad += ms > budget_ms
            frac = bad / n if n else 0.0
            windows[label] = {"requests": n, "over_budget": bad,
                              "bad_fraction": frac,
                              "burn_rate": frac / 0.01}
        return {"ttft_budget_ms": budget_ms, "windows": windows}

    def debug_status(self):
        """One-call ops snapshot behind ``GET /debug/statusz``: live
        topology, per-worker health + clock offsets + cached stats
        (tier occupancy included), in-flight request states, SLO burn
        gauges, and the flight-recorder state."""
        now = time.perf_counter()
        with self._lock:
            workers = []
            for w in self.workers.values():
                st = w.stats or {}
                tail = self._flight_tails.get(w.name)
                workers.append({
                    "worker": w.name, "role": w.role,
                    "alive": w.alive, "dead": w.dead,
                    "standby": w.name in self._standby,
                    "draining": w.draining,
                    "outstanding": len(w.outstanding),
                    "heartbeat_age_s": now - w.last_seen,
                    "pid": w.pid or (w.proc.pid
                                     if w.proc is not None else None),
                    "clock_offset_us": None if w.clock_rtt is None
                    else w.clock_offset * 1e6,
                    "clock_rtt_us": None if w.clock_rtt is None
                    else w.clock_rtt * 1e6,
                    "active_requests": st.get("active_requests"),
                    "pages_in_use": st.get("pages_in_use"),
                    "free_pages": st.get("free_pages"),
                    "tier": st.get("tier"),
                    "flight_tail_events": None if tail is None
                    else len(tail),
                    "error": repr(w.error) if w.error else None})
            reqs = [{"rid": r.rid, "trace_id": r.trace_id,
                     "state": r.state, "phase": r.phase,
                     "prefill": r.prefill, "decode": r.decode,
                     "gen": r.gen, "committed": len(r.committed),
                     "failovers": r.failovers,
                     "age_s": now - r.submit_t,
                     "ttft_ms": None if r.first_token_t is None
                     else (r.first_token_t - r.submit_t) * 1e3}
                    for r in self.requests.values()
                    if r.state == "running"]
            slo = self._slo_locked(now)
            recovered = sorted(self._flight_tails)
        return {"kind": "disagg", "closed": self._closed,
                "workers": workers, "in_flight": reqs, "slo": slo,
                "flight": {"path": self._flight.path,
                           "recovered": recovered}}

    def request_trace(self, rid):
        """Everything the router knows about one request's timeline:
        its record (state/assignment/timing) plus every span workers
        shipped for it (clock-corrected store behind
        ``GET /debug/trace/<rid>``).  KeyError on a rid the router
        has never seen."""
        with self._lock:
            cr = self.requests.get(rid)
            router = None if cr is None else {
                "rid": cr.rid, "trace_id": cr.trace_id,
                "state": cr.state, "phase": cr.phase,
                "prefill": cr.prefill, "decode": cr.decode,
                "gen": cr.gen, "committed": len(cr.committed),
                "failovers": cr.failovers, "submit_t": cr.submit_t,
                "first_token_t": cr.first_token_t}
            spans = [dict(s) for s in self._span_store.get(rid, ())]
        if router is None and not spans:
            raise KeyError("request_trace(%r): unknown rid" % (rid,))
        return {"rid": rid, "router": router, "spans": spans}

    @property
    def registry(self):
        return self._obs.registry if self._obs is not None else None

    def kill_worker(self, name, sig=None):
        """Test/ops helper: SIGKILL a spawned worker process."""
        import signal as _signal
        wh = self.workers[name]
        if wh.proc is None:
            raise ValueError("worker %s was not spawned locally"
                             % name)
        os.kill(wh.proc.pid, sig or _signal.SIGKILL)

    # ------------------------------------------------- scale-up/down --
    def _handshake_one(self, wh, timeout):
        """Handshake ONE late worker on the live listener (the
        add_worker path — same protocol as the construction-time
        ``_handshake_all``).  Hellos from OTHER concurrently-joining
        workers are stashed, not closed — closing them would kill a
        sibling's join (the multi-worker ``--workers-only`` flow
        starts several workers at once; _handshake_all's any-name
        acceptance has the same property at construction)."""
        deadline = time.perf_counter() + timeout
        with self._lock:
            conn = self._early_hellos.pop(wh.name, None)
        while conn is None:
            left = deadline - time.perf_counter()
            if left <= 0:
                raise RuntimeError(
                    "add_worker: %s never connected" % wh.name)
            try:
                cand = self._pending_conns.get(timeout=min(left, 1.0))
            except queue.Empty:
                continue
            got = cand.recv(timeout=left)
            if got in (None, "timeout"):
                cand.close()
                continue
            kind, meta, _ = got
            name = meta.get("name") if kind == "hello" else None
            if name == wh.name:
                conn = cand
                wh.pid = meta.get("pid")
            elif name:
                # a sibling joiner beat us to the accept queue: park
                # its hello'd connection for ITS add_worker call
                with self._lock:
                    old = self._early_hellos.pop(name, None)
                    self._early_hellos[name] = cand
                if old is not None:
                    old.close()
            else:
                cand.close()
        wh.conn = conn
        pm, pb = self._params_frames
        wh.conn.send("config",
                     {"cfg": self.cfg, "role": wh.role,
                      "engine_kwargs": self._engine_kwargs,
                      "params_meta": pm,
                      "watchdog_s": self.watchdog_s}, pb)
        got = wh.conn.recv(timeout=max(
            1.0, deadline - time.perf_counter()))
        if got in (None, "timeout") or got[0] != "ready":
            raise RuntimeError(
                "add_worker: worker %s failed to build its engine "
                "(%r)" % (wh.name, got))
        _, meta, _ = got
        wh.data_host = meta["data_host"]
        wh.data_port = meta["data_port"]
        wh.last_seen = time.perf_counter()

    def add_worker(self, role, spawn=None, ready_timeout=None,
                   standby=False):
        """Scale-up actuation (round 16): add one more ``role``
        worker PROCESS to the live cluster.  ``spawn=True`` forks it
        here (multiprocessing spawn, like construction);
        ``spawn=False`` waits for an externally-launched worker —
        ``tools/launch.py --launcher serve --workers-only`` (or bare
        ``run_worker()`` with ``MXNET_SERVE_*`` env) started against
        this router's port, which is how an autoscaler adds capacity
        on ANOTHER host.  Blocks through handshake + engine pre-warm;
        every live worker receives the refreshed peer map.  Returns
        the new worker's name.

        ``standby=True`` (round 18, the pre-provisioned-join path):
        the worker is brought ALL the way up — handshake, params
        ship, engine build, step-program pre-warm, peer map — but
        parked out of routing and out of the healthy-capacity gauge.
        ``scale_up()`` adopts the warmest standby of the needed role
        in O(peer-map flip) instead of paying process-spawn + jax
        import + compile (~15 s on CPU — longer than the whole burst
        the round-16 goodput row measured; the honest caveat this
        path exists to close)."""
        if role not in ("prefill", "decode"):
            raise ValueError("add_worker: role must be 'prefill' or "
                             "'decode', got %r" % (role,))
        if ready_timeout is None:
            ready_timeout = _env_default(
                "MXNET_SERVE_READY_TIMEOUT_S", 120.0)
        with self._lock:
            if self._closed:
                raise ClusterClosed("add_worker() after close()")
            i = 0
            while "%s%d" % (role, i) in self.workers:
                i += 1
            name = "%s%d" % (role, i)
            wh = _WorkerHandle(name, role)
            # hidden from _pick until FULLY ready: the handshake sets
            # wh.conn (making it "alive") several messages before the
            # worker has its peer map — a submit dispatched into that
            # window would hit a worker still in __init__, which
            # treats the unexpected frame as a broken handshake and
            # dies
            wh.draining = True
            self.workers[name] = wh
        if spawn is None:
            spawn = self._spawn
        if spawn:
            import multiprocessing as mp
            ctx = mp.get_context("spawn")
            wh.proc = ctx.Process(
                target=_disagg_worker_entry,
                args=(name, role, self._listener.host,
                      self._listener.port),
                daemon=True, name="serving-" + name)
            wh.proc.start()
        try:
            self._handshake_one(wh, ready_timeout)
        except BaseException:
            with self._lock:
                wh.dead = True
                self._standby.discard(name)
                self.workers.pop(name, None)
            if wh.proc is not None and wh.proc.is_alive():
                wh.proc.terminate()
                wh.proc.join(timeout=5)   # reap the zombie pid
            if wh.conn is not None:
                wh.conn.close()
            raise
        with self._lock:
            peers = {n: {"role": w.role, "host": w.data_host,
                         "port": w.data_port}
                     for n, w in self.workers.items() if w.alive}
            targets = [w for w in self.workers.values() if w.alive]
        for w in targets:
            try:
                w.conn.send("peers", {"peers": peers})
            except OSError:
                pass                      # the monitor will fail it over
        wh.recv_thread = threading.Thread(
            target=self._recv_loop, args=(wh,), daemon=True,
            name="disagg-recv-" + wh.name)
        wh.recv_thread.start()
        self._clock_ping(wh)
        with self._lock:
            if standby:
                # fully warm, deliberately invisible: stays draining
                # (never routed, never chaos-targeted) until adopted
                self._standby.add(name)
            else:
                wh.draining = False       # ready: now routable
            if self._obs is not None:
                self._obs.g_workers.set(self._serving_count())
        return name

    def adopt_standby(self, role):
        """Put one pre-provisioned standby ``role`` worker into
        rotation (round 18).  O(flag flip): the worker is already
        handshaken, pre-warmed, and in every peer map.  Returns its
        name, or None when no standby of that role is parked."""
        with self._lock:
            for name in sorted(self._standby):
                wh = self.workers.get(name)
                if wh is not None and wh.role == role and wh.alive:
                    self._standby.discard(name)
                    wh.draining = False
                    if self._obs is not None:
                        self._obs.g_workers.set(self._serving_count())
                    return name
        return None

    def drain_worker(self, name, timeout=60.0):
        """Graceful scale-down of one worker process: stop routing to
        it, wait for its outstanding requests to finish, then shut it
        down (clean exit, not SIGKILL — its engine drains with zero
        in-flight loss).  Refuses to drain the last live worker of a
        role.  Returns True once drained and stopped; False (and back
        in rotation) on timeout."""
        with self._lock:
            wh = self.workers[name]
            if wh.dead:
                return False
            siblings = [w for w in self.workers.values()
                        if w.role == wh.role and w.alive
                        and not w.draining and w is not wh]
            if not siblings:
                return False
            wh.draining = True
        deadline = time.perf_counter() + float(timeout)
        drained = False
        while time.perf_counter() < deadline:
            with self._lock:
                drained = not wh.outstanding
            if drained:
                break
            time.sleep(0.02)
        if not drained:
            with self._lock:
                wh.draining = False       # back in rotation
            return False
        with self._lock:
            wh.dead = True                # recv EOF won't fail over
            self._standby.discard(name)   # a drained spare is gone
            self.index.drop_owner(name)
            if self._obs is not None:
                self._obs.g_workers.set(self._serving_count())
        try:
            wh.conn.send("shutdown", {})
        except OSError:
            pass
        if wh.proc is not None:
            wh.proc.join(timeout=10)
            if wh.proc.is_alive():
                wh.proc.terminate()
                wh.proc.join(timeout=5)   # reap the zombie pid
        try:
            wh.conn.close()
        except Exception:
            pass
        return True

    # the autoscaler's actuation protocol (shared with
    # ServingCluster) — role-aware here: scale_up grows the role with
    # the higher mean outstanding load, scale_down drains the
    # least-loaded worker of any role that keeps >= 1 worker
    def scale_up(self):
        with self._lock:
            load = {}
            for role in ("prefill", "decode"):
                ws = [w for w in self.workers.values()
                      if w.role == role and w.alive
                      and not w.draining]
                load[role] = (float("inf") if not ws else
                              sum(len(w.outstanding) for w in ws)
                              / len(ws))
        role = max(sorted(load), key=lambda r: load[r])
        # a pre-provisioned standby of the needed role is adopted in
        # O(peer-map flip); only a cold cluster pays spawn + compile
        if self.adopt_standby(role) is not None:
            return True
        self.add_worker(role)
        return True

    def scale_down(self, timeout=60.0):
        with self._lock:
            cands = []
            for role in ("prefill", "decode"):
                ws = [w for w in self.workers.values()
                      if w.role == role and w.alive
                      and not w.draining]
                if len(ws) > 1:
                    cands.extend(ws)
            if not cands:
                return False
            name = min(cands, key=lambda w: (len(w.outstanding),
                                             w.name)).name
        return self.drain_worker(name, timeout=timeout)

    @property
    def slots_per_replica(self):
        return self._engine_kwargs["num_slots"]

    def close(self, timeout=30.0):
        with self._lock:
            self._closed = True
            workers = list(self.workers.values())
            # a result() waiter on another thread must not block
            # forever on a request the shutdown abandons — fail every
            # non-terminal request loudly (the in-process cluster
            # DRAINS instead; this transport has no graceful drain
            # yet, so honesty beats a silent hang)
            for cr in self.requests.values():
                if cr.state == "running":
                    cr.state = "failed"
                    cr.error = ClusterClosed(
                        "cluster closed with the request in flight")
                    self._terminal.append(cr.rid)
                    self._finish_locked(cr)
        for wh in workers:
            if wh.conn is not None:
                try:
                    wh.conn.send("shutdown", {})
                except OSError:
                    pass
        from .transport import put_sweep
        from ..obs.flight import flight_sweep
        for wh in workers:
            if wh.proc is not None:
                wh.proc.join(timeout=timeout)
                if wh.proc.is_alive():
                    wh.proc.terminate()
                    wh.proc.join(timeout=5)
            # drain the recv thread BEFORE closing the conn: the
            # worker's last-gasp frames (its final span ship) are
            # still in the socket buffer, and the thread exits on the
            # EOF the dead worker left only after folding them —
            # closing first would drop the trace tail of every
            # sub-tick run
            if wh.recv_thread is not None and \
                    wh.recv_thread is not threading.current_thread():
                wh.recv_thread.join(timeout=5)
            if wh.conn is not None:
                wh.conn.close()
            # belt over the workers' own exit sweeps: a worker that
            # died uncleanly leaves pid-prefixed segments (and its
            # flight ring) behind
            pid = wh.pid or (wh.proc.pid if wh.proc is not None
                             else None)
            if pid is not None:
                put_sweep(pid)
                flight_sweep(pid)
        self._flight.close(unlink=True)
        with self._lock:
            early = list(self._early_hellos.values())
            self._early_hellos.clear()
        for conn in early:
            try:
                conn.close()
            except Exception:
                pass
        self._listener.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


# --------------------------------------------------------------------------
# disaggregated worker-process side
# --------------------------------------------------------------------------

class _DisaggWorker:
    """One prefill or decode worker process: a single main loop owns
    the engine (all device work stays on one thread — receive threads
    only enqueue host bytes), a data listener serves peer page
    fetches / the prefill→decode stream, and a control connection
    carries submits/tokens/stats to the router."""

    def __init__(self, name, role, router_host, router_port):
        from .transport import connect, frames_to_tree, Listener
        self.name = name
        self.role = role
        self.inbox: "queue.Queue" = queue.Queue()
        self.fetch_inbox: "queue.Queue" = queue.Queue()
        self.router = connect(router_host, router_port, timeout=60.0,
                              retry_until=60.0)
        self.router.send("hello", {"name": name, "role": role,
                                   "pid": os.getpid()})
        got = self.router.recv(timeout=120.0)
        if got in (None, "timeout") or got[0] != "config":
            raise RuntimeError("worker %s: bad config handshake: %r"
                               % (name, got))
        _, meta, bufs = got
        self.cfg = meta["cfg"]
        self.watchdog_s = meta.get("watchdog_s", 30.0)
        params = frames_to_tree(meta["params_meta"], bufs)
        kw = dict(meta["engine_kwargs"])
        if role == "prefill":
            # the prefill replica's trie is the cluster's page source;
            # speculation never pays on a 1-token budget
            kw.update(prefix_cache=True, spec_K=0)
        else:
            kw.update(prefix_cache=False)
        self.eng = ServingEngine(params, self.cfg, **kw)
        # pre-warm the compiled step BEFORE reporting ready: the
        # handshake timeout covers the compile, so the router's
        # watchdog never mistakes a first-request compile for a stall
        wid = self.eng.submit(np.ones(1, np.int32), 1)
        self.eng.run()
        del self.eng.requests[wid]
        # pre-warm the bucketed page-transfer programs too (round
        # 18): the first peer fetch, prefill->decode stream, or
        # pressure spill after handshake must pay a TRANSFER, not a
        # compile — a bucket-4 install compile inside a fetch reply
        # is most of a cold prefill on CPU.  One allocated page
        # repeated per bucket exercises every gather/scatter shape
        # the small-run paths use; the page is scratch-grade warmup
        # state and goes straight back to the free list.
        ids = self.eng.cache.alloc(1)
        if ids is not None:
            for b in (1, 2, 4, 8):
                content = self.eng.cache.export_pages(ids * b)
                self.eng.cache.install_pages(ids * b, content)
            self.eng.cache.free(ids)
        if self.eng.prefix is not None:
            self.eng.prefix.clear()
        for k in self.eng.stats:
            self.eng.stats[k] = type(self.eng.stats[k])()
        if self.eng.prefix is not None:
            self.eng.prefix.evict_cb = self._on_evict
            if self.eng.tier is not None:
                self.eng.prefix.tier_cb = self._on_tier_move
        if role == "prefill":
            self.eng.retire_cb = self._on_retire
        self._evicted_keys: List[bytes] = []
        # chain key -> last tier seen ("host"/"hbm"), flushed with the
        # stats tick as `tier` frames: absolute per-key state, so only
        # the LAST transition per key travels (a spill+restore inside
        # one tick cancels out to a no-op re-tag)
        self._tier_moves: Dict[bytes, str] = {}
        from .page_streamer import PageStreamer, PageReceiver
        self.streamer = PageStreamer(self.eng)
        self.receiver = PageReceiver(self.eng)
        # data plane: loopback for spawned local workers; an
        # externally-placed worker (another host) sets
        # MXNET_SERVE_DATA_HOST to ITS reachable address — we then
        # bind all interfaces and advertise that address to peers
        data_host = os.environ.get("MXNET_SERVE_DATA_HOST")
        self.listener = Listener(
            host="0.0.0.0" if data_host else "127.0.0.1")
        self.listener.start(self._peer_handler)
        self.router.send("ready",
                         {"data_host": data_host or "127.0.0.1",
                          "data_port": self.listener.port})
        got = self.router.recv(timeout=120.0)
        if got in (None, "timeout") or got[0] != "peers":
            raise RuntimeError("worker %s: no peer map" % name)
        self.peers = got[1]["peers"]
        self._peer_conns: Dict[str, object] = {}
        # request state: engine rid -> {rid, gen, meta, inp}
        self.by_erid: Dict[int, dict] = {}
        self.by_rid: Dict[int, int] = {}  # cluster rid -> engine rid
        self._reported: Dict[int, int] = {}   # rid -> tokens reported
        self.remote_hits = 0
        self.remote_hit_tokens = 0
        self.remote_hits_host_tier = 0
        self.fetch_bytes = 0
        self.pages_put_total = 0          # pages sent via put segments
        self.put_bytes_total = 0
        self._fetch_seq = 0               # fetch/reply correlation
        # rid -> lowest still-valid gen (per-request fence): a
        # fenced-out zombie prefill's late frames must be DROPPED —
        # letting them recreate staging would read as an out-of-order
        # stream and a protocol error must not kill a healthy worker
        self._fenced: Dict[int, int] = {}
        self.transfer_ms: List[float] = []
        self._last_stats = 0.0
        # round 23 observability: the crash-durable flight ring
        # (recovered by the router if we are SIGKILLed) and the span
        # staging buffer shipped to the router on the stats tick
        from ..obs.flight import FlightRecorder
        from ..obs.trace import SpanBuffer
        self._flight = FlightRecorder()
        self._spans = SpanBuffer()
        self._decode_t0: Dict[int, float] = {}   # rid -> admit time
        self._flight.record("ready", worker=name, role=role)
        self._running = True
        threading.Thread(target=self._router_recv, daemon=True,
                         name="disagg-router-recv").start()

    # -- feeder threads -> inbox ------------------------------------
    def _router_recv(self):
        while True:
            got = self.router.recv()
            if got is None:
                self.inbox.put(("_lost", None, None, None))
                return
            kind, meta, bufs = got
            self.inbox.put((kind, meta, bufs, None))

    def _peer_handler(self, conn):
        """One accepted peer connection: prefill→decode page streams
        and sibling FETCH requests; frames are enqueued with the conn
        so the main loop can reply in order.  The FIRST frame out is
        our transport caps (round 22) — the connector's ``wait_caps``
        relies on it preceding any reply."""
        try:
            conn.send_caps()
        except OSError:
            return
        while True:
            got = conn.recv()
            if got is None:
                return
            kind, meta, bufs = got
            if kind == "caps":
                continue                  # recorded on conn by recv
            if kind == "fetch":
                self.fetch_inbox.put((meta, bufs, conn))
                # wake token: an idle main loop is parked on the
                # general inbox — without it a fetch waits out the
                # full idle poll (20 ms) before being served, which
                # would dominate the remote-hit TTFT
                self.inbox.put(("_wake", None, None, None))
            else:
                self.inbox.put((kind, meta, bufs, conn))

    def _on_evict(self, key):
        self._evicted_keys.append(key)
        self._tier_moves.pop(key, None)   # gone beats any re-tag

    def _on_tier_move(self, key, tier):
        self._tier_moves[key] = tier

    def _on_retire(self, req):
        """Engine retire hook (prefill role): snapshot the finishing
        request's page ids + cache depth before ``_release`` clears
        them — the post-step handoff export streams from this
        snapshot (freed pages stay byte-intact until the next step's
        allocations)."""
        st = self.by_erid.get(req.rid)
        if st is not None:
            st["final_pages"] = list(req.pages)
            st["final_n_cached"] = req.n_cached
            st["final_chain_upto"] = req.chain_upto

    # -- remote prefix fetch (prefill role) -------------------------
    def _peer_conn(self, owner):
        from .transport import connect
        conn = self._peer_conns.get(owner)
        if conn is None or conn.closed:
            p = self.peers[owner]
            conn = connect(p["host"], p["port"], timeout=10.0)
            # caps handshake (round 22): advertise ours, learn theirs
            # (the acceptor's caps frame is its first) — a timeout
            # just means a socket-only peer, never a failure
            try:
                conn.send_caps()
                conn.wait_caps(timeout=5.0)
            except OSError:
                conn.close()              # died mid-handshake
                raise
            self._peer_conns[owner] = conn
        return conn

    def _send_pages_frame(self, conn, kind, meta, bufs):
        """Send a page-carrying frame (``pages`` stream or
        ``fetch_reply``) over the negotiated transport: a /dev/shm
        put when both ends advertised same-host ``put_pages``, else
        inline socket bytes — the segment holds EXACTLY the bytes the
        socket body would, so the two paths are bit-identical on
        install.  Raises OSError like ``conn.send`` (callers' peer
        failover paths apply unchanged)."""
        from .transport import put_capability, put_eligible, put_write
        if bufs and put_eligible(put_capability(), conn.peer_put):
            path, sizes = put_write(bufs)
            try:
                conn.send(kind, dict(
                    meta, put={"path": path, "sizes": sizes}), ())
            except BaseException:
                # the peer never got the frame: the segment has no
                # unlinker left — reclaim it before re-raising into
                # the caller's drop/abandon path
                try:
                    os.unlink(path)
                except OSError:
                    pass
                raise
            # receipt is invisible to the sender (the receiver
            # unlinks at open); a receiver that dies between our send
            # and its open strands the segment — our pid-prefixed
            # name makes it sweepable (put_sweep at our exit, or the
            # router's by-pid sweep if WE are the one killed)
            self.pages_put_total += int(meta.get("n", len(bufs)))
            self.put_bytes_total += sum(sizes)
        else:
            conn.send(kind, meta, bufs)

    def _serve_fetches(self):
        """Answer queued sibling FETCH requests (also called while
        WAITING on our own fetch — two replicas fetching from each
        other must not deadlock).  The reply goes out on EVERY exit
        edge: if serving the fetch raises, the requester gets an n=0
        miss NOW instead of waiting out its full fetch timeout on a
        reply that will never come — and one bad fetch must not take
        down the whole worker (proto-reply-pairing's checked
        invariant)."""
        while True:
            try:
                meta, bufs, conn = self.fetch_inbox.get_nowait()
            except queue.Empty:
                return
            reply_bufs = []
            n_full = 0
            try:
                tokens = np.frombuffer(bytes(bufs[0]), np.int32)
                if self.eng.prefix is not None:
                    # restore=False: serving a sibling must not spend
                    # OUR pool pages re-installing spilled chains —
                    # the spilled tail ships straight from host DRAM
                    entries, pages, m = self.eng.prefix.match(
                        tokens, restore=False)
                    try:
                        n_hot = min(len(pages),
                                    m // self.eng.page_size)
                        parts = []
                        if n_hot:
                            parts.append(self.eng.cache.export_pages(
                                pages[:n_hot]))
                        # round 18: spilled continuation off the host
                        # tier — a spilled chain stays P2P-fetchable,
                        # and CHEAPER to serve (no device gather)
                        tail = self.eng.prefix.spilled_content(
                            tokens, n_hot)
                        n_full = n_hot + len(tail)
                        parts.extend(tail)
                        if parts:
                            from .page_streamer import (
                                merge_page_content, pages_to_bufs)
                            reply_bufs = pages_to_bufs(
                                merge_page_content(parts))
                    finally:
                        self.eng.prefix.release(entries)
            except Exception:
                # degrade to a miss: the requester falls back to a
                # cold prefill instead of eating its fetch timeout
                n_full, reply_bufs = 0, []
            try:
                self._send_pages_frame(
                    conn, "fetch_reply",
                    {"n": n_full, "fid": meta.get("fid"),
                     "trace_id": meta.get("trace_id"),
                     "t_send": time.perf_counter()},
                    reply_bufs)
                self.fetch_bytes += sum(
                    memoryview(b).nbytes for b in reply_bufs)
            except OSError:
                pass                      # requester died: their loss

    def _fetch_remote(self, owner, tokens, timeout=15.0,
                      peer_tier=None, trace_id=None):
        """Fetch the longest cached chain for ``tokens`` from a
        sibling replica and graft it into the local trie.  A miss (or
        a dead/slow peer) degrades to a cold local prefill — the
        exactness contract never depends on the fetch.  ``peer_tier``
        is the router's tag for the owner's copy (``hbm``/``host``) —
        accounting only: a spilled peer chain serves from its host
        tier without a device gather, and the per-tier hit counters
        are how the tier-sweep benchmark prices that difference."""
        from .page_streamer import bufs_to_pages, _release
        self._fetch_seq += 1
        fid = self._fetch_seq
        try:
            conn = self._peer_conn(owner)
            conn.send("fetch", {"fid": fid, "trace_id": trace_id},
                      [np.ascontiguousarray(tokens).data])
        except (OSError, KeyError):
            return 0
        deadline = time.perf_counter() + timeout
        while True:
            left = deadline - time.perf_counter()
            if left <= 0:
                # a reply may still be in flight on this cached conn;
                # drop the conn so a LATER fetch cannot mistake the
                # stale reply (old tokens' page bytes!) for its own
                self._peer_conns.pop(owner, None)
                conn.close()
                return 0
            got = conn.recv(timeout=min(left, 0.05))
            if got == "timeout":
                self._serve_fetches()     # break fetch-fetch deadlock
                continue
            if got is None:
                self._peer_conns.pop(owner, None)
                return 0
            kind, meta, bufs = got
            if kind != "fetch_reply" or meta.get("fid") != fid:
                _release(bufs)            # stale put reply: unmap it
                continue                  # stale/uncorrelated frame
            break
        n = meta["n"]
        if not n:
            return 0
        ps = self.eng.page_size
        ids = self.eng.cache.alloc(n)
        if ids is None:
            _release(bufs)                # put segment: unmap now
            return 0                      # pool too tight: stay cold
        self.eng.cache.install_pages(
            ids, bufs_to_pages(self.eng.cache, n, bufs))
        _release(bufs)
        created = self.eng.prefix.insert_chain(
            tokens[:n * ps], ids, upto_page=n)
        created_idx = {j for j, _ in created}
        # pages whose chain position was already cached locally stay
        # unowned — free them instead of leaking
        extra = [ids[j] for j in range(n) if j not in created_idx]
        if extra:
            self.eng.cache.free(extra)
        # the fetched entries are cache-owned (refcount 0 until a
        # request maps them); drop the donor refs insert_chain took
        self.eng.prefix.release([e for _, e in created])
        self.remote_hits += 1
        self.remote_hit_tokens += n * ps
        if peer_tier == "host":
            self.remote_hits_host_tier += 1
        self.transfer_ms.append(
            (time.perf_counter() - meta["t_send"]) * 1e3)
        # bytes are counted SENDER-side only (the owner's
        # _serve_fetches), matching the prefill→decode stream
        # convention — counting here too would double every fetch in
        # cluster_page_bytes_streamed_total
        return n * ps

    # -- message handling -------------------------------------------
    def _handle(self, kind, meta, bufs, conn):
        if kind == "submit":
            inp = np.frombuffer(bytes(bufs[0]), np.int32)
            if meta["gen"] < self._fenced.get(meta["rid"], -1):
                # a late dispatch racing an abort for a NEWER
                # incarnation of the same rid: the router no longer
                # wants this gen — admitting it would resurrect a
                # fenced zombie (proto-gen-fence checked invariant)
                return
            t_recv = time.perf_counter()
            tid = meta.get("trace_id")
            self._flight.record("submit_recv", rid=meta["rid"],
                                gen=meta["gen"], trace_id=tid)
            self._spans.instant(meta["rid"], "submit_recv", t_recv,
                                trace_id=tid)
            if meta.get("hint") and self.eng.prefix is not None:
                # round 18: the local depth a fetch must beat counts
                # BOTH tiers — hot trie pages and spilled (host-tier)
                # pages, which restore for one install.  A peer copy
                # wins only on strictly deeper coverage: transfer
                # competes with transfer, not with prefill
                # (probe_depth takes no refs and restores nothing).
                hot, warm = self.eng.prefix.probe_depth(inp)
                if meta["hint_depth"] > hot + warm:
                    t0f = time.perf_counter()
                    got = self._fetch_remote(
                        meta["hint"], inp,
                        peer_tier=meta.get("hint_tier"),
                        trace_id=tid)
                    # the remote-hit transfer, visible INSIDE this
                    # request's TTFT span in the merged dump
                    self._spans.span(
                        meta["rid"], "fetch", t0f,
                        time.perf_counter(), trace_id=tid,
                        cat="transport",
                        args={"owner": meta["hint"],
                              "hit_tokens": got})
            try:
                erid = self.eng.submit(
                    inp, 1 if self.role == "prefill"
                    else meta["max_new"], eos_id=meta["eos"],
                    trace_id=tid)
            except Exception as e:
                # a request THIS engine rejects fails alone — it must
                # not take the worker (and every other request on it)
                # down with it
                self.router.send("reqfail", {"rid": meta["rid"],
                                             "gen": meta["gen"],
                                             "msg": repr(e)})
                return
            self.by_erid[erid] = {"rid": meta["rid"],
                                  "gen": meta["gen"],
                                  "meta": meta, "inp": inp,
                                  "t0": t_recv}
            self.by_rid[meta["rid"]] = erid
            self._reported[meta["rid"]] = 0
        elif kind == "pages":
            key = tuple(meta["srid"])
            if key[1] < self._fenced.get(key[0], -1):
                return                    # zombie incarnation's frame
            try:
                self.receiver.on_pages(key, meta["start"],
                                       meta["n"], bufs)
            except RuntimeError:
                # a gapped stream cannot be resumed; drop ITS staging
                # and let the router's reassignment recover — one bad
                # stream must not take down the whole worker
                self.receiver.abort(key)
                return
            now = time.perf_counter()
            self.transfer_ms.append((now - meta["t_send"]) * 1e3)
            self._flight.record("pages_recv", rid=key[0],
                                start=meta["start"], n=meta["n"])
            # the prefill->decode page transfer as a transport-lane
            # span: t0 is the SENDER's t_send on the same-host
            # monotonic clock (the h_transfer convention)
            self._spans.span(key[0], "transfer", meta["t_send"], now,
                             trace_id=meta.get("trace_id"),
                             cat="transport",
                             args={"start": meta["start"],
                                   "pages": meta["n"]})
        elif kind == "handoff":
            key = tuple(meta["srid"])
            if key[1] < self._fenced.get(key[0], -1):
                return
            self.receiver.on_handoff(
                key, meta["total"],
                dict(meta, prompt=np.frombuffer(bytes(bufs[0]),
                                                np.int32)))
            self._flight.record("handoff_recv", rid=key[0],
                                total=meta["total"])
            self._spans.instant(key[0], "handoff_recv",
                                time.perf_counter(),
                                trace_id=meta.get("trace_id"))
        elif kind == "abort":
            # flight record AFTER the fenced abort: protolint's
            # gen-fence rule wants no state touched before the fence
            self._abort(meta["rid"], meta["below_gen"])
            self._flight.record("abort", rid=meta["rid"],
                                below_gen=meta["below_gen"])
        elif kind == "cancel":
            # round 20: client-disconnect propagation.  Same fencing
            # and cleanup as a failover abort — drop staged pages,
            # force-retire the engine request (pages + slot recycle
            # NOW, not at generation end) — but nothing resubmits
            # afterwards: the router already retired the request.  A
            # late cancel for a gen that already died is a no-op by
            # the same fence.
            self._abort(meta["rid"], meta["below_gen"])
            self._flight.record("cancel", rid=meta["rid"],
                                trace_id=meta.get("trace_id"))
        elif kind == "drop":
            key = tuple(meta["srid"])
            if key[1] < self._fenced.get(key[0], -1):
                return                    # zombie incarnation's frame
            # the prefill side completed this request itself: free
            # any staged pages of its stream
            self.receiver.abort(key)
        elif kind == "peers":
            # live peer-map refresh (router add_worker/scale-up):
            # only ever grows or re-addresses — cached conns to
            # still-present peers stay valid
            self.peers = meta["peers"]
        elif kind == "stats_req":
            self._send_stats(sid=meta.get("sid"))
        elif kind == "clock_req":
            # ping-pong clock-offset probe (round 23): echo the
            # router's t0 with OUR clock read, immediately — any
            # extra queueing here inflates the RTT estimate, and the
            # router's min-RTT filter discards the sample
            try:
                self.router.send("clock",
                                 {"seq": meta["seq"],
                                  "t0": meta["t0"],
                                  "t_worker": time.perf_counter()})
            except OSError:
                self._running = False
        elif kind == "caps":
            pass                          # recorded on the conn by recv
        elif kind == "_wake":
            pass                          # fetch_inbox wake token
        elif kind in ("shutdown", "_lost"):
            self._running = False

    def _abort(self, rid, below_gen):
        """Fence a resubmitted incarnation: drop staged pages and any
        running engine request with an older gen; remember the fence
        so the zombie's LATE frames drop instead of recreating
        staging."""
        if below_gen > self._fenced.get(rid, -1):
            self._fenced[rid] = below_gen
            if len(self._fenced) > 4096:  # bound: oldest rids first
                for k in sorted(self._fenced)[:1024]:
                    del self._fenced[k]
        for key in [k for k in self.receiver.staged_rids
                    if k[0] == rid and k[1] < below_gen]:
            self.receiver.abort(key)
        erid = self.by_rid.get(rid)
        if erid is not None and self.by_erid[erid]["gen"] < below_gen:
            self.by_erid.pop(erid)
            self.by_rid.pop(rid, None)
            self._reported.pop(rid, None)
            self._decode_t0.pop(rid, None)
            self.streamer.drop(erid)
            if erid in self.eng.requests:
                self.eng.cancel(erid)
                del self.eng.requests[erid]

    # -- per-step work ----------------------------------------------
    def _admit_ready(self):
        """Decode role: admit handed-off requests whose pages are all
        installed, as slots free up.  Installs themselves run AFTER
        the step (round 21 — off the dispatch critical path, hidden
        behind the launched step's device time under overlap); when
        the engine is idle there is nothing to hide behind, so
        install eagerly here."""
        if self.eng._inflight is None and not any(
                s is not None for s in self.eng._slots):
            self.receiver.retry_installs()
        for key in list(self.receiver.staged_rids):
            if not self.receiver.ready(key):
                continue
            if self.eng.free_slots == 0:
                return
            pages, meta = self.receiver.take(key)
            rid, gen = key
            erid = self.eng.admit_prefilled(
                meta["prompt"], meta["toks"], pages,
                max_new_tokens=meta["max_new"], eos_id=meta["eos"])
            self.by_erid[erid] = {"rid": rid, "gen": gen,
                                  "meta": meta}
            self.by_rid[rid] = erid
            t_admit = time.perf_counter()
            self._decode_t0[rid] = t_admit
            self._flight.record("admit", rid=rid, gen=gen)
            self._spans.instant(rid, "admit_prefilled", t_admit,
                                trace_id=meta.get("trace_id"))
            # report from zero: the handoff tokens travel to the
            # router in OUR stream (single FIFO connection), not the
            # prefill worker's — cross-connection ordering is the
            # race _on_handed documents
            self._reported[rid] = 0

    def _abandon(self, erid, st):
        """The decode peer is unreachable (connect refused, or a send
        died mid-stream — which also means the decode side's in-order
        page stream now has a gap): abandon this incarnation and hand
        the request BACK to the router for reassignment.  Merely
        relying on decode-death failover is not enough — the peer
        PROCESS may be alive with only the data-plane link broken,
        and its heartbeats would keep the watchdog quiet forever."""
        try:
            self.router.send("lost", {"rid": st["rid"],
                                      "gen": st["gen"]})
        except OSError:
            pass                          # router gone: shutting down
        self.streamer.drop(erid)
        self.by_erid.pop(erid, None)
        self.by_rid.pop(st["rid"], None)
        self._reported.pop(st["rid"], None)
        if erid in self.eng.requests:
            if self.eng.requests[erid].state in ("queued", "running"):
                self.eng.cancel(erid)
            del self.eng.requests[erid]

    def _stream_pages(self, finished):
        """Prefill role: after a step, stream newly-completed pages of
        every in-flight handoff; finish the stream + hand off for
        requests that sampled their token this step."""
        fin = set(finished or ())
        for erid, st in list(self.by_erid.items()):
            req = self.eng.requests.get(erid)
            if req is None:
                continue
            final = erid in fin
            dec = self._conn_or_none(st["meta"]["decode"])
            if final:
                out = self.streamer.pump(
                    erid, st.get("final_n_cached", req.n_cached),
                    st.get("final_pages", req.pages), final=True)
            else:
                out = self.streamer.pump(erid, req.n_cached,
                                         req.pages)
            if out is not None and dec is not None:
                start, n, bufs = out
                try:
                    self._send_pages_frame(
                        dec, "pages",
                        {"srid": (st["rid"], st["gen"]),
                         "start": start, "n": n,
                         "trace_id": st["meta"].get("trace_id"),
                         "t_send": time.perf_counter()}, bufs)
                    self._flight.record("pages_sent", rid=st["rid"],
                                        start=start, n=n)
                except OSError:
                    self._drop_peer(st["meta"]["decode"])
                    dec = None            # gap in the stream: abandon
            if dec is None and st["meta"]["max_new"] > 1:
                self._abandon(erid, st)
                continue
            if final:
                toks = [int(t) for t in req.generated]
                total = self.streamer.pending(erid)
                remaining = st["meta"]["max_new"] - len(toks)
                eos = st["meta"]["eos"]
                if eos is not None and toks and toks[-1] == eos:
                    remaining = 0         # eos at prefill: complete
                if remaining > 0:
                    try:
                        dec.send(
                            "handoff",
                            {"srid": (st["rid"], st["gen"]),
                             "total": total, "toks": toks,
                             "max_new": st["meta"]["max_new"],
                             "eos": st["meta"]["eos"],
                             "trace_id":
                                 st["meta"].get("trace_id")},
                            [np.ascontiguousarray(st["inp"]).data])
                    except OSError:
                        # the decode side never got the handoff:
                        # reporting "handed" anyway would strand the
                        # request on a worker that keeps heartbeating
                        self._drop_peer(st["meta"]["decode"])
                        self._report_inserts(
                            req, st.get("final_chain_upto", 0))
                        self._abandon(erid, st)
                        continue
                    # phase flip only — the decode worker reports the
                    # tokens (see _on_handed)
                    self.router.send("handed", {"rid": st["rid"],
                                                "gen": st["gen"]})
                else:
                    # 1-token budget / eos at prefill: prefill was
                    # the whole request — tell the decode side to
                    # drop any pages already streamed to it, or they
                    # leak in its staging
                    self.router.send("done", {"rid": st["rid"],
                                              "gen": st["gen"],
                                              "toks": toks})
                    if dec is not None:
                        try:
                            dec.send("drop",
                                     {"srid": (st["rid"],
                                               st["gen"])})
                        except OSError:
                            pass
                t1 = time.perf_counter()
                tid = st["meta"].get("trace_id")
                self._spans.span(st["rid"], "prefill",
                                 st.get("t0", t1), t1, trace_id=tid,
                                 args={"toks": len(toks),
                                       "pages": total,
                                       "handed": remaining > 0})
                self._flight.record(
                    "handoff_sent" if remaining > 0 else "done",
                    rid=st["rid"], total=total)
                self._report_inserts(req,
                                     st.get("final_chain_upto", 0))
                self.streamer.drop(erid)
                self.by_erid.pop(erid, None)
                self.by_rid.pop(st["rid"], None)
                self._reported.pop(st["rid"], None)
                del self.eng.requests[erid]

    def _report_inserts(self, req, chain_upto):
        """Tell the router which chains this replica now holds
        (``chain_upto`` from the retire-time snapshot — ``_release``
        zeroes the live field before this runs)."""
        if self.eng.prefix is None or chain_upto == 0:
            return
        keys = chain_keys(req.prompt,
                          self.eng.page_size)[:chain_upto]
        if keys:
            try:
                self.router.send("insert", {"keys": keys})
            except OSError:
                pass

    def _flush_tokens(self, finished):
        """Decode role: stream each request's newly committed tokens;
        DONE when finished."""
        fin = set(finished or ())
        for erid, st in list(self.by_erid.items()):
            req = self.eng.requests.get(erid)
            if req is None:
                continue
            rid = st["rid"]
            new = [int(t) for t in
                   req.generated[self._reported.get(rid, 0):]]
            if erid in fin:
                self.router.send("done", {"rid": rid,
                                          "gen": st["gen"],
                                          "toks": new})
                # decode span closes with the request: its token
                # count equals the committed stream the router saw
                # for this incarnation (decode reports from zero) —
                # the trace-merge reconciliation the slow tier pins
                t1 = time.perf_counter()
                self._spans.span(
                    rid, "decode", self._decode_t0.pop(rid, t1), t1,
                    trace_id=st["meta"].get("trace_id"),
                    args={"toks": len(req.generated)})
                self._flight.record("done", rid=rid,
                                    toks=len(req.generated))
                self.by_erid.pop(erid, None)
                self.by_rid.pop(rid, None)
                self._reported.pop(rid, None)
                del self.eng.requests[erid]
            elif new:
                self.router.send("tokens", {"rid": rid,
                                            "gen": st["gen"],
                                            "toks": new})
                self._reported[rid] = len(req.generated)

    def _conn_or_none(self, name):
        try:
            return self._peer_conn(name)
        except (OSError, KeyError):
            return None

    def _drop_peer(self, name):
        """Evict a cached peer connection after a send failure — the
        Connection object never learns its socket died, so leaving it
        cached would poison every later send to that peer even after
        the peer recovers (the next ``_peer_conn`` reconnects)."""
        conn = self._peer_conns.pop(name, None)
        if conn is not None:
            conn.close()

    def _maybe_send_stats(self):
        """Rate-limited periodic stats tick (the main loop's path);
        the `stats_req` reply rides :meth:`_send_stats` directly — a
        rate limit on the reply path would DROP solicited replies
        and stall the router's cluster_stats() round trip."""
        if time.perf_counter() - self._last_stats < 0.25:
            return
        self._send_stats()
        # span shipping rides the same tick but NOT _send_stats
        # itself: that is the stats_req reply path and must stay
        # call-free (proto-reply-pairing)
        spans = self._spans.drain()
        if spans:
            try:
                self.router.send("spans", {"spans": spans})
            except OSError:
                self._running = False

    def _send_stats(self, sid=None):
        """Send one stats frame NOW.  This is the `stats_req` →
        `stats` reply path, so it must reach the send on every exit
        edge (proto-reply-pairing): no early returns; the only
        excused failure is the router connection itself dying."""
        self._last_stats = time.perf_counter()
        eng = self.eng
        prefix = eng.prefix
        stats = {
            "role": self.role,
            "steps": eng.stats["steps"],
            "prefill_rows": eng.stats["prefill_rows"],
            "decode_rows": eng.stats["decode_rows"],
            "preemptions": eng.stats["preemptions"],
            "prefix_hit_tokens": eng.stats["prefix_hit_tokens"],
            "pages_in_use": eng.cache.pages_in_use,
            "free_pages": eng.cache.free_pages,
            "prefix_cached_pages":
                0 if prefix is None else prefix.cached_pages,
            "prefix_refs": 0 if prefix is None else prefix.refs_total,
            "active_requests": len(self.by_erid),
            "staged_rids": len(self.receiver.staged_rids),
            "remote_hits": self.remote_hits,
            "remote_hit_tokens": self.remote_hit_tokens,
            "remote_hits_host_tier": self.remote_hits_host_tier,
            "prefix_spilled_pages":
                0 if prefix is None else prefix.spilled_pages,
            "warm_hits": 0 if prefix is None
                else prefix.warm_hits_total,
            "warm_hit_tokens": 0 if prefix is None
                else prefix.warm_hit_tokens_total,
            "swap_outs": eng.stats["swap_outs"],
            "swap_ins": eng.stats["swap_ins"],
            "overlap_steps": eng.stats["overlap_steps"],
            "overlap_fences": eng.stats["overlap_fences"],
            "host_hidden_ms": eng.stats["host_hidden_ms"],
            # inlined (not eng.tier.stats()): this fn is the
            # stats_req reply path, so the dict build must be
            # call-free — proto-reply-pairing's exception-edge rule
            "tier": None if eng.tier is None else {
                "pages_held": eng.tier.pages_held,
                "bytes_held": eng.tier.bytes_held,
                "budget_bytes": eng.tier.budget_bytes,
                "spilled_pages_total": eng.tier.spilled_pages_total,
                "installed_pages_total":
                    eng.tier.installed_pages_total,
                "bytes_moved_total": eng.tier.bytes_moved_total,
                "evicted_pages_total": eng.tier.evicted_pages_total,
                "evictions_total": eng.tier.evictions_total},
            "bytes_streamed": self.streamer.bytes_streamed_total
            + self.fetch_bytes,
            "pages_streamed": self.streamer.pages_streamed_total,
            "pages_installed": self.receiver.pages_installed_total,
            # round 22 put-transport accounting: logical page bytes
            # above count IDENTICALLY on both transports (the perf
            # counters measure pages moved, not socket bytes); these
            # say how many rode /dev/shm puts instead of the socket
            "pages_put": self.pages_put_total,
            "put_bytes": self.put_bytes_total,
            # send-then-clear: the router OBSERVES every sample it
            # receives into the transfer histogram, so samples must
            # travel exactly once (re-sending a sliding window would
            # re-observe lingering samples every 0.25 s tick)
            "transfer_ms": self.transfer_ms,
        }
        self.transfer_ms = []
        if self._tier_moves:
            moves, self._tier_moves = self._tier_moves, {}
            by_tier: Dict[str, List[bytes]] = {}
            for k, t in moves.items():
                by_tier.setdefault(t, []).append(k)
            for t, keys in by_tier.items():
                try:
                    self.router.send("tier", {"keys": keys,
                                              "tier": t})
                except OSError:
                    pass
        if self._evicted_keys:
            keys, self._evicted_keys = self._evicted_keys, []
            try:
                self.router.send("evict", {"keys": keys})
            except OSError:
                pass
        try:
            self.router.send("stats", {"stats": stats, "sid": sid})
        except OSError:
            self._running = False

    # -- main loop ---------------------------------------------------
    def run(self):
        try:
            while self._running:
                drained = False
                while True:
                    try:
                        item = self.inbox.get_nowait()
                    except queue.Empty:
                        break
                    drained = True
                    self._handle(*item)
                self._serve_fetches()
                if not self._running:
                    break
                if self.role == "decode":
                    self._admit_ready()
                busy = bool(self.eng._queue) or any(
                    s is not None for s in self.eng._slots)
                if busy:
                    finished = self.eng.step()
                    self._flight.record(
                        "step", active=len(self.by_erid),
                        finished=len(finished or ()))
                    if self.role == "prefill":
                        self._stream_pages(finished)
                    else:
                        # staged-page installs land here, AFTER the
                        # step — overlapped with the dispatched
                        # step's device time, not serialized between
                        # admission and dispatch (round 21)
                        self.receiver.retry_installs()
                        self._flush_tokens(finished)
                elif not drained:
                    try:
                        item = self.inbox.get(timeout=0.02)
                        self._handle(*item)
                    except queue.Empty:
                        pass
                self._maybe_send_stats()
        except Exception as e:
            try:
                self.router.send("error", {"msg": repr(e)})
            except OSError:
                pass
            raise
        finally:
            # last-gasp span ship: a worker shut down between 0.25 s
            # stats ticks (every sub-second run) still delivers its
            # staged spans — without this a short-lived cluster's
            # merged trace shows the router talking to silence
            spans = self._spans.drain()
            if spans:
                try:
                    self.router.send("spans", {"spans": spans})
                except OSError:
                    pass
            self.listener.close()
            self.router.close()
            for c in self._peer_conns.values():
                c.close()
            # orderly exit needs no forensics: unlink our flight
            # ring (a SIGKILL skips this finally — that file IS the
            # evidence the router recovers)
            self._flight.record("exit")
            self._flight.close(unlink=True)
            # reclaim any put segment we wrote whose receiver never
            # opened it (peer died mid-flight): our pid prefixes
            # every segment name
            from .transport import put_sweep
            put_sweep()


def _disagg_worker_entry(name, role, router_host, router_port):
    """Spawned-process entry point (multiprocessing spawn target).

    Exits via ``os._exit``: a worker that ran its engine has live
    PJRT/XLA thread pools whose C++ static destructors abort
    (``std::terminate``) under normal interpreter teardown; the
    router tracks liveness by connection EOF, so skipping teardown
    loses nothing."""
    try:
        _DisaggWorker(name, role, router_host, router_port).run()
    except BaseException:
        import traceback
        traceback.print_exc()
        os._exit(1)
    os._exit(0)


def run_worker():
    """Externally-launched worker entry (``tools/launch.py --launcher
    serve`` or bare env): connects to the router named by
    ``MXNET_SERVE_ROUTER_HOST``/``MXNET_SERVE_ROUTER_PORT`` as
    ``MXNET_SERVE_WORKER`` with role ``MXNET_SERVE_ROLE``."""
    _disagg_worker_entry(
        os.environ["MXNET_SERVE_WORKER"],
        os.environ.get("MXNET_SERVE_ROLE", "prefill"),
        os.environ.get("MXNET_SERVE_ROUTER_HOST", "127.0.0.1"),
        int(os.environ["MXNET_SERVE_ROUTER_PORT"]))
