"""Streaming HTTP/SSE front door for the serving clusters (round 20).

Until this round every byte of the serving stack — engine, cluster,
disaggregated router, autoscaler, goodput gates — was reachable only
by a Python caller in the same process.  This module is the real
front door (ROADMAP item 6): a **stdlib-asyncio HTTP/1.1 server** (no
third-party dependency; request parsing and chunked transfer encoding
are hand-rolled here) fronting either :class:`ServingCluster` or
:class:`DisaggServingCluster`.

* **Token streaming** — ``POST /v1/generate`` with ``"stream": true``
  answers as Server-Sent Events (``text/event-stream`` over chunked
  transfer encoding): one ``token`` event per committed token, fed
  from the cluster's per-token failover log via
  ``cluster.attach_stream`` — the same token list a failover would
  replay, so a stream survives replica/worker death without a gap or
  a repeat.  The bridge from the thread-based cluster into asyncio is
  one ``loop.call_soon_threadsafe`` enqueue per event batch; the
  event loop never blocks on ``result()``.
* **Cancellation propagation** — a client disconnect (read-side EOF
  or a write error) cancels the request end-to-end via
  ``cluster.cancel(rid)``: pages and slot are recycled immediately on
  in-process replicas (before the engine's next step completes), and
  the disaggregated router sends the gen-fenced ``cancel`` wire kind
  to both assigned workers.
* **Edge admission control** — per-tenant API keys
  (:class:`ApiKeyTable`; a static JSON file / dict / the
  ``MXNET_SERVE_KEYS`` env var) with token-bucket rate limits and
  max-in-flight quotas enforced BEFORE ``submit()``.  Quota breach →
  ``429`` with ``Retry-After``; unknown key → ``401``; oversized body
  → ``413``; ``ClusterOverloaded`` → ``429`` with the cluster's own
  ``retry_after_s`` hint (clamped to the watchdog).  Every response
  carries an ``X-Request-Id`` header for trace correlation.
* **Observability** — ``GET /metrics`` serves the round-8 Prometheus
  text exposition (:func:`mxnet_tpu.obs.prometheus_text`), ``GET
  /healthz`` the cluster's ``health()`` snapshot; the front door's
  own counters (streams, disconnects, edge rejections) land on the
  cluster registry when metrics are enabled.

Env (docs/env_vars.md): ``MXNET_SERVE_KEYS`` (path to, or inline,
key-table JSON), ``MXNET_SERVE_HTTP_PORT``,
``MXNET_SERVE_HTTP_MAX_BODY`` (bytes, default 1 MiB),
``MXNET_SERVE_HTTP_MAX_CONNECTIONS`` (default 1024 — over the cap new
connections get ``503`` and are closed).

Load proof: ``benchmark/http_bench.py`` — a many-hundred-connection
open-loop asyncio client replaying the round-16 trace format over
real loopback sockets, with slow-client (trickle-read) backpressure
and a mass-disconnect storm mid-burst; hard-fails unless completed
streams are bit-identical to ``generate``, zero pages/refs leak after
the storm, and the edge 429 count matches the quota arithmetic
exactly.  Gate: ``gpt_http_stream_ttfb_ms``.

Clock: ``time.perf_counter`` throughout (the serving trace clock;
mxlint ``clock-mix`` enforces it for this package).

API reference: ``docs/http_api.md``.
"""
from __future__ import annotations

import asyncio
import itertools
import json
import math
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .cluster import ClusterOverloaded

__all__ = ["HttpFrontend", "ApiKeyTable", "TokenBucket",
           "parse_request_head", "sse_event", "chunk"]

_MiB = 1 << 20


def _env_int(name, fallback):
    v = os.environ.get(name)
    if v is None or v == "":
        return fallback
    try:
        return int(v)
    except ValueError:
        raise ValueError("%s=%r: expected int" % (name, v))


# ---------------------------------------------------------------------------
# wire-format helpers (pure functions: the FAST-tier unit surface)
# ---------------------------------------------------------------------------

def parse_request_head(head: bytes) -> Tuple[str, str, Dict[str, str]]:
    """Parse an HTTP/1.1 request head (everything up to and including
    the blank line) into ``(method, path, headers)`` with
    lower-cased, last-wins header names.  Raises ``ValueError`` on a
    malformed head — the caller answers 400."""
    try:
        text = head.decode("latin-1")
    except UnicodeDecodeError:              # pragma: no cover (latin-1
        raise ValueError("undecodable request head")  # never raises)
    lines = text.split("\r\n")
    parts = lines[0].split(" ")
    if len(parts) != 3 or not parts[2].startswith("HTTP/1."):
        raise ValueError("malformed request line: %r" % lines[0])
    method, path = parts[0], parts[1]
    if not path.startswith("/"):
        raise ValueError("malformed path: %r" % path)
    headers: Dict[str, str] = {}
    for ln in lines[1:]:
        if not ln:
            continue
        if ":" not in ln:
            raise ValueError("malformed header line: %r" % ln)
        k, v = ln.split(":", 1)
        headers[k.strip().lower()] = v.strip()
    return method, path, headers


def sse_event(event: str, data: dict) -> bytes:
    """One Server-Sent-Events frame: ``event:`` name + one-line JSON
    ``data:`` payload, blank-line terminated."""
    return ("event: %s\ndata: %s\n\n"
            % (event, json.dumps(data, separators=(",", ":")))
            ).encode()


def chunk(payload: bytes) -> bytes:
    """One HTTP/1.1 chunked-transfer-encoding chunk (hex length line,
    payload, CRLF).  ``chunk(b"")`` is the terminal chunk."""
    return b"%x\r\n%s\r\n" % (len(payload), payload)


def _status_line(code: int) -> bytes:
    reason = {200: "OK", 400: "Bad Request", 401: "Unauthorized",
              404: "Not Found", 405: "Method Not Allowed",
              408: "Request Timeout", 411: "Length Required",
              413: "Payload Too Large", 429: "Too Many Requests",
              500: "Internal Server Error",
              503: "Service Unavailable"}.get(code, "Error")
    return b"HTTP/1.1 %d %s\r\n" % (code, reason.encode())


# ---------------------------------------------------------------------------
# edge admission: API keys, token buckets, in-flight quotas
# ---------------------------------------------------------------------------

class TokenBucket:
    """Classic token bucket: ``burst`` capacity refilled at ``rate``
    tokens/s.  ``rate`` 0 means no refill (a hard burst budget — the
    quota-arithmetic shape the load proof checks exactly);
    ``rate`` None means unlimited.  Single-threaded by design: the
    front door mutates quota state only on its event loop."""

    def __init__(self, rate: Optional[float], burst: int):
        self.rate = rate
        self.burst = int(burst)
        self.tokens = float(burst)
        self.t = time.perf_counter()

    def take(self, now: Optional[float] = None):
        """Try to take one token.  Returns ``(ok, retry_after_s)``;
        ``retry_after_s`` is None when the bucket never refills."""
        if self.rate is None:
            return True, 0.0
        if now is None:
            now = time.perf_counter()
        if self.rate > 0:
            self.tokens = min(float(self.burst),
                              self.tokens + (now - self.t) * self.rate)
        self.t = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True, 0.0
        if self.rate > 0:
            return False, (1.0 - self.tokens) / self.rate
        return False, None


class _Tenant:
    __slots__ = ("name", "bucket", "max_in_flight", "in_flight",
                 "accepted", "rejected")

    def __init__(self, name, rate, burst, max_in_flight):
        self.name = name
        self.bucket = TokenBucket(rate, burst)
        self.max_in_flight = max_in_flight
        self.in_flight = 0
        self.accepted = 0
        self.rejected = 0


class ApiKeyTable:
    """Static per-tenant API keys with admission quotas.

    The table maps **key string → tenant spec**::

        {"sk-tenant-a": {"tenant": "a", "rate": 10.0, "burst": 20,
                         "max_in_flight": 8},
         "sk-tenant-b": {"tenant": "b"}}          # unlimited

    Spec fields (all optional): ``tenant`` (display name, defaults to
    the key), ``rate`` (token-bucket refill per second; 0 = hard
    burst budget, absent = unlimited), ``burst`` (bucket capacity,
    default ``max(1, ceil(rate))``), ``max_in_flight`` (concurrent
    admitted requests, absent = unlimited).

    ``load()`` accepts a dict, inline JSON, or a file path — the
    ``MXNET_SERVE_KEYS`` env var takes either of the latter two."""

    def __init__(self, specs: Dict[str, dict]):
        self.tenants: Dict[str, _Tenant] = {}
        for key, spec in specs.items():
            spec = dict(spec or {})
            rate = spec.get("rate")
            if rate is not None:
                rate = float(rate)
            burst = int(spec.get("burst",
                                 1 if rate is None
                                 else max(1, math.ceil(rate))))
            mif = spec.get("max_in_flight")
            self.tenants[key] = _Tenant(
                spec.get("tenant", key), rate, burst,
                None if mif is None else int(mif))

    @classmethod
    def load(cls, spec) -> "ApiKeyTable":
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, dict):
            return cls(spec)
        text = str(spec)
        if text.lstrip().startswith("{"):
            return cls(json.loads(text))
        with open(text) as f:
            return cls(json.load(f))

    def lookup(self, key: Optional[str]) -> Optional[_Tenant]:
        if key is None:
            return None
        return self.tenants.get(key)

    def snapshot(self) -> List[dict]:
        return [{"tenant": t.name, "in_flight": t.in_flight,
                 "accepted": t.accepted, "rejected": t.rejected}
                for t in self.tenants.values()]


# ---------------------------------------------------------------------------
# the server
# ---------------------------------------------------------------------------

class _HttpObs:
    """Front-door instrument bundle on the CLUSTER registry — the
    front door is an edge of the cluster, not a separate system, so
    its counters scrape alongside ``cluster_*``."""

    def __init__(self, registry):
        c, g = registry.counter, registry.gauge
        self.requests = c("http_requests_total",
                          "HTTP requests parsed (all endpoints)")
        self.streams = c("http_streams_total",
                         "SSE generate streams opened")
        self.rej_auth = c("http_rejected_auth_total",
                          "401s: missing/unknown API key")
        self.rej_quota = c("http_rejected_quota_total",
                           "429s: tenant rate/in-flight quota, or "
                           "cluster backpressure surfaced at the "
                           "edge")
        self.rej_body = c("http_rejected_body_total",
                          "413s: body over MXNET_SERVE_HTTP_MAX_BODY")
        self.disconnects = c("http_client_disconnects_total",
                             "mid-stream client disconnects "
                             "propagated to cancel(rid)")
        self.g_conns = g("http_connections",
                         "currently open HTTP connections")


class HttpFrontend:
    """Asyncio HTTP/1.1 + SSE server over a serving cluster.

    ``start()`` runs the event loop on a daemon thread and returns
    once the socket is bound (``self.port`` then holds the real
    port); ``close()`` stops it.  The server owns NO cluster
    lifecycle — closing the front door leaves the cluster running.

    Endpoints (full reference: ``docs/http_api.md``):

    * ``POST /v1/generate`` — body ``{"prompt": [ints], "max_new_tokens":
      N, "eos_id"?, "ttl_s"?, "stream"?}``; SSE stream or JSON.
    * ``GET /healthz`` — cluster ``health()`` as JSON.
    * ``GET /metrics`` — Prometheus text exposition.
    """

    def __init__(self, cluster, *, host="127.0.0.1", port=None,
                 keys=None, max_body=None, max_connections=None):
        self.cluster = cluster
        self.host = host
        if port is None:
            port = _env_int("MXNET_SERVE_HTTP_PORT", 0)
        self.port = int(port)
        if max_body is None:
            max_body = _env_int("MXNET_SERVE_HTTP_MAX_BODY", _MiB)
        self.max_body = int(max_body)
        if max_connections is None:
            max_connections = _env_int(
                "MXNET_SERVE_HTTP_MAX_CONNECTIONS", 1024)
        self.max_connections = int(max_connections)
        if keys is None:
            keys = os.environ.get("MXNET_SERVE_KEYS") or None
        self.keys = None if keys is None else ApiKeyTable.load(keys)
        reg = cluster.registry
        self._obs = _HttpObs(reg) if reg is not None else None
        self._rid_seq = itertools.count(1)
        self._active = 0                   # event-loop-thread only
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stop: Optional[asyncio.Event] = None
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._startup_error: Optional[BaseException] = None

    # ------------------------------------------------------ lifecycle --
    def start(self):
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="http-frontend")
        self._thread.start()
        if not self._ready.wait(30) or self._startup_error is not None:
            raise RuntimeError("HttpFrontend failed to start: %r"
                               % (self._startup_error,))
        return self

    def _run(self):
        try:
            asyncio.run(self._main())
        except BaseException as e:          # surface bind errors etc.
            self._startup_error = e
            self._ready.set()

    async def _main(self):
        self._loop = asyncio.get_running_loop()
        self._stop = asyncio.Event()
        server = await asyncio.start_server(
            self._serve_conn, self.host, self.port, limit=256 * 1024)
        self.port = server.sockets[0].getsockname()[1]
        self._ready.set()
        async with server:
            await self._stop.wait()
        # asyncio.run cancels lingering per-connection tasks on exit

    def close(self, timeout=10.0):
        if self._loop is not None and self._stop is not None:
            try:
                self._loop.call_soon_threadsafe(self._stop.set)
            except RuntimeError:
                pass                       # loop already gone
        if self._thread is not None:
            self._thread.join(timeout)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.close()

    # ------------------------------------------------------ plumbing --
    async def _send(self, writer, code, body: bytes,
                    ctype="application/json", req_id=None,
                    extra=(), close=False):
        head = [_status_line(code),
                b"Content-Type: %s\r\n" % ctype.encode(),
                b"Content-Length: %d\r\n" % len(body)]
        if req_id is not None:
            head.append(b"X-Request-Id: %s\r\n" % req_id.encode())
        for k, v in extra:
            head.append(("%s: %s\r\n" % (k, v)).encode())
        head.append(b"Connection: close\r\n" if close
                    else b"Connection: keep-alive\r\n")
        head.append(b"\r\n")
        writer.write(b"".join(head) + body)
        await writer.drain()

    async def _send_json(self, writer, code, obj, req_id=None,
                         extra=(), close=False):
        await self._send(writer, code,
                         json.dumps(obj).encode() + b"\n",
                         req_id=req_id, extra=extra, close=close)

    @staticmethod
    def _auth_key(headers) -> Optional[str]:
        auth = headers.get("authorization")
        if auth and auth.lower().startswith("bearer "):
            return auth[7:].strip()
        return headers.get("x-api-key")

    # ---------------------------------------------------- connection --
    async def _serve_conn(self, reader, writer):
        obs = self._obs
        if self._active >= self.max_connections:
            # over the edge cap: refuse outright — the bounded
            # admission queue is the CLUSTER's backpressure; this cap
            # protects the event loop itself
            try:
                await self._send_json(
                    writer, 503, {"error": "connection limit"},
                    req_id="r%06d" % next(self._rid_seq),
                    extra=[("Retry-After", "1")], close=True)
            except OSError:
                pass
            writer.close()
            try:
                # close() only schedules the close — wait for the
                # transport to drain so refused connections can't
                # pile up half-closed under an overload burst
                await writer.wait_closed()
            except OSError:
                pass
            return
        self._active += 1
        if obs is not None:
            obs.g_conns.set(self._active)
        try:
            await self._conn_loop(reader, writer)
        except (asyncio.IncompleteReadError, asyncio.LimitOverrunError,
                ConnectionResetError, BrokenPipeError,
                asyncio.TimeoutError, OSError):
            pass                           # peer went away mid-parse
        finally:
            self._active -= 1
            if obs is not None:
                obs.g_conns.set(self._active)
            writer.close()
            try:
                await writer.wait_closed()
            except OSError:
                pass

    async def _conn_loop(self, reader, writer):
        """Keep-alive loop: one request head at a time; SSE responses
        and error paths close the connection."""
        while True:
            try:
                head = await reader.readuntil(b"\r\n\r\n")
            except asyncio.IncompleteReadError:
                return                     # clean keep-alive close
            except asyncio.LimitOverrunError:
                # head larger than the stream limit (256 KiB): answer
                # like every other malformed input instead of a
                # silent close
                await self._send_json(
                    writer, 400, {"error": "request head too large"},
                    req_id="r%06d" % next(self._rid_seq), close=True)
                return
            try:
                method, path, headers = parse_request_head(head)
            except ValueError as e:
                await self._send_json(
                    writer, 400, {"error": str(e)},
                    req_id="r%06d" % next(self._rid_seq), close=True)
                return
            req_id = "r%06d" % next(self._rid_seq)
            if self._obs is not None:
                self._obs.requests.inc()
            # honor the client's keep-alive choice: a `Connection:
            # close` request gets its response and the socket closed
            # (open-loop bench clients read-until-EOF per request)
            want_close = headers.get("connection",
                                     "").lower() == "close"
            if path == "/healthz" or path == "/metrics":
                if method != "GET":
                    await self._send_json(
                        writer, 405, {"error": "GET only"},
                        req_id=req_id, close=True)
                    return
                if path == "/healthz":
                    await self._handle_healthz(writer, req_id)
                else:
                    await self._handle_metrics(writer, req_id)
                if want_close:
                    return
                continue
            if path == "/debug/statusz" \
                    or path.startswith("/debug/trace/"):
                # ops introspection (round 23): same operator surface
                # class as /metrics — GET-only, unauthenticated,
                # read-only snapshots off the executor
                if method != "GET":
                    await self._send_json(
                        writer, 405, {"error": "GET only"},
                        req_id=req_id, close=True)
                    return
                if path == "/debug/statusz":
                    await self._handle_statusz(writer, req_id)
                else:
                    await self._handle_trace(writer, path, req_id)
                if want_close:
                    return
                continue
            if path != "/v1/generate":
                await self._send_json(
                    writer, 404, {"error": "unknown path %s" % path},
                    req_id=req_id, close=True)
                return
            if method != "POST":
                await self._send_json(
                    writer, 405, {"error": "POST only"},
                    req_id=req_id, close=True)
                return
            closing = await self._handle_generate(
                reader, writer, headers, req_id)
            if closing or want_close:
                return

    async def _handle_healthz(self, writer, req_id):
        health = await self._in_executor(self.cluster.health)
        ok = any(h.get("alive") for h in health)
        body = {"ok": ok, "health": health}
        if self.keys is not None:
            body["tenants"] = self.keys.snapshot()
        await self._send_json(writer, 200 if ok else 503, body,
                              req_id=req_id)

    async def _handle_metrics(self, writer, req_id):
        from ..obs import prometheus_text
        text = await self._in_executor(prometheus_text)
        await self._send(writer, 200, text.encode(),
                         ctype="text/plain; version=0.0.4",
                         req_id=req_id)

    def _in_executor(self, fn, *args):
        return asyncio.get_running_loop().run_in_executor(
            None, fn, *args)

    # ----------------------------------------------- debug (rnd 23) --
    async def _handle_statusz(self, writer, req_id):
        """``GET /debug/statusz``: live topology, per-worker health /
        clock offsets / tier occupancy, in-flight request states, and
        SLO burn gauges — whatever snapshot the attached cluster
        flavor provides."""
        fn = getattr(self.cluster, "debug_status", None)
        if fn is None:
            await self._send_json(
                writer, 404,
                {"error": "cluster has no debug_status surface",
                 "request_id": req_id}, req_id=req_id, close=True)
            return
        status = await self._in_executor(fn)
        status["request_id"] = req_id
        await self._send_json(writer, 200, status, req_id=req_id)

    async def _handle_trace(self, writer, path, req_id):
        """``GET /debug/trace/<rid>``: the router's view of one
        request's timeline plus every span workers shipped for it."""
        tail = path[len("/debug/trace/"):]
        try:
            rid = int(tail)
        except ValueError:
            await self._send_json(
                writer, 400,
                {"error": "bad rid %r" % tail, "request_id": req_id},
                req_id=req_id, close=True)
            return
        fn = getattr(self.cluster, "request_trace", None)
        if fn is None:
            await self._send_json(
                writer, 404,
                {"error": "cluster has no request_trace surface",
                 "request_id": req_id}, req_id=req_id, close=True)
            return
        try:
            trace = await self._in_executor(fn, rid)
        except KeyError:
            await self._send_json(
                writer, 404,
                {"error": "unknown rid %d" % rid,
                 "request_id": req_id}, req_id=req_id, close=True)
            return
        trace["request_id"] = req_id
        await self._send_json(writer, 200, trace, req_id=req_id)

    # ------------------------------------------------------ generate --
    async def _handle_generate(self, reader, writer, headers, req_id):
        """Returns True when the connection must close (SSE/errors)."""
        obs = self._obs
        # ---- edge admission, strictly BEFORE submit(): auth is
        # checked on the headers alone (an unauthorized caller must
        # not cost a body buffer), size on the declared length, and
        # the quota spend comes last — only a request that would
        # otherwise be admitted drains the bucket
        tenant = None
        if self.keys is not None:
            tenant = self.keys.lookup(self._auth_key(headers))
            if tenant is None:
                if obs is not None:
                    obs.rej_auth.inc()
                await self._send_json(
                    writer, 401, {"error": "unknown or missing API "
                                           "key", "request_id": req_id},
                    req_id=req_id, close=True)
                return True
        # ---- body size: declared length is checked before the quota
        # spend — an oversized request is refused on its headers alone
        # and must not burn a bucket token (the load proof's 429
        # arithmetic counts only well-formed requests)
        clen = headers.get("content-length")
        if clen is None or not clen.isdigit():
            await self._send_json(
                writer, 411, {"error": "Content-Length required",
                              "request_id": req_id},
                req_id=req_id, close=True)
            return True
        clen = int(clen)
        if clen > self.max_body:
            if obs is not None:
                obs.rej_body.inc()
            await self._send_json(
                writer, 413,
                {"error": "body %d > max %d bytes"
                 % (clen, self.max_body), "request_id": req_id},
                req_id=req_id, close=True)
            return True
        body = await reader.readexactly(clen)
        try:
            req = json.loads(body)
            prompt = np.asarray(req["prompt"], np.int32).reshape(-1)
            max_new = int(req.get("max_new_tokens", 16))
            eos_id = req.get("eos_id")
            ttl_s = req.get("ttl_s")
            stream = bool(req.get("stream", True))
        except (ValueError, KeyError, TypeError) as e:
            await self._send_json(
                writer, 400, {"error": "bad request body: %r" % (e,),
                              "request_id": req_id},
                req_id=req_id, close=True)
            return True
        # ---- quota spend, last edge stop before submit(): only a
        # well-formed, rightly-sized, authenticated request costs a
        # bucket token or an in-flight slot — the load proof's 429
        # arithmetic depends on malformed traffic not draining quota
        if tenant is not None:
            if tenant.max_in_flight is not None \
                    and tenant.in_flight >= tenant.max_in_flight:
                tenant.rejected += 1
                if obs is not None:
                    obs.rej_quota.inc()
                await self._send_json(
                    writer, 429,
                    {"error": "tenant %s at max_in_flight %d"
                     % (tenant.name, tenant.max_in_flight),
                     "request_id": req_id},
                    req_id=req_id, extra=[("Retry-After", "1")],
                    close=True)
                return True
            ok, retry = tenant.bucket.take()
            if not ok:
                tenant.rejected += 1
                if obs is not None:
                    obs.rej_quota.inc()
                retry_s = 60.0 if retry is None else max(0.001, retry)
                await self._send_json(
                    writer, 429,
                    {"error": "tenant %s rate limit" % tenant.name,
                     "retry_after_s": retry_s, "request_id": req_id},
                    req_id=req_id,
                    extra=[("Retry-After",
                            str(int(math.ceil(retry_s))))],
                    close=True)
                return True
            # past every edge check: the request is edge-ACCEPTED
            # (what happens next — ClusterOverloaded, engine error —
            # is the cluster's accounting, not the tenant quota's, so
            # accepted + rejected partitions the tenant's traffic)
            tenant.accepted += 1
        # ---- submit (executor: submit takes the cluster lock)
        if tenant is not None:
            tenant.in_flight += 1
        try:
            return await self._run_request(
                writer, reader, prompt, max_new, eos_id, ttl_s,
                stream, req_id)
        finally:
            if tenant is not None:
                tenant.in_flight -= 1

    def _submit(self, prompt, max_new, eos_id, ttl_s, req_id):
        # the edge mints the trace context: X-Request-Id IS the
        # trace_id, so the access log, the engine trace instants, and
        # the cluster-wide merged trace all correlate by one string
        kw = {"trace_id": req_id}
        if ttl_s is not None:
            kw["ttl_s"] = float(ttl_s)
        try:
            return self.cluster.submit(prompt, max_new,
                                       eos_id=eos_id, **kw)
        except TypeError:
            # older cluster flavors: shed optional kwargs (disagg has
            # no TTL support; pre-round-23 clusters no trace_id),
            # never the request
            try:
                kw.pop("ttl_s", None)
                return self.cluster.submit(prompt, max_new,
                                           eos_id=eos_id, **kw)
            except TypeError:
                return self.cluster.submit(prompt, max_new,
                                           eos_id=eos_id)

    async def _run_request(self, writer, reader, prompt, max_new,
                           eos_id, ttl_s, stream, req_id):
        obs = self._obs
        loop = asyncio.get_running_loop()
        try:
            rid = await self._in_executor(
                lambda: self._submit(prompt, max_new, eos_id, ttl_s,
                                     req_id))
        except ClusterOverloaded as e:
            if obs is not None:
                obs.rej_quota.inc()
            retry_s = e.retry_after_s or 1.0
            await self._send_json(
                writer, 429,
                {"error": str(e), "retry_after_s": retry_s,
                 "request_id": req_id},
                req_id=req_id,
                extra=[("Retry-After", str(int(math.ceil(retry_s))))],
                close=True)
            return True
        except ValueError as e:
            await self._send_json(
                writer, 400, {"error": str(e), "request_id": req_id},
                req_id=req_id, close=True)
            return True
        except Exception as e:
            await self._send_json(
                writer, 503, {"error": repr(e),
                              "request_id": req_id},
                req_id=req_id, close=True)
            return True
        q: "asyncio.Queue" = asyncio.Queue()

        def feed(evt):
            # called from a CLUSTER thread: the only cross-thread
            # touch on asyncio state.  The loop can close between the
            # last token and the callback (front door shutting down
            # mid-stream) — drop the event rather than crash the
            # cluster's completion thread
            try:
                loop.call_soon_threadsafe(q.put_nowait, evt)
            except RuntimeError:
                pass

        await self._in_executor(self.cluster.attach_stream, rid, feed)
        if stream:
            if obs is not None:
                obs.streams.inc()
            await self._stream_sse(writer, reader, q, rid, prompt,
                                   req_id)
            return True                    # SSE always closes
        return await self._respond_json(writer, reader, q, rid,
                                        prompt, req_id)

    async def _wait_stream_event(self, getter, monitor_box, reader,
                                 rid):
        """Await the next queue event while watching the socket's read
        side (``monitor_box`` holds the one live read task so callers
        can re-arm/cancel it).  Returns the event, or None when the
        client disconnected (EOF/RST — the request is cancelled here).
        Data arriving mid-wait (a pipelined next request) keeps the
        stream alive but is DROPPED and flags the connection to close
        after the in-flight response — this server does not support
        HTTP pipelining, and closing is the honest refusal (the
        client retries; we never misparse a stolen byte)."""
        pipelined = False
        while True:
            done, _ = await asyncio.wait(
                {getter, monitor_box[0]},
                return_when=asyncio.FIRST_COMPLETED)
            if monitor_box[0] in done:
                try:
                    data = monitor_box[0].result()
                except (ConnectionResetError, BrokenPipeError,
                        OSError):
                    data = b""             # RST reads as a raise
                if not data:               # EOF: client disconnected
                    getter.cancel()
                    await self._cancel_disconnected(rid)
                    return None, pipelined
                pipelined = True
                monitor_box[0] = asyncio.ensure_future(
                    reader.read(4096))
                if getter in done:
                    return getter.result(), pipelined
                continue
            return getter.result(), pipelined

    async def _respond_json(self, writer, reader, q, rid, prompt,
                            req_id):
        """JSON mode shares the SSE path's disconnect detection: the
        read side is watched while the request runs, so a gone client
        cancels the request instead of decoding to completion for
        nobody."""
        monitor_box = [asyncio.ensure_future(reader.read(4096))]
        getter = None
        must_close = False
        try:
            while True:
                getter = asyncio.ensure_future(q.get())
                evt, pipelined = await self._wait_stream_event(
                    getter, monitor_box, reader, rid)
                must_close = must_close or pipelined
                if evt is None:            # disconnected
                    return True
                kind, payload = evt
                if kind == "tokens":
                    continue               # buffered by the cluster
                if kind == "done":
                    # retire the monitor BEFORE writing: a cancelled-
                    # in-time read leaves the next keep-alive
                    # request's bytes in the stream buffer; one that
                    # already completed stole them (pipelining or an
                    # EOF racing the response) — then close, so a
                    # stolen byte can never misparse request N+1.
                    # The cancel must be AWAITED: StreamReader allows
                    # one waiter, and the next readuntil would hit
                    # "another coroutine is already waiting" while
                    # the cancelled read is still pending
                    mon = monitor_box[0]
                    if mon.done():
                        must_close = True
                    else:
                        mon.cancel()
                        try:
                            await mon
                        except (asyncio.CancelledError, OSError):
                            pass
                    await self._send_json(
                        writer, 200,
                        {"request_id": req_id, "rid": rid,
                         "prompt_len": int(prompt.size),
                         "tokens": [int(t) for t in
                                    payload[prompt.size:]]},
                        req_id=req_id, close=must_close)
                    return must_close      # keep-alive unless flagged
                await self._send_json(     # ("error", exc)
                    writer, 503,
                    {"error": repr(payload), "request_id": req_id,
                     "rid": rid},
                    req_id=req_id, close=True)
                return True
        finally:
            monitor_box[0].cancel()
            if getter is not None and not getter.done():
                getter.cancel()

    async def _stream_sse(self, writer, reader, q, rid, prompt,
                          req_id):
        """The SSE hot path.  Disconnect detection is the read side:
        a well-behaved SSE client sends nothing after the request, so
        the pending ``reader.read`` completes only on EOF/reset —
        which is exactly the moment to ``cancel(rid)``.  Write errors
        (peer gone mid-burst) propagate the same way."""
        writer.write(
            _status_line(200)
            + b"Content-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n"
            b"Transfer-Encoding: chunked\r\n"
            + b"X-Request-Id: %s\r\n" % req_id.encode()
            + b"Connection: close\r\n\r\n")
        n_sent = 0
        monitor_box = [asyncio.ensure_future(reader.read(4096))]
        getter = None
        try:
            await writer.drain()
            while True:
                getter = asyncio.ensure_future(q.get())
                evt, _ = await self._wait_stream_event(
                    getter, monitor_box, reader, rid)
                if evt is None:            # EOF: client disconnected
                    return
                kind, payload = evt
                if kind == "tokens":
                    out = b"".join(
                        chunk(sse_event("token",
                                        {"i": n_sent + j, "t": t}))
                        for j, t in enumerate(payload))
                    n_sent += len(payload)
                    writer.write(out)
                    await writer.drain()
                elif kind == "done":
                    writer.write(chunk(sse_event(
                        "done", {"request_id": req_id, "rid": rid,
                                 "prompt_len": int(prompt.size),
                                 "n": n_sent})) + chunk(b""))
                    await writer.drain()
                    return
                else:
                    writer.write(chunk(sse_event(
                        "error", {"error": repr(payload),
                                  "request_id": req_id})) + chunk(b""))
                    await writer.drain()
                    return
        except (ConnectionResetError, BrokenPipeError, OSError):
            await self._cancel_disconnected(rid)
        finally:
            monitor_box[0].cancel()
            if getter is not None and not getter.done():
                getter.cancel()

    async def _cancel_disconnected(self, rid):
        if self._obs is not None:
            self._obs.disconnects.inc()
        try:
            await self._in_executor(self.cluster.cancel, rid)
        except KeyError:
            pass                           # already purged: moot


# ---------------------------------------------------------------------------
# CLI: `python -m mxnet_tpu.serving.http_frontend` — the demo/ops
# entry `tools/launch.py --launcher http` wraps (random-weights model;
# production embeds HttpFrontend over its own cluster + params)
# ---------------------------------------------------------------------------

def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=None,
                    help="default: MXNET_SERVE_HTTP_PORT or an "
                         "OS-assigned port (printed at startup)")
    ap.add_argument("--keys", default=None, metavar="FILE|JSON",
                    help="API key table (default: MXNET_SERVE_KEYS; "
                         "absent = open access)")
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--disagg", action="store_true",
                    help="front a DisaggServingCluster (spawns "
                         "--prefill/--decode worker processes)")
    ap.add_argument("--prefill", type=int, default=1)
    ap.add_argument("--decode", type=int, default=1)
    ap.add_argument("--vocab", type=int, default=4096)
    ap.add_argument("--max-len", type=int, default=256)
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--n-layers", type=int, default=4)
    ap.add_argument("--d-ff", type=int, default=1024)
    ap.add_argument("--num-slots", type=int, default=8)
    ap.add_argument("--page-size", type=int, default=16)
    args = ap.parse_args(argv)

    import jax
    from ..models import gpt
    cfg = gpt.gpt_config(
        vocab_size=args.vocab, max_len=args.max_len,
        d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff, dropout=0.0,
        use_flash=False, remat=False, dtype="float32")
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    kw = dict(num_slots=args.num_slots, page_size=args.page_size,
              metrics=True)
    if args.disagg:
        from .cluster import DisaggServingCluster
        cl = DisaggServingCluster(params, cfg, prefill=args.prefill,
                                  decode=args.decode, **kw)
    else:
        from .cluster import ServingCluster
        cl = ServingCluster(params, cfg, replicas=args.replicas, **kw)
    fe = HttpFrontend(cl, host=args.host, port=args.port,
                      keys=args.keys).start()
    print(json.dumps({"listening": "%s:%d" % (fe.host, fe.port),
                      "disagg": bool(args.disagg)}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        fe.close()
        cl.close()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
