"""Continuous-batching GPT serving engine over a paged KV cache.

The fixed-batch decode entry points (``models/gpt.py generate`` /
``generate_speculative``) assume a batch of requests that start and
finish together, with one contiguous max-seq KV allocation per slot —
real mixed-length traffic pays padding in both HBM and tokens/sec.
This package is the Orca-style fix: in-flight (iteration-level)
batching with a vLLM-style paged KV cache.

- ``paged_kv.PagedKVCache`` — fixed-size pages in one preallocated
  pool per layer, per-request block tables, host-side free-list
  allocator, int8-KV supported via the existing per-(row, token)
  scale layout.
- ``engine.ServingEngine`` — admits new requests into free decode
  slots each iteration, runs (chunked) prefill for admitted requests
  and one decode step for running requests in a SINGLE compiled XLA
  program (padded to static slot/page shapes: exactly one compilation
  per config), retires finished sequences, and recycles their pages.

Benchmark: ``benchmark/serve_bench.py`` (Poisson arrivals over a mixed
prompt/output-length distribution); gate ``gpt_serve_mixed_tok_s``.
Exactness: paged greedy decode is token-identical to ``generate``
under f32 (``tests/test_serving.py``).
"""
from .paged_kv import PagedKVCache
from .engine import Request, ServingEngine

__all__ = ["PagedKVCache", "Request", "ServingEngine"]
