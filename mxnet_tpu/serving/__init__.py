"""Continuous-batching GPT serving engine over a paged KV cache.

The fixed-batch decode entry points (``models/gpt.py generate`` /
``generate_speculative``) assume a batch of requests that start and
finish together, with one contiguous max-seq KV allocation per slot —
real mixed-length traffic pays padding in both HBM and tokens/sec.
This package is the Orca-style fix: in-flight (iteration-level)
batching with a vLLM-style paged KV cache.

- ``paged_kv.PagedKVCache`` — fixed-size pages in one preallocated
  pool per layer, per-request block tables, host-side free-list
  allocator, int8-KV supported via the existing per-(row, token)
  scale layout.
- ``engine.ServingEngine`` — admits new requests into free decode
  slots each iteration, runs (chunked) prefill for admitted requests
  and one decode step for running requests in a SINGLE compiled XLA
  program (padded to static slot/page shapes: exactly one compilation
  per config), retires finished sequences, and recycles their pages.

Round 10 adds the cluster layer above the engine:

- ``prefix_cache.PrefixCache`` — refcounted shared-prefix page reuse
  inside the paged pool: prompt pages are content-keyed per prefix
  chain, matching requests map them read-only (copy-on-write at the
  first divergent token), refcount-0 chains are LRU-evicted under
  pool pressure.  ``ServingEngine(prefix_cache=True)``.
- ``cluster.ServingCluster`` — N engine replicas (threads
  in-process) behind one async ``submit()/result()`` API:
  least-loaded routing with prefix affinity, bounded admission queue
  with backpressure + per-request TTL, health checks, watchdog
  failover with recompute-exact resubmission, graceful
  drain/scale-down.

Round 11 adds the raw-decode-speed levers (ROADMAP item 2):

- ``ServingEngine(kernel="pallas")`` — the step program attends via
  the fused block-table-walk Pallas kernel
  (``kernels/paged_attention.py``: online-softmax over pages, int8
  dequant in the inner loop, no materialized gather); ``"xla"`` keeps
  the gather + ``_attend_rows`` path, cross-checked by tests.
- ``ServingEngine(spec_K=K)`` — in-engine speculative decode:
  host-side drafting (``drafters.ngram_draft``) feeds K extra rows
  per decode slot into the SAME step program, which verifies every
  row's drafts in one batched forward; accepts commit by pointer
  advance, rejections roll back exactly.

Benchmark: ``benchmark/serve_bench.py`` (Poisson arrivals over a mixed
prompt/output-length distribution; ``--replicas N
--shared-prefix-frac F`` for the cluster section; ``--kernel`` /
``--spec-K`` / ``--kernel-ablation`` / ``--spec-sweep`` for the
round-11 levers); gates ``gpt_serve_mixed_tok_s`` /
``gpt_serve_prefix_hit_ttft_ms`` / ``gpt_serve_decode_step_ms``.
Exactness: paged greedy decode is token-identical to ``generate``
under f32, through the cluster as well — prefix hits, COW divergence,
mid-flight replica failure, either attention kernel, and speculation
with arbitrary drafters included (``tests/test_serving.py``,
``tests/test_serving_cluster.py``).

Round 15 disaggregates the cluster across OS processes
(ROADMAP item 3):

- ``transport.py`` — framed zero-copy messaging over the
  ``parallel/dist.py`` raw-frame wire (tensor bytes never pickle).
- ``page_streamer.py`` — prefill→decode KV-page streaming pipelined
  with prefill chunks; decode-side staging installer.
- ``cluster.DisaggServingCluster`` — router + spawned prefill/decode
  worker PROCESSES: chunked prefill on one process streams int8/f32
  KV pages to a decode process that picks the request up at
  ``n_cached = prompt_len``; the prefix trie's knowledge lives in a
  router-owned ``ClusterPrefixIndex`` so a hot prefix is prefilled
  once per CLUSTER and fetched (raw page bytes) by whoever needs it;
  SIGKILL of any worker fails over recompute-exact from the token
  stream.  ``serve_bench --disagg``;
  ``gpt_serve_disagg_remote_hit_ttft_ms`` gate;
  ``tests/test_serving_disagg.py`` (slow group j).

Round 16 adds the traffic-realism layer (ROADMAP item 2):

- ``autoscaler.Autoscaler`` — a metrics-driven control loop over the
  ``cluster_*`` gauges/histograms that drives the clusters' scaling
  actuation paths (``add_replica``/``remove_replica`` thread
  replicas; role-aware ``add_worker``/``drain_worker`` disagg worker
  processes) with hysteresis, cooldowns, and a replica budget;
  scale-down drains gracefully under a CHECKED zero-leak contract.
- ``chaos.ChaosDriver`` — seeded, trace-relative fault injection
  (injected replica death/stall in-process; real SIGKILL/SIGSTOP/
  connection-reset for disagg worker processes), so "replica death
  during the burst" is a reproducible scenario.
- ``ClusterOverloaded.retry_after_s`` — a structured Retry-After
  hint from queue excess / recent drain rate (the future HTTP 429).
  Workload side: ``benchmark/traffic_trace.py`` (seeded diurnal +
  burst + heavy-tail traces, goodput SLO classification) and
  ``serve_bench --trace`` (open-loop replay + ``gpt_serve_goodput``
  gate; ``tests/test_serving_traffic.py``, slow group k).

Round 18 adds hierarchical KV tiering (ROADMAP item 4):

- ``tier_store.HostTierStore`` — a byte-budgeted host-DRAM LRU of
  exact pool-layout page bytes under every engine's pool
  (``ServingEngine(tier_bytes=N)`` / ``MXNET_SERVE_TIER_BYTES``):
  pressure-evicted refcount-0 prefix chains SPILL instead of drop
  and re-install as **warm hits** (the outcome between hot-hit and
  miss); preemption victims SWAP OUT and resume install-exact
  instead of recompute-exact — O(transfer), not O(prefill).  In the
  disaggregated cluster the router's ``ClusterPrefixIndex`` carries
  a per-key tier tag (``hbm``/``host``) and spilled chains stay
  peer-fetchable, served straight from the owner's host tier.
  ``serve_bench --tier-sweep``; ``gpt_serve_tier_hit_ttft_ms`` gate;
  ``tests/test_serving_tier.py`` (slow group l).
"""
from .paged_kv import PagedKVCache
from .prefix_cache import PrefixCache, ClusterPrefixIndex
from .drafters import ngram_draft
from .engine import Request, ServingEngine
from .tier_store import HostTierStore
from .cluster import (ServingCluster, ClusterRequest, ClusterOverloaded,
                      RequestExpired, RequestCancelled, ClusterClosed,
                      ClusterFailed, DisaggServingCluster, run_worker)
from .autoscaler import Autoscaler, HistogramWindow
from .chaos import ChaosDriver, ChaosEvent, chaos_schedule
from .http_frontend import HttpFrontend, ApiKeyTable

__all__ = ["PagedKVCache", "PrefixCache", "ClusterPrefixIndex",
           "HostTierStore", "Request", "ServingEngine",
           "ServingCluster", "ClusterRequest", "ClusterOverloaded",
           "RequestExpired", "RequestCancelled", "ClusterClosed",
           "ClusterFailed", "DisaggServingCluster", "run_worker",
           "ngram_draft", "Autoscaler", "HistogramWindow",
           "ChaosDriver", "ChaosEvent", "chaos_schedule",
           "HttpFrontend", "ApiKeyTable"]
