"""Message transport for the disaggregated serving cluster.

One layer above ``parallel/dist.py``'s wire: every message is a raw
frame (:func:`parallel.dist.send_frame`) whose pickled header is a
small control dict ``{"kind": str, ...}`` and whose buffers are raw
tensor bytes — **KV pages, prompts, and params never go through
pickle**.  The framing is the length-prefixed protocol the dist
KVStore already speaks, with the raw-flag bit selecting the zero-copy
path, so the hardening there (bounded prefixes, reset-as-EOF for the
process-kill path) covers this transport too.

Pieces:

* :class:`Connection` — one duplex framed socket: ``send(kind, meta,
  bufs)`` under a send lock (many threads may reply on one
  connection), ``recv(timeout)`` via ``select`` + a blocking frame
  read (the timeout applies to frame *arrival* only — a frame is
  never abandoned halfway, which would desynchronize the stream).
* :class:`Listener` — a listening socket handing accepted
  :class:`Connection` objects to a callback thread-per-peer (the
  per-replica page server: FETCH requests from sibling replicas,
  PAGES/HANDOFF streams from prefill to decode).
* :func:`tree_to_frames` / :func:`frames_to_tree` — numpy pytree
  (nested dict/list/tuple) codec over raw buffers: the router ships
  the model params to every worker process at handshake this way, so
  spawned workers need nothing but a socket address.

Byte accounting: every connection counts ``bytes_sent`` /
``bytes_received`` (header + buffers), which the workers roll up into
the router's ``cluster_page_bytes_streamed_total`` counter — the
prefill-once perf claim is *measured* in bytes moved, not asserted.
"""
from __future__ import annotations

import select
import socket
import threading
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..parallel.dist import recv_frame, send_frame

__all__ = ["Connection", "Listener", "tree_to_frames",
           "frames_to_tree", "connect"]


class Connection:
    """One framed duplex transport connection."""

    def __init__(self, sock: socket.socket):
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)
        self._sock = sock
        self._slock = threading.Lock()
        self.bytes_sent = 0
        self.bytes_received = 0
        self.closed = False

    def send(self, kind: str, meta: Optional[dict] = None, bufs=()):
        """Send one message; raises ``OSError`` on a dead peer (the
        caller decides whether that means failover or shutdown)."""
        head = {"kind": kind}
        if meta:
            head.update(meta)
        n = sum(memoryview(b).nbytes for b in bufs)
        with self._slock:
            send_frame(self._sock, head, bufs)
            self.bytes_sent += n

    def recv(self, timeout: Optional[float] = None):
        """Receive one message as ``(kind, meta, bufs)``; ``None`` on
        EOF/reset, the string ``"timeout"`` when no frame ARRIVED
        within ``timeout`` seconds (mid-frame reads always block to
        completion — a partially-consumed frame cannot be resumed)."""
        if timeout is not None:
            r, _, _ = select.select([self._sock], [], [], timeout)
            if not r:
                return "timeout"
        try:
            got = recv_frame(self._sock)
        except OSError:
            return None
        if got is None:
            return None
        meta, bufs = got
        bufs = bufs or []
        self.bytes_received += sum(len(b) for b in bufs)
        if not isinstance(meta, dict) or "kind" not in meta:
            return None                   # foreign frame: drop the conn
        return meta["kind"], meta, bufs

    def close(self):
        self.closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def __del__(self):
        try:
            self.close()
        except BaseException:
            pass


def connect(host: str, port: int, timeout: float = 10.0,
            retry_until: float = 0.0) -> Connection:
    """Connect, optionally retrying refused/unreachable attempts for
    ``retry_until`` seconds — an externally-launched worker may come
    up before the router process has bound its port."""
    import time
    deadline = time.perf_counter() + retry_until
    while True:
        try:
            return Connection(socket.create_connection(
                (host, port), timeout=timeout))
        except OSError:
            if time.perf_counter() >= deadline:
                raise
            time.sleep(0.1)


class Listener:
    """Accept loop handing each peer :class:`Connection` to
    ``handler(conn)`` on its own daemon thread."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    def start(self, handler: Callable[[Connection], None]):
        def loop():
            while not self._stop:
                try:
                    s, _ = self._sock.accept()
                except OSError:
                    return
                t = threading.Thread(target=handler,
                                     args=(Connection(s),), daemon=True)
                t.start()
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def close(self):
        self._stop = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# numpy-pytree <-> raw frames (params shipping at worker handshake)
# ---------------------------------------------------------------------------

def _flatten(tree, path, leaves):
    if isinstance(tree, dict):
        return {k: _flatten(v, path + (k,), leaves)
                for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        skel = [_flatten(v, path + (i,), leaves)
                for i, v in enumerate(tree)]
        return skel if isinstance(tree, list) else tuple(skel)
    leaves.append((path, np.asarray(tree)))
    return None                           # leaf slot in the skeleton


def tree_to_frames(tree) -> Tuple[dict, List]:
    """Flatten a nested dict/list/tuple of arrays into ``(meta,
    bufs)``: meta carries the container skeleton + per-leaf
    path/dtype/shape, bufs the raw array bytes in order."""
    leaves: List[Tuple[tuple, np.ndarray]] = []
    skel = _flatten(tree, (), leaves)
    meta = {"skel": skel,
            "leaves": [{"path": p, "dtype": str(a.dtype),
                        "shape": a.shape} for p, a in leaves]}
    from .page_streamer import _raw
    return meta, [_raw(a) for _, a in leaves]


def _np_dtype(name: str):
    """dtype-by-name, including the ml_dtypes extension types jax
    params use (bfloat16 & friends) when plain numpy cannot resolve
    them."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def frames_to_tree(meta: dict, bufs: List):
    """Inverse of :func:`tree_to_frames`."""
    tree = meta["skel"]
    # a bare-leaf tree (skeleton None) rebuilds from the single buffer
    if tree is None and len(meta["leaves"]) == 1 \
            and meta["leaves"][0]["path"] == ():
        lf = meta["leaves"][0]
        return np.frombuffer(bufs[0], _np_dtype(lf["dtype"])) \
            .reshape(lf["shape"])
    for lf, b in zip(meta["leaves"], bufs):
        # no bytes() copy: the whole params tree travels through here
        # at every worker handshake
        arr = np.frombuffer(b, _np_dtype(lf["dtype"])) \
            .reshape(lf["shape"])
        node = tree
        *parents, last = lf["path"]
        for k in parents:
            node = node[k]
        if isinstance(node, tuple):
            raise ValueError("frames_to_tree: tuple leaf containers "
                             "are not rebuildable in place; use lists")
        node[last] = arr
    return tree
