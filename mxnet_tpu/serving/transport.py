"""Message transport for the disaggregated serving cluster.

One layer above ``parallel/dist.py``'s wire: every message is a raw
frame (:func:`parallel.dist.send_frame`) whose pickled header is a
small control dict ``{"kind": str, ...}`` and whose buffers are raw
tensor bytes — **KV pages, prompts, and params never go through
pickle**.  The framing is the length-prefixed protocol the dist
KVStore already speaks, with the raw-flag bit selecting the zero-copy
path, so the hardening there (bounded prefixes, reset-as-EOF for the
process-kill path) covers this transport too.

Pieces:

* :class:`Connection` — one duplex framed socket: ``send(kind, meta,
  bufs)`` under a send lock (many threads may reply on one
  connection), ``recv(timeout)`` via ``select`` + a blocking frame
  read (the timeout applies to frame *arrival* only — a frame is
  never abandoned halfway, which would desynchronize the stream).
* :class:`Listener` — a listening socket handing accepted
  :class:`Connection` objects to a callback thread-per-peer (the
  per-replica page server: FETCH requests from sibling replicas,
  PAGES/HANDOFF streams from prefill to decode).
* :func:`tree_to_frames` / :func:`frames_to_tree` — numpy pytree
  (nested dict/list/tuple) codec over raw buffers: the router ships
  the model params to every worker process at handshake this way, so
  spawned workers need nothing but a socket address.

Byte accounting: every connection counts ``bytes_sent`` /
``bytes_received`` (header + buffers), which the workers roll up into
the router's ``cluster_page_bytes_streamed_total`` counter — the
prefill-once perf claim is *measured* in bytes moved, not asserted.

Round 22 — the ``put_pages`` capability: page-sized payloads
(``pages`` streams, ``fetch_reply`` bodies) between SAME-HOST workers
skip the socket body entirely.  The sender lands the raw pool bytes in
one ``/dev/shm`` segment (:func:`put_write`) and sends only a control
frame naming it (``meta["put"]``); :meth:`Connection.recv`
materializes the segment as zero-copy memoryviews (:class:`PutBufs`)
and unlinks the file at open, so on-disk segments exist only while a
frame is in flight.  Whether the path is live is NEGOTIATED: each side
of a data-plane connection sends a ``caps`` frame right after
connect/accept (:func:`put_capability`), and a sender puts only when
both sides advertise ``put_pages`` with the same host token — anything
else falls back to inline socket bytes, bit-identically (the segment
holds EXACTLY the bytes the socket body would).  ``/dev/shm`` + the
engine's donated install scatter (a ``jax.device_put`` of the mapped
pages) is the single-host stand-in for a true device-to-device ICI
put; docs/perf.md prices the two honestly.  ``MXNET_SERVE_TRANSPORT``
gates it: ``auto`` (default), ``socket`` (never advertise), ``put``
(advertise + assert used; tests force the path with it).
"""
from __future__ import annotations

import mmap
import os
import select
import socket
import tempfile
import threading
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..parallel.dist import recv_frame, send_frame

__all__ = ["Connection", "Listener", "tree_to_frames",
           "frames_to_tree", "connect", "put_capability",
           "put_write", "put_read", "put_sweep", "PutBufs",
           "PUT_DIR", "PUT_STATS"]

# --------------------------------------------------------------------------
# zero-copy same-host page puts (round 22)
# --------------------------------------------------------------------------

PUT_DIR = "/dev/shm" if os.path.isdir("/dev/shm") \
    else tempfile.gettempdir()
_PUT_PREFIX = "mxserve-put-"

# module-level open/release accounting: tests pin releases == opens
# (every materialized segment is explicitly released after install or
# abort — no held segment leaks past its staging record)
PUT_STATS = {"writes": 0, "opens": 0, "releases": 0}


def put_capability() -> Optional[dict]:
    """The capability dict this process advertises on data-plane
    connections, or ``None`` when the put path is disabled
    (``MXNET_SERVE_TRANSPORT=socket``).  The host token scopes the
    shared-memory domain: two workers may put to each other only when
    their tokens match (same kernel, same ``/dev/shm``)."""
    mode = os.environ.get("MXNET_SERVE_TRANSPORT", "auto")
    if mode == "socket":
        return None
    return {"put_pages": True, "host": socket.gethostname(),
            "dir": PUT_DIR}


def put_eligible(mine: Optional[dict],
                 theirs: Optional[dict]) -> bool:
    """Both ends advertised ``put_pages`` from the same shm domain?"""
    return (mine is not None and theirs is not None
            and bool(theirs.get("put_pages"))
            and mine.get("host") == theirs.get("host")
            and mine.get("dir") == theirs.get("dir"))


def put_write(bufs) -> Tuple[str, List[int]]:
    """Land raw buffers in one fresh shm segment; returns ``(path,
    sizes)`` for the control frame.  The file name carries the
    writer's pid so a supervisor can sweep a killed worker's
    unreceived segments (:func:`put_sweep`)."""
    fd, path = tempfile.mkstemp(
        prefix="%s%d-" % (_PUT_PREFIX, os.getpid()), dir=PUT_DIR)
    sizes = []
    try:
        with os.fdopen(fd, "wb") as f:
            for b in bufs:
                mv = memoryview(b)
                sizes.append(mv.nbytes)
                f.write(mv)
    except BaseException:
        try:
            os.unlink(path)
        except OSError:
            pass
        raise
    PUT_STATS["writes"] += 1
    return path, sizes


class PutBufs(list):
    """Received put-segment payload: zero-copy memoryviews into one
    shared mapping.  :meth:`release` drops the views and closes the
    map — the backing file was already unlinked at open, so release
    returns the memory to the kernel.  If an installer still exports
    a view (a device array aliasing host memory), closing degrades to
    dropping our references and the map closes with the last view."""

    def __init__(self, views: List[memoryview], mm_obj, base):
        super().__init__(views)
        self._mm = mm_obj
        self._base = base
        self.released = False

    def release(self):
        if self.released:
            return
        self.released = True
        PUT_STATS["releases"] += 1
        try:
            for v in self:
                v.release()
            self._base.release()
            self._mm.close()
        except BufferError:
            pass                          # exported view: GC closes it
        self._mm = self._base = None
        self[:] = []


def put_read(path: str, sizes: List[int]) -> PutBufs:
    """Open + map + UNLINK a put segment: the unlink is immediate so
    the filesystem namespace only ever holds in-flight segments; the
    mapping keeps the bytes alive until :meth:`PutBufs.release`."""
    fd = os.open(path, os.O_RDONLY)
    try:
        mm_obj = mmap.mmap(fd, 0, prot=mmap.PROT_READ)
    finally:
        os.close(fd)
    try:
        os.unlink(path)
    except OSError:
        pass                              # already swept: bytes live on
    base = memoryview(mm_obj)
    views, off = [], 0
    for n in sizes:
        views.append(base[off:off + n])
        off += n
    PUT_STATS["opens"] += 1
    return PutBufs(views, mm_obj, base)


def put_sweep(pid: Optional[int] = None) -> int:
    """Unlink leftover put segments — ours at orderly shutdown, or a
    KILLED worker's (by its pid) from the supervising router: a
    segment written microseconds before SIGKILL has no receiver left
    to unlink it.  Returns files removed."""
    import glob
    pat = os.path.join(PUT_DIR, "%s%s-*" % (
        _PUT_PREFIX, pid if pid is not None else os.getpid()))
    n = 0
    for p in glob.glob(pat):
        try:
            os.unlink(p)
            n += 1
        except OSError:
            pass
    return n


class Connection:
    """One framed duplex transport connection."""

    def __init__(self, sock: socket.socket):
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)
        self._sock = sock
        self._slock = threading.Lock()
        self.bytes_sent = 0
        self.bytes_received = 0
        self.closed = False
        # peer transport capability (round 22): recv records the
        # peer's `caps` frame here; senders consult it via
        # `put_eligible(put_capability(), conn.peer_put)`
        self.peer_put: Optional[dict] = None
        self.caps_seen = False

    def send(self, kind: str, meta: Optional[dict] = None, bufs=()):
        """Send one message; raises ``OSError`` on a dead peer (the
        caller decides whether that means failover or shutdown)."""
        head = {"kind": kind}
        if meta:
            head.update(meta)
        n = sum(memoryview(b).nbytes for b in bufs)
        with self._slock:
            send_frame(self._sock, head, bufs)
            self.bytes_sent += n

    def recv(self, timeout: Optional[float] = None):
        """Receive one message as ``(kind, meta, bufs)``; ``None`` on
        EOF/reset, the string ``"timeout"`` when no frame ARRIVED
        within ``timeout`` seconds (mid-frame reads always block to
        completion — a partially-consumed frame cannot be resumed)."""
        if timeout is not None:
            r, _, _ = select.select([self._sock], [], [], timeout)
            if not r:
                return "timeout"
        try:
            got = recv_frame(self._sock)
        except OSError:
            return None
        if got is None:
            return None
        meta, bufs = got
        bufs = bufs or []
        self.bytes_received += sum(len(b) for b in bufs)
        if not isinstance(meta, dict) or "kind" not in meta:
            return None                   # foreign frame: drop the conn
        if meta["kind"] == "caps":
            # handshake frame: record and surface (callers treat
            # unknown kinds as no-ops; wait_caps spins on caps_seen)
            self.peer_put = meta.get("put")
            self.caps_seen = True
        put = meta.get("put") if meta["kind"] != "caps" else None
        if put is not None:
            # put-transport frame: the body rides a shm segment, not
            # the socket — map it (and unlink NOW) so downstream code
            # sees ordinary zero-copy buffers
            try:
                bufs = put_read(put["path"], put["sizes"])
            except OSError:
                return None               # sender died mid-put: as EOF
            self.bytes_received += sum(v.nbytes for v in bufs)
        return meta["kind"], meta, bufs

    def send_caps(self):
        """Advertise this end's transport capability — the FIRST frame
        each side sends on a data-plane connection."""
        self.send("caps", {"put": put_capability()})

    def wait_caps(self, timeout: float = 5.0) -> Optional[dict]:
        """Connector-side half of the handshake: the acceptor's first
        frame is always its ``caps`` (sent before its handler can
        reply to anything), so one recv resolves it.  Returns the
        peer capability (or ``None`` on timeout/EOF — treated as a
        socket-only peer)."""
        import time
        deadline = time.perf_counter() + timeout
        while not self.caps_seen:
            left = deadline - time.perf_counter()
            if left <= 0:
                return None
            got = self.recv(timeout=left)
            if got in (None, "timeout"):
                return None
        return self.peer_put

    def close(self):
        self.closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def __del__(self):
        try:
            self.close()
        except BaseException:
            pass


def connect(host: str, port: int, timeout: float = 10.0,
            retry_until: float = 0.0) -> Connection:
    """Connect, optionally retrying refused/unreachable attempts for
    ``retry_until`` seconds — an externally-launched worker may come
    up before the router process has bound its port."""
    import time
    deadline = time.perf_counter() + retry_until
    while True:
        try:
            return Connection(socket.create_connection(
                (host, port), timeout=timeout))
        except OSError:
            if time.perf_counter() >= deadline:
                raise
            time.sleep(0.1)


class Listener:
    """Accept loop handing each peer :class:`Connection` to
    ``handler(conn)`` on its own daemon thread."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    def start(self, handler: Callable[[Connection], None]):
        def loop():
            while not self._stop:
                try:
                    s, _ = self._sock.accept()
                except OSError:
                    return
                t = threading.Thread(target=handler,
                                     args=(Connection(s),), daemon=True)
                t.start()
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def close(self):
        self._stop = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# numpy-pytree <-> raw frames (params shipping at worker handshake)
# ---------------------------------------------------------------------------

def _flatten(tree, path, leaves):
    if isinstance(tree, dict):
        return {k: _flatten(v, path + (k,), leaves)
                for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        skel = [_flatten(v, path + (i,), leaves)
                for i, v in enumerate(tree)]
        return skel if isinstance(tree, list) else tuple(skel)
    leaves.append((path, np.asarray(tree)))
    return None                           # leaf slot in the skeleton


def tree_to_frames(tree) -> Tuple[dict, List]:
    """Flatten a nested dict/list/tuple of arrays into ``(meta,
    bufs)``: meta carries the container skeleton + per-leaf
    path/dtype/shape, bufs the raw array bytes in order."""
    leaves: List[Tuple[tuple, np.ndarray]] = []
    skel = _flatten(tree, (), leaves)
    meta = {"skel": skel,
            "leaves": [{"path": p, "dtype": str(a.dtype),
                        "shape": a.shape} for p, a in leaves]}
    from .page_streamer import _raw
    return meta, [_raw(a) for _, a in leaves]


def _np_dtype(name: str):
    """dtype-by-name, including the ml_dtypes extension types jax
    params use (bfloat16 & friends) when plain numpy cannot resolve
    them."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def frames_to_tree(meta: dict, bufs: List):
    """Inverse of :func:`tree_to_frames`."""
    tree = meta["skel"]
    # a bare-leaf tree (skeleton None) rebuilds from the single buffer
    if tree is None and len(meta["leaves"]) == 1 \
            and meta["leaves"][0]["path"] == ():
        lf = meta["leaves"][0]
        return np.frombuffer(bufs[0], _np_dtype(lf["dtype"])) \
            .reshape(lf["shape"])
    for lf, b in zip(meta["leaves"], bufs):
        # no bytes() copy: the whole params tree travels through here
        # at every worker handshake
        arr = np.frombuffer(b, _np_dtype(lf["dtype"])) \
            .reshape(lf["shape"])
        node = tree
        *parents, last = lf["path"]
        for k in parents:
            node = node[k]
        if isinstance(node, tuple):
            raise ValueError("frames_to_tree: tuple leaf containers "
                             "are not rebuildable in place; use lists")
        node[last] = arr
    return tree
