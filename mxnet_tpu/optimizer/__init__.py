"""Optimizer API (reference: ``python/mxnet/optimizer/``)."""
from .optimizer import (Optimizer, SGD, Adam, AdaGrad, RMSProp, FTRL, NAG,
                        Signum, LAMB, LARS, AdaDelta, Adamax, Nadam, Test,
                        Updater, get_updater, create, register)
