"""Optimizer API.

Reference: ``python/mxnet/optimizer/`` (SURVEY.md §2.2 "Optimizers") —
registry-created optimizers whose ``update`` dispatches to the fused
``*_update`` ops (``src/operator/optimizer_op.cc``), per-weight lr/wd
multipliers, multi-precision (fp32 master weights for low-precision
params), and the ``Updater`` state-holder used by Module/KVStore.
"""
from __future__ import annotations

import math
import pickle
import numpy as _np

from ..base import Registry, MXNetError
from .. import ndarray as nd

__all__ = ["Optimizer", "SGD", "Adam", "AdaGrad", "RMSProp", "FTRL", "NAG",
           "Signum", "LAMB", "AdaDelta", "Adamax", "Nadam", "LARS", "Test",
           "Updater", "get_updater", "create", "register", "state_zeros"]

_REG = Registry("optimizer")
register = _REG.register


def state_zeros(weight, dtype=None):
    """Optimizer-state allocation matching the weight's PLACEMENT.

    For a single-device weight this is ``nd.zeros(ctx=weight.context)``
    (the reference behavior).  For a mesh-SHARDED weight (the FSDP /
    ZeRO world, round 19) the state is materialized directly INTO the
    weight's sharding — init-then-reshard would peak at full replicated
    size on one device, defeating the reason the weight is sharded
    (the same argument as ``parallel/mesh.init_sharded_opt_state``);
    a single-device state next to a sharded weight would also force a
    reshard on every ``update``."""
    data = getattr(weight, "_data", None)
    dtype = dtype or weight.dtype
    if data is not None and hasattr(data, "sharding") \
            and len(getattr(data, "devices", lambda: [None])()) > 1:
        import jax
        import jax.numpy as jnp
        zeros = jax.jit(lambda: jnp.zeros(data.shape, dtype),
                        out_shardings=data.sharding)()
        from ..ndarray.ndarray import NDArray
        return NDArray(zeros)
    return nd.zeros(weight.shape, ctx=weight.context, dtype=dtype)


class Optimizer:
    """Base optimizer (reference: ``mxnet.optimizer.Optimizer``)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01,
                 lr_scheduler=None, sym=None, begin_num_update=0,
                 multi_precision=False, param_dict=None,
                 aggregate_num=0, **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.aggregate_num = aggregate_num
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = dict(param_idx2name)
        self.param_dict = param_dict if param_dict else {}

    # -- state -------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        weight_master_copy = None
        if self.multi_precision and weight.dtype == _np.float16:
            weight_master_copy = weight.astype("float32")
            return (self.create_state(index, weight_master_copy),
                    weight_master_copy)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == _np.float16:
            original_state, weight32 = state[0], state[1]
            grad32 = grad.astype("float32")
            self.update(index, weight32, grad32, original_state)
            weight._set_data(weight32.astype(weight.dtype)._data)
        else:
            self.update(index, weight, grad, state)

    # -- per-weight multipliers -------------------------------------------
    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index],
                              self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.param_dict:
            p = self.param_dict[index]
            lr *= getattr(p, "lr_mult", 1.0)
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            p = self.param_dict[index]
            wd *= getattr(p, "wd_mult", 1.0)
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("LRScheduler of the optimizer has already been "
                             "defined.")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def __repr__(self):
        return "%s(lr=%s)" % (type(self).__name__, self.lr)

    @staticmethod
    def create_optimizer(name, **kwargs):
        return _REG.create(name, **kwargs)


def _common_kwargs(opt, index):
    kw = {"rescale_grad": opt.rescale_grad}
    if opt.clip_gradient is not None:
        kw["clip_gradient"] = opt.clip_gradient
    return kw


@register("sgd")
class SGD(Optimizer):
    """SGD with momentum; dispatches to fused ``sgd_update`` /
    ``sgd_mom_update`` / ``mp_*`` variants."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return state_zeros(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = _common_kwargs(self, index)
        if grad.stype == "row_sparse" and self.lazy_update:
            from ..ndarray import sparse as _sp
            if state is not None:
                _sp.sgd_mom_update(weight, grad, state, out=weight, lr=lr,
                                   wd=wd, momentum=self.momentum, **kw)
            else:
                _sp.sgd_update(weight, grad, out=weight, lr=lr, wd=wd, **kw)
            return
        if grad.stype != "default":
            grad = grad.todense()
        if state is not None:
            nd.sgd_mom_update(weight, grad, state, out=weight, lr=lr, wd=wd,
                              momentum=self.momentum, **kw)
        else:
            nd.sgd_update(weight, grad, out=weight, lr=lr, wd=wd, **kw)

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == _np.float16:
            self._update_count(index)
            lr = self._get_lr(index)
            wd = self._get_wd(index)
            kw = _common_kwargs(self, index)
            mom, w32 = state
            if mom is not None:
                nd.mp_sgd_mom_update(weight, grad, mom, w32, out=weight,
                                     lr=lr, wd=wd, momentum=self.momentum,
                                     **kw)
            else:
                nd.mp_sgd_update(weight, grad, w32, out=weight, lr=lr,
                                 wd=wd, **kw)
        else:
            self.update(index, weight, grad, state)


@register("nag")
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return state_zeros(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = _common_kwargs(self, index)
        if state is not None:
            nd.nag_mom_update(weight, grad, state, out=weight, lr=lr, wd=wd,
                              momentum=self.momentum, **kw)
        else:
            nd.sgd_update(weight, grad, out=weight, lr=lr, wd=wd, **kw)


@register("adam")
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (state_zeros(weight), state_zeros(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        kw = _common_kwargs(self, index)
        mean, var = state
        if grad.stype == "row_sparse" and self.lazy_update:
            from ..ndarray import sparse as _sp
            _sp.adam_update(weight, grad, mean, var, out=weight, lr=lr,
                            wd=wd, beta1=self.beta1, beta2=self.beta2,
                            epsilon=self.epsilon, **kw)
        else:
            if grad.stype != "default":
                grad = grad.todense()
            nd.adam_update(weight, grad, mean, var, out=weight, lr=lr, wd=wd,
                           beta1=self.beta1, beta2=self.beta2,
                           epsilon=self.epsilon, **kw)


@register("adagrad")
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        grad = grad + wd * weight
        state._set_data((state + grad * grad)._data)
        weight._set_data(
            (weight - lr * grad / (nd.sqrt(state) +
                                   self.float_stable_eps))._data)


@register("rmsprop")
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (nd.zeros(weight.shape, ctx=weight.context),
                    nd.zeros(weight.shape, ctx=weight.context),
                    nd.zeros(weight.shape, ctx=weight.context))
        return nd.zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = _common_kwargs(self, index)
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if not self.centered:
            nd.rmsprop_update(weight, grad, state, out=weight, lr=lr, wd=wd,
                              gamma1=self.gamma1, epsilon=self.epsilon,
                              **kw)
        else:
            n, g, delta = state
            nd.rmspropalex_update(weight, grad, n, g, delta, out=weight,
                                  lr=lr, wd=wd, gamma1=self.gamma1,
                                  gamma2=self.gamma2, epsilon=self.epsilon,
                                  **kw)


@register("ftrl")
class FTRL(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),
                nd.zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = _common_kwargs(self, index)
        z, n = state
        nd.ftrl_update(weight, grad, z, n, out=weight, lr=lr, wd=wd,
                       lamda1=self.lamda1, beta=self.beta, **kw)


@register("signum")
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, ctx=weight.context,
                            dtype=weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = _common_kwargs(self, index)
        if state is not None:
            nd.signum_update(weight, grad, state, out=weight, lr=lr, wd=wd,
                             momentum=self.momentum, wd_lh=self.wd_lh, **kw)
        else:
            nd.signsgd_update(weight, grad, out=weight, lr=lr, wd=wd, **kw)


@register("lamb")
class LAMB(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        kw = _common_kwargs(self, index)
        # phase1 mutates mean/var in place (FMutateInputs contract)
        g = nd.lamb_update_phase1(weight, grad, mean, var, beta1=self.beta1,
                                  beta2=self.beta2, epsilon=self.epsilon,
                                  t=t, bias_correction=self.bias_correction,
                                  wd=wd, **kw)
        r1 = nd.norm(weight)
        r2 = nd.norm(g)
        kw2 = {}
        if self.lower_bound is not None:
            kw2["lower_bound"] = self.lower_bound
        if self.upper_bound is not None:
            kw2["upper_bound"] = self.upper_bound
        nd.lamb_update_phase2(weight, g, r1, r2, out=weight, lr=lr, **kw2)


@register("lars")
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (reference: contrib LARS)."""

    def __init__(self, momentum=0.0, eta=0.001, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return state_zeros(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        w_norm = float(nd.norm(weight).asscalar())
        g_norm = float(nd.norm(grad * self.rescale_grad).asscalar())
        if w_norm > 0 and g_norm > 0:
            lr = lr * self.eta * w_norm / (g_norm + wd * w_norm +
                                           self.epsilon)
        kw = _common_kwargs(self, index)
        if state is not None:
            nd.sgd_mom_update(weight, grad, state, out=weight, lr=lr, wd=wd,
                              momentum=self.momentum, **kw)
        else:
            nd.sgd_update(weight, grad, out=weight, lr=lr, wd=wd, **kw)


@register("adadelta")
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),
                nd.zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        grad = grad + wd * weight
        acc_g, acc_delta = state
        acc_g._set_data((self.rho * acc_g +
                         (1 - self.rho) * grad * grad)._data)
        delta = (nd.sqrt(acc_delta + self.epsilon) /
                 nd.sqrt(acc_g + self.epsilon)) * grad
        acc_delta._set_data((self.rho * acc_delta +
                             (1 - self.rho) * delta * delta)._data)
        weight._set_data((weight - delta)._data)


@register("adamax")
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        lr /= (1. - self.beta1 ** t)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        grad = grad + wd * weight
        m_t, u_t = state
        m_t._set_data((self.beta1 * m_t + (1 - self.beta1) * grad)._data)
        u_t._set_data(nd.maximum(self.beta2 * u_t, nd.abs(grad))._data)
        weight._set_data((weight - lr * m_t / (u_t + 1e-8))._data)


@register("nadam")
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1. - 0.5 * 0.96 **
                                   (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1. - 0.5 * 0.96 **
                                     ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m_t, v_t = state
        m_t._set_data((self.beta1 * m_t + (1. - self.beta1) * grad)._data)
        v_t._set_data((self.beta2 * v_t +
                       (1. - self.beta2) * grad * grad)._data)
        grad_prime = grad / (1. - self.m_schedule)
        m_t_prime = m_t / (1. - m_schedule_next)
        v_t_prime = v_t / (1. - self.beta2 ** t)
        m_t_bar = ((1. - momentum_t) * grad_prime +
                   momentum_t_1 * m_t_prime)
        weight._set_data((weight - lr * m_t_bar /
                          (nd.sqrt(v_t_prime) + self.epsilon))._data)


@register("test")
class Test(Optimizer):
    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        weight._set_data((weight + grad * self.rescale_grad)._data)
        state._set_data(weight._data)


class Updater:
    """State-holding update closure (reference: ``mxnet.optimizer.Updater``,
    used by KVStore server-side updates and Module)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        def _np_state(s):
            if s is None:
                return None
            if isinstance(s, (list, tuple)):
                return tuple(_np_state(x) for x in s)
            return s.asnumpy()
        states = {k: _np_state(v) for k, v in self.states.items()}
        if dump_optimizer:
            return pickle.dumps((states, self.optimizer))
        return pickle.dumps(states)

    def set_states(self, states):
        data = pickle.loads(states)
        if isinstance(data, tuple) and len(data) == 2 and \
                isinstance(data[1], Optimizer):
            states, self.optimizer = data
        else:
            states = data

        def _nd_state(s):
            if s is None:
                return None
            if isinstance(s, tuple):
                return tuple(_nd_state(x) for x in s)
            return nd.array(s)
        self.states = {k: _nd_state(v) for k, v in states.items()}
        self.states_synced = {k: False for k in self.states}


def get_updater(optimizer):
    return Updater(optimizer)


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    return _REG.create(name, **kwargs)
