"""RecordIO — binary record container + indexed variant.

Reference: ``python/mxnet/recordio.py`` + ``dmlc/recordio.h`` (SURVEY.md
§2.1 "RecordIO + dmlc-core", §2.2 "IO/image").  Format kept wire-compatible
with the dmlc spec: each record is ``[kMagic u32][cflag:3|len:29 u32]
[payload][pad to 4B]``; continuation flags split payloads containing the
magic; ``.idx`` maps integer keys to byte offsets.  ``IRHeader`` packs
``[flag u32][label f32][id u64][id2 u64]`` with multi-label payloads
inlined after the header when ``flag > 1``.

A C++ fast parser for the hot decode path lives in ``native/`` (threaded
prefetch); this module is the always-available implementation.
"""
from __future__ import annotations

import collections
import ctypes
import os
import struct
import numpy as _np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_KMAGIC = 0xced7230a
_LEN_MASK = (1 << 29) - 1


def _lrec(cflag, length):
    return (cflag << 29) | length


def _cflag(lrec):
    return lrec >> 29


def _length(lrec):
    return lrec & _LEN_MASK


class MXRecordIO:
    """Sequential record reader/writer (reference: ``MXRecordIO``)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.pid = None
        self.record = None
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError("Invalid flag %s" % self.flag)
        self.pid = os.getpid()
        self.is_open = True

    def close(self):
        if not self.is_open:
            return
        self.record.close()
        self.is_open = False
        self.pid = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        is_open = self.is_open
        self.close()
        d = dict(self.__dict__)
        d["is_open"] = is_open
        del d["record"]
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        is_open = d["is_open"]
        self.is_open = False
        self.record = None
        if is_open:
            self.open()

    def _check_pid(self):
        # reopen after fork (reference: DataLoader worker semantics)
        if self.pid != os.getpid():
            pos = self.record.tell() if self.is_open else 0
            self.close()
            self.open()
            if self.flag == "r":
                self.record.seek(pos)

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        self._check_pid()
        magic_bytes = struct.pack("<I", _KMAGIC)
        # split payload on embedded magic (dmlc continuation encoding)
        chunks = []
        data = bytes(buf)
        start = 0
        while True:
            idx = data.find(magic_bytes, start)
            if idx == -1:
                chunks.append(data[start:])
                break
            chunks.append(data[start:idx])
            start = idx + 4
        n = len(chunks)
        for i, chunk in enumerate(chunks):
            if n == 1:
                cflag = 0
            elif i == 0:
                cflag = 1
            elif i == n - 1:
                cflag = 3
            else:
                cflag = 2
            self.record.write(magic_bytes)
            self.record.write(struct.pack("<I", _lrec(cflag, len(chunk))))
            self.record.write(chunk)
            pad = (4 - len(chunk) % 4) % 4
            if pad:
                self.record.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        self._check_pid()
        out = b""
        first = True
        while True:
            header = self.record.read(8)
            if len(header) < 8:
                return None if first else out
            magic, lrec = struct.unpack("<II", header)
            if magic != _KMAGIC:
                raise MXNetError("Invalid RecordIO magic at offset %d"
                                 % (self.record.tell() - 8))
            cflag, length = _cflag(lrec), _length(lrec)
            data = self.record.read(length)
            pad = (4 - length % 4) % 4
            if pad:
                self.record.read(pad)
            if not first:
                out += struct.pack("<I", _KMAGIC)
            out += data
            first = False
            if cflag in (0, 3):
                return out

    def tell(self):
        return self.record.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Keyed random-access records via an ``.idx`` sidecar (reference:
    ``MXIndexedRecordIO``)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.exists(self.idx_path):
            with open(self.idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) >= 2:
                        key = self.key_type(parts[0])
                        self.idx[key] = int(parts[1])
                        self.keys.append(key)
        if self.flag == "w":
            self.fidx = open(self.idx_path, "w")

    def close(self):
        if not self.is_open:
            return
        super().close()
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None

    def seek(self, idx):
        assert not self.writable
        self._check_pid()
        self.record.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        assert self.writable
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(idx), pos))
        self.idx[idx] = pos
        self.keys.append(idx)


IRHeader = collections.namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a header + raw bytes into a record payload (reference:
    ``recordio.pack``)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_IR_FORMAT, 0, float(header.label), header.id,
                          header.id2)
    else:
        label = _np.asarray(header.label, dtype=_np.float32)
        hdr = struct.pack(_IR_FORMAT, label.size, 0.0, header.id,
                          header.id2) + label.tobytes()
    return hdr + s


def unpack(s):
    """Unpack a record payload into (IRHeader, bytes)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        arr = _np.frombuffer(s[:flag * 4], dtype=_np.float32)
        header = IRHeader(flag, arr, id_, id2)
        s = s[flag * 4:]
    else:
        header = IRHeader(flag, label, id_, id2)
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack header + image array, encoding with cv2."""
    import cv2
    encode_params = None
    if img_fmt.lower() in (".jpg", ".jpeg"):
        encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif img_fmt.lower() == ".png":
        encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
    ret, buf = cv2.imencode(img_fmt, img, encode_params)
    if not ret:
        raise MXNetError("failed to encode image")
    return pack(header, buf.tobytes())


def unpack_img(s, iscolor=-1):
    """Unpack a record payload into (IRHeader, decoded image ndarray)."""
    import cv2
    header, s = unpack(s)
    img = cv2.imdecode(_np.frombuffer(s, dtype=_np.uint8), iscolor)
    return header, img
