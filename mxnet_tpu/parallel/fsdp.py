"""FSDP — fully-sharded data parallelism over the mesh's ``dp`` axis.

Reference semantics: none (MXNet 1.x shards nothing; ZeRO-1 in
``mesh.zero1_sharding`` shards only the optimizer moments).  The
TPU-native mechanism (SURVEY.md §2.4 extension, ROADMAP item 5): params
AND optimizer state live sharded over ``dp`` — per-device param+opt
bytes are exactly ÷dp — and the ONE jitted train step all-gathers each
weight on use and reduce-scatters its gradient straight into the
sharded optimizer update.  XLA GSPMD inserts both collectives from the
shardings alone; there is no hand-written gather/scatter, exactly like
the serving engine's tensor-parallel lowering (round 14).

The sharding story is the SAME rule-table pattern tensor-parallel
serving binds (``models/transformer.py param_specs``): a MESH-FREE
table of partition rules, here as ``(regex, dim)`` pairs over tree
paths (the SNIPPETS.md [3] ``match_partition_rules`` idiom) composed
ONTO the megatron specs — ``dp`` lands on a dim the tp rule leaves
free, so FSDP composes with tensor parallelism instead of fighting it
(the same composition argument as ``mesh.zero1_sharding``).

Entry points
------------
``fsdp_rules()``             the checked-in regex rule table
``match_partition_rules``    SNIPPETS [3]: rules × param paths → dim
``fsdp_param_specs``         mesh-free PartitionSpec tree for a cfg
``fsdp_param_shardings``     the specs bound to a mesh
``shard_bytes``              actual per-device bytes from
                             ``addressable_shards`` (the PR-9 ÷tp
                             assertion protocol, here for ÷dp)

``models/transformer.py make_train_step(fsdp=True)`` consumes these;
``tools/analysis/graphlint.py`` verifies the step's DECLARED specs
against its own shape-aware derivation (docs/sharding_readiness.md).
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError

__all__ = ["fsdp_rules", "match_partition_rules", "fsdp_param_specs",
           "fsdp_param_shardings", "shard_bytes"]


def fsdp_rules() -> List[Tuple[str, int]]:
    """The mesh-free FSDP rule table: ``(path regex, dim)`` — the dim
    of each matching param that shards over ``dp``.

    Dims are chosen to COMPOSE with the megatron tp entries
    (``models/transformer.py param_specs``): where tp shards dim 1
    (wq/wk/wv/w1 and the embedding tables), dp takes dim 0; where tp
    shards dim 0 (wo/w2), dp takes dim 1.  ``type_emb`` is the one
    table whose dim 0 (type_vocab_size=2) cannot divide any real dp
    degree, so its rule names dim 1 — the shape-aware derivation in
    graphlint's audit independently reaches the same choice.  First
    match wins, and an unmatched param is an ERROR, not a silent
    replicate (the SNIPPETS [3] contract): a new param family must be
    added to the table deliberately."""
    return [
        (r"(^|/)type_emb$", 1),
        (r"(^|/)(tok_emb|pos_emb|mlm_dense)$", 0),
        (r"(^|/)(wq|wk|wv|w1)$", 0),
        (r"(^|/)(wo|w2)$", 1),
        (r"(^|/)(bq|bk|bv|bo|b1|b2|mlm_bias)$", 0),
        (r"(^|/)(ln1|ln2|emb_ln|mlm_ln)/(g|b)$", 0),
    ]


def _tree_paths(tree):
    """``(path-string, leaf)`` pairs with ``a/b[3]/c``-style paths —
    the ``named_tree_map(sep='/')`` spelling of SNIPPETS [3]."""
    import jax

    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                if parts:
                    parts[-1] += "[%d]" % p.idx
                else:
                    parts.append("[%d]" % p.idx)
            else:
                parts.append(str(p))
        out.append(("/".join(parts), leaf))
    return out


def match_partition_rules(rules, tree) -> List[Tuple[str, Any, int]]:
    """Apply the rule table to every leaf of ``tree`` (params or
    abstract shapes): returns ``(path, leaf, dim)`` triples.  A leaf
    no rule matches raises — the SNIPPETS [3] contract (silently
    replicating a new 100M-row embedding is how FSDP quietly stops
    being FSDP)."""
    out = []
    for path, leaf in _tree_paths(tree):
        for rx, dim in rules:
            if re.search(rx, path) is not None:
                out.append((path, leaf, dim))
                break
        else:
            raise MXNetError(
                "fsdp: no partition rule matches param %r — add it to "
                "parallel/fsdp.py fsdp_rules()" % path)
    return out


def _compose(spec, dim, axis, ndim):
    """Insert ``axis`` at ``dim`` of ``spec`` (a PartitionSpec or
    None), stacking onto an existing entry as a sub-axis tuple (the
    megatron axis stays outermost: tp partitions the dim first, dp
    subdivides each tp shard)."""
    from jax.sharding import PartitionSpec as P

    entries = list(spec) if spec is not None else []
    entries = entries[:ndim] + [None] * (ndim - len(entries))
    cur = entries[dim]
    if cur is None:
        entries[dim] = axis
    elif isinstance(cur, tuple):
        entries[dim] = cur + (axis,)
    else:
        entries[dim] = (cur, axis)
    return P(*entries)


def fsdp_param_specs(cfg, dp: str = "dp", tp: Optional[str] = None):
    """Mesh-free FSDP ``PartitionSpec`` pytree for a transformer
    config: the megatron table (``param_specs`` — the SAME table
    tensor-parallel serving binds) with ``dp`` composed onto the dim
    the rule table names.  ``tp=None`` drops the tensor axis (a pure
    dp mesh)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from ..models import transformer as T

    if getattr(cfg, "n_experts", 0):
        raise MXNetError(
            "fsdp: MoE configs are unsupported — the expert dim is "
            "already the 'ep' data-movement axis and the rule table "
            "deliberately does not cover expert weights (compose ep "
            "with ZeRO-1 via shard_optimizer=True instead)")
    base = T.param_specs(cfg, tp=tp)
    shapes = jax.eval_shape(
        lambda: T.init_params(jax.random.PRNGKey(0), cfg))
    triples = {path: dim for path, _, dim
               in match_partition_rules(fsdp_rules(), shapes)}
    leaves, treedef = jax.tree_util.tree_flatten(
        base, is_leaf=lambda x: isinstance(x, P))
    paths = [p for p, _ in _tree_paths(shapes)]
    shape_leaves = [l for _, l in _tree_paths(shapes)]
    assert len(paths) == len(leaves)
    out = [
        _compose(spec, triples[path], dp, len(leaf.shape))
        for path, leaf, spec in zip(paths, shape_leaves, leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def fsdp_param_shardings(cfg, mesh, dp: str = "dp"):
    """``fsdp_param_specs`` bound to ``mesh`` (tp included when the
    mesh has a live tp axis, the ``param_shardings`` convention)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from .mesh import live_axis

    if live_axis(mesh, dp) is None:
        raise MXNetError(
            "fsdp needs a live %r mesh axis (size > 1); mesh has %s"
            % (dp, dict(mesh.shape)))
    specs = fsdp_param_specs(cfg, dp=dp, tp=live_axis(mesh, "tp"))
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))


def shard_bytes(tree, device=None) -> Tuple[int, int]:
    """(total_bytes, per_device_bytes) of a pytree of live arrays,
    per-device measured from the ACTUAL ``addressable_shards`` on
    ``device`` (default: the first device seen) — the PR-9 protocol:
    the ÷dp claim is asserted against what the runtime placed, not
    against the specs."""
    import jax

    total = 0
    per_dev = 0
    dev = device
    for leaf in jax.tree_util.tree_leaves(tree):
        if not hasattr(leaf, "addressable_shards"):
            continue
        total += leaf.nbytes
        shards = leaf.addressable_shards
        if dev is None:
            dev = shards[0].device
        for sh in shards:
            if sh.device == dev:
                per_dev += sh.data.nbytes
    return total, per_dev
