"""Multi-host coordination — the DCN half of the communication story.

Reference: ps-lite's scheduler/rendezvous (``DMLC_PS_ROOT_URI`` env
rendezvous, SURVEY.md §2.1 "ps-lite" row) and the dmlc tracker that
``tools/launch.py`` drives.  TPU-native equivalent (§5.8): a
jax.distributed coordination service — every host runs the SAME program,
``jax.devices()`` becomes the global device set, meshes span hosts, and
XLA routes intra-slice collectives over ICI and cross-slice over DCN.
No parameter server in the data path.

``initialize()`` accepts both its native arguments and the reference's
``DMLC_*`` environment (as set by ``tools/launch.py``), so a launch
script written for the reference's tracker drives multi-host TPU
training unchanged.
"""
from __future__ import annotations

import os
from typing import Optional

from ..base import MXNetError

__all__ = ["initialize", "shutdown", "is_initialized", "rank",
           "num_hosts", "local_devices", "global_mesh"]

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None):
    """Join the multi-host cluster (reference analog: worker start-up
    against ``DMLC_PS_ROOT_URI``).

    With no arguments, reads ``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT``
    (coordinator), ``DMLC_NUM_WORKER`` (process count) and
    ``DMLC_WORKER_ID`` (this process) — the env contract
    ``tools/launch.py`` emits.  Without DMLC env, jax's own pod
    auto-detection runs when a pod marker is present
    (``JAX_COORDINATOR_ADDRESS``, ``MEGASCALE_COORDINATOR_ADDRESS`` or
    ``TPU_WORKER_HOSTNAMES``); otherwise the process is treated as
    single-host.
    """
    global _initialized
    import jax

    if _initialized:
        return
    if coordinator_address is None and "DMLC_PS_ROOT_URI" in os.environ:
        coordinator_address = "%s:%s" % (
            os.environ["DMLC_PS_ROOT_URI"],
            os.environ.get("DMLC_PS_ROOT_PORT", "9000"))
        num_processes = num_processes or int(
            os.environ.get("DMLC_NUM_WORKER", "1"))
        process_id = process_id if process_id is not None else int(
            os.environ.get("DMLC_WORKER_ID", "0"))

    if coordinator_address is None and num_processes is None:
        if process_id is not None:
            raise MXNetError(
                "multihost.initialize(process_id=%r) without a "
                "coordinator_address/num_processes — the launcher "
                "likely failed to export DMLC_PS_ROOT_URI; refusing "
                "to run as a lone single-host process" % process_id)
        # pod-environment markers → let jax auto-detect the cluster;
        # plain single host otherwise (nothing to coordinate).  A
        # single-entry TPU_WORKER_HOSTNAMES (e.g. 'localhost' on
        # one-chip setups) is NOT a pod.
        hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        if (os.environ.get("JAX_COORDINATOR_ADDRESS")
                or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
                or len([h for h in hostnames.split(",") if h]) > 1):
            _jax_dist_init(jax)
        _initialized = True
        return
    if num_processes is None or (num_processes > 1
                                 and process_id is None):
        raise MXNetError(
            "multihost.initialize(coordinator_address=...) needs "
            "num_processes and process_id too (or set DMLC_NUM_WORKER/"
            "DMLC_WORKER_ID like tools/launch.py does)")
    if num_processes == 1:
        _initialized = True
        return
    _jax_dist_init(jax, coordinator_address=coordinator_address,
                   num_processes=num_processes, process_id=process_id)
    _initialized = True


def _backend_already_up(jax):
    try:
        from jax._src import xla_bridge
        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        return False


def _jax_dist_init(jax, **kw):
    global _initialized
    backend_up = _backend_already_up(jax)
    try:
        jax.distributed.initialize(**kw)
    except (RuntimeError, ValueError) as e:
        if backend_up or "before any JAX calls" in str(e):
            raise MXNetError(
                "multihost.initialize() must run before the first jax "
                "computation/device query in the process — call it at "
                "the top of your training script (launch.py does this "
                "for you): %s" % e)
        raise MXNetError("multihost.initialize() failed: %s" % e)
    _initialized = True


def host_staged_put(value, sharding):
    """``jax.device_put`` that works for cross-process shardings.

    A sharding spanning processes cannot be fed to ``device_put`` from
    a process-local committed array.  Round-19 audit (ROADMAP item 5
    satellite): a device-resident value no longer round-trips through
    host numpy for that case either — each local shard is sliced ON
    DEVICE from the local copy (``make_array_from_callback`` with jax
    array slices = device-to-device), so sharded params stay
    device-resident end to end.  Host numpy staging remains only for
    values that are already host data.  Callers must hold identical
    values on every process (the same synchronized-start contract as
    the reference's workers — ``init_params`` is deterministic per
    key)."""
    import jax
    if jax.process_count() > 1:
        if isinstance(value, jax.Array) and value.is_fully_addressable:
            # device-resident: feed each local shard as an on-device
            # slice of the local copy — no D2H, no host numpy
            return jax.make_array_from_callback(
                value.shape, sharding, lambda idx: value[idx])
        import numpy as _np
        value = _np.asarray(value)
    return jax.device_put(value, sharding)


def shutdown():
    global _initialized
    if not _initialized:
        return
    import jax
    try:
        jax.distributed.shutdown()
    except Exception:
        pass
    _initialized = False


def is_initialized() -> bool:
    return _initialized


def rank() -> int:
    """This host's index (reference: kvstore ``rank``)."""
    import jax
    return jax.process_index()


def num_hosts() -> int:
    """Participating host count (reference: ``num_workers``)."""
    import jax
    return jax.process_count()


def local_devices():
    import jax
    return jax.local_devices()


def global_mesh(axes):
    """A mesh over the GLOBAL device set (all hosts).  Same semantics as
    :func:`mxnet_tpu.parallel.make_mesh` — sized against
    ``jax.devices()``, which spans hosts after :func:`initialize`."""
    from .mesh import make_mesh
    return make_mesh(axes)
