"""Sequence/context parallelism: ring attention and Ulysses (all-to-all).

No reference counterpart — MXNet 1.x predates sequence parallelism
(SURVEY.md §5.7 marks it ABSENT; the task brief makes it first-class for
the TPU build).  Design follows the public ring-attention recipe: shard the
sequence over the ``sp`` mesh axis, keep Q resident, rotate K/V blocks
around the ring with ``lax.ppermute`` while accumulating online softmax in
float32 — the collective rides ICI and overlaps with the block matmuls.
Ulysses instead swaps sequence-sharding for head-sharding with two
``all_to_all``s and runs dense local attention.

Both are reverse-mode differentiable (scan + ppermute / all_to_all have
transposes), so they drop straight into training steps under ``jit``.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

from ..base import MXNetError

__all__ = ["ring_attention", "ulysses_attention",
           "sequence_parallel_attention"]


def _ring_shard(q, k, v, kmask, *, axis_name, causal, sm_scale):
    """Per-shard ring attention.  q/k/v: (B, Ts, H, dh) local blocks;
    kmask: (B, Ts) 1=valid.  Runs n_shards steps of blockwise online
    softmax, rotating (k, v, kmask) one hop per step."""
    import jax
    import jax.numpy as jnp

    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, Tq, H, dh = q.shape
    Tk = k.shape[1]

    qf = q.astype(jnp.float32)
    q_pos = my * Tq + jnp.arange(Tq)

    perm = [(j, (j + 1) % n) for j in range(n)]

    m0 = jnp.full((B, H, Tq, 1), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((B, H, Tq, 1), dtype=jnp.float32)
    acc0 = jnp.zeros((B, Tq, H, dh), dtype=jnp.float32)

    def step(carry, i):
        k_c, v_c, km_c, m, l, acc = carry
        # block currently held originated on shard (my - i) mod n
        src = (my - i) % n
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_c.astype(jnp.float32))
        s = s * sm_scale
        valid = km_c[:, None, None, :] != 0
        if causal:
            k_pos = src * Tk + jnp.arange(Tk)
            valid = valid & (k_pos[None, None, None, :] <=
                             q_pos[None, None, :, None])
        s = jnp.where(valid, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_c.astype(jnp.float32))
        acc_new = acc * jnp.moveaxis(alpha, 1, 2) + pv
        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        km_c = jax.lax.ppermute(km_c, axis_name, perm)
        return (k_c, v_c, km_c, m_new, l_new, acc_new), ()

    (k_c, v_c, km_c, m, l, acc), _ = jax.lax.scan(
        step, (k, v, kmask, m0, l0, acc0), jnp.arange(n))
    out = acc / jnp.maximum(jnp.moveaxis(l, 1, 2), 1e-30)
    return out.astype(q.dtype)


def _ulysses_shard(q, k, v, kmask, *, axis_name, causal, sm_scale):
    """Per-shard Ulysses: all-to-all seq-shard → head-shard, dense local
    attention over the full sequence, all-to-all back."""
    import jax
    import jax.numpy as jnp

    n = jax.lax.psum(1, axis_name)
    B, Ts, H, dh = q.shape
    # (B, Ts, H, dh) -> (B, T, H/n, dh)
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                            split_axis=2, concat_axis=1, tiled=True)
    qg, kg, vg = a2a(q), a2a(k), a2a(v)
    maskg = jax.lax.all_gather(kmask, axis_name, axis=1, tiled=True)

    T = qg.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", qg.astype(jnp.float32),
                   kg.astype(jnp.float32)) * sm_scale
    valid = maskg[:, None, None, :] != 0
    if causal:
        pos = jnp.arange(T)
        valid = valid & (pos[None, None, None, :] <=
                         pos[None, None, :, None])
    s = jnp.where(valid, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vg.astype(jnp.float32))
    out = out.astype(q.dtype)
    # (B, T, H/n, dh) -> (B, Ts, H, dh)
    return jax.lax.all_to_all(out, axis_name=axis_name, split_axis=1,
                              concat_axis=2, tiled=True)


def _wrap(fn_shard, q, k, v, mask, mesh, seq_axis, causal):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    if seq_axis not in mesh.axis_names:
        raise MXNetError("mesh has no axis %r" % seq_axis)
    batch_axis = "dp" if "dp" in mesh.axis_names else None
    if mask is None:
        mask = jnp.ones(q.shape[:2], dtype=jnp.int8)

    sm_scale = 1.0 / math.sqrt(q.shape[-1])
    qspec = P(batch_axis, seq_axis, None, None)
    mspec = P(batch_axis, seq_axis)
    fn = functools.partial(fn_shard, axis_name=seq_axis, causal=causal,
                           sm_scale=sm_scale)
    from .mesh import shard_map_compat
    return shard_map_compat(fn, mesh=mesh,
                            in_specs=(qspec, qspec, qspec, mspec),
                            out_specs=qspec,
                            check_vma=False)(q, k, v, mask)


def ring_attention(q, k, v, mask=None, *, mesh, seq_axis="sp",
                   causal=False):
    """Ring attention over the ``seq_axis`` mesh axis.

    q/k/v: (B, T, H, dh) GLOBAL arrays (sharded or to-be-sharded on T);
    mask: (B, T) key-validity.  Returns (B, T, H, dh)."""
    return _wrap(_ring_shard, q, k, v, mask, mesh, seq_axis, causal)


def ulysses_attention(q, k, v, mask=None, *, mesh, seq_axis="sp",
                      causal=False):
    """Ulysses (all-to-all head-scatter) attention over ``seq_axis``.
    Requires n_heads % mesh.shape[seq_axis] == 0."""
    if q.shape[2] % mesh.shape[seq_axis]:
        raise MXNetError(
            "ulysses: n_heads=%d not divisible by %s=%d"
            % (q.shape[2], seq_axis, mesh.shape[seq_axis]))
    return _wrap(_ulysses_shard, q, k, v, mask, mesh, seq_axis, causal)


def sequence_parallel_attention(q, k, v, mask=None, *, mesh,
                                seq_axis="sp", causal=False,
                                method="ring"):
    """Dispatch helper: ``method`` in {'ring', 'ulysses'}."""
    if method == "ring":
        return ring_attention(q, k, v, mask, mesh=mesh, seq_axis=seq_axis,
                              causal=causal)
    if method == "ulysses":
        return ulysses_attention(q, k, v, mask, mesh=mesh,
                                 seq_axis=seq_axis, causal=causal)
    raise MXNetError("unknown sequence-parallel method %r" % method)
