"""Distributed KVStore — multi-host parameter-server semantics.

Reference: ``src/kvstore/kvstore_dist.h`` + ``kvstore_dist_server.h`` over
``3rdparty/ps-lite`` (SURVEY.md §2.1 "KVStore distributed"/"ps-lite",
§3.4 call stack, §2.4 row "Data parallel, multi-node").

TPU-native split of responsibilities:

* The PERFORMANCE path for multi-chip/multi-host gradients is XLA
  collectives over ICI/DCN emitted by GSPMD for mesh-sharded arrays
  (``mxnet_tpu.parallel``) — that replaces NCCL/ps-lite for throughput,
  as the scaling-book recipe prescribes.
* THIS module preserves the reference's *API and semantics* —
  ``dist_sync`` (aggregate-all-workers-then-update + barrier),
  ``dist_async`` (apply-on-arrival), server-side optimizer
  (``update_on_kvstore``) — over a real TCP transport, so existing MXNet
  distributed scripts and the §4.5-style multi-process tests run
  unchanged.  Like ps-lite it uses ``DMLC_*`` env vars for rendezvous.

Protocol: length-prefixed pickled (cmd, key, payload) messages.  Keys are
sharded over ``DMLC_NUM_SERVER`` server processes by stable hash (server
``i`` listens on ``DMLC_PS_ROOT_PORT + i``) — the reference's ps-lite
key-range partitioning.  Optional 2-bit gradient compression with error
feedback rides the push wire path (``parallel/compression.py``).

Round 15 adds a second frame kind to the same length-prefixed wire: a
**raw frame** (:func:`send_frame` / :func:`recv_frame`) whose length
prefix carries a flag bit and whose payload is a small pickled control
header followed by N raw byte buffers sent/received without pickling
or copying (``sendall(memoryview)`` out, ``recv_into`` a preallocated
``bytearray`` in).  The disaggregated serving transport
(``serving/transport.py``) streams int8 KV pages through it — tensor
bytes never go through pickle.  Both frame kinds share
:func:`_recv_exact`, which is hardened for the process-kill path: the
length prefix is bounded (``MAX_FRAME_BYTES`` — a peer SIGKILLed
mid-frame leaves garbage that must not turn into a 2^60-byte
allocation), EINTR retries, and a reset/half-closed connection reads
as EOF (``None``) instead of raising into the handler loop.
"""
from __future__ import annotations

import os
import pickle
import queue
import socket
import struct
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["DistServer", "DistKVStore", "create_dist_kvstore",
           "run_server", "send_frame", "recv_frame", "MAX_FRAME_BYTES"]


# ---------------------------------------------------------------------------
# wire protocol
# ---------------------------------------------------------------------------

# upper bound on any single frame component (pickled message, raw-frame
# header, or one raw buffer).  A garbage length prefix — a peer killed
# mid-frame, a stray client speaking another protocol — must fail the
# connection, not allocate half the host's RAM before failing.
MAX_FRAME_BYTES = 1 << 31

# high bit of the length prefix marks a raw frame (header + raw
# buffers) rather than a single pickled object; the remaining 63 bits
# are the header length.  Legacy endpoints never see the flag — the
# kvstore protocol is pickled-only.
_RAW_FLAG = 1 << 63


def _send(sock: socket.socket, obj):
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(data)) + data)


def _check_len(n):
    if n > MAX_FRAME_BYTES:
        raise MXNetError(
            "dist wire: frame length %d exceeds MAX_FRAME_BYTES %d — "
            "garbage/oversized length prefix (peer killed mid-frame, "
            "or a foreign protocol on this port)" % (n, MAX_FRAME_BYTES))
    return n


def _recv(sock: socket.socket):
    hdr = _recv_exact(sock, 8)
    if hdr is None:
        return None
    (n,) = struct.unpack("<Q", hdr)
    if n & _RAW_FLAG:
        raise MXNetError(
            "dist wire: raw frame on a pickled-protocol connection "
            "(use recv_frame on transport endpoints)")
    data = _recv_exact(sock, _check_len(n))
    if data is None:
        return None
    return pickle.loads(data)


def _recv_exact(sock, n):
    """Read exactly ``n`` bytes into a fresh bytearray; ``None`` on
    EOF *or* abortive close (peer SIGKILL → ECONNRESET; a concurrently
    closed local socket → EBADF/ENOTCONN).  EINTR retries.  The caller
    treats ``None`` as a clean disconnect — the process-kill path must
    look like EOF, not an exception racing ``__del__``."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        try:
            r = sock.recv_into(view[got:])
        except InterruptedError:          # EINTR (pre-PEP475 paths)
            continue
        except socket.timeout:            # recv timeout is the caller's
            raise                         # poll signal, not a disconnect
        except OSError:
            return None                   # reset / closed under us
        if r == 0:
            return None
        got += r
    return bytes(buf) if n <= 64 else buf


def send_frame(sock: socket.socket, meta, bufs=()):
    """Send a raw frame: a small pickled ``meta`` header plus N raw
    byte buffers.  Buffers are sent via ``sendall(memoryview)`` — no
    pickling, no concatenation copy of tensor bytes (the header and
    per-buffer length words are coalesced into one small send)."""
    mb = pickle.dumps(meta, protocol=pickle.HIGHEST_PROTOCOL)
    views = [memoryview(b).cast("B") for b in bufs]
    head = [struct.pack("<Q", _RAW_FLAG | len(mb)), mb,
            struct.pack("<I", len(views))]
    head.append(b"".join(struct.pack("<Q", v.nbytes) for v in views))
    sock.sendall(b"".join(head))
    for v in views:
        sock.sendall(v)


def recv_frame(sock: socket.socket):
    """Receive either frame kind.  Returns ``(meta, bufs)`` for a raw
    frame (``bufs`` = list of bytearrays read zero-copy via
    ``recv_into``), ``(obj, None)`` for a legacy pickled message, or
    ``None`` on EOF/reset."""
    hdr = _recv_exact(sock, 8)
    if hdr is None:
        return None
    (n,) = struct.unpack("<Q", hdr)
    if not n & _RAW_FLAG:
        data = _recv_exact(sock, _check_len(n))
        if data is None:
            return None
        return pickle.loads(data), None
    mb = _recv_exact(sock, _check_len(n & ~_RAW_FLAG))
    if mb is None:
        return None
    meta = pickle.loads(mb)
    cnt = _recv_exact(sock, 4)
    if cnt is None:
        return None
    (nbuf,) = struct.unpack("<I", cnt)
    if nbuf > 4096:
        raise MXNetError("dist wire: raw frame claims %d buffers — "
                         "garbage header" % nbuf)
    lens = _recv_exact(sock, 8 * nbuf)
    if lens is None and nbuf:
        return None
    sizes = struct.unpack("<%dQ" % nbuf, bytes(lens or b""))
    bufs = []
    for sz in sizes:
        b = _recv_exact(sock, _check_len(sz))
        if b is None:
            return None
        bufs.append(b if isinstance(b, bytearray) else bytearray(b))
    return meta, bufs


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class DistServer:
    """The server role (reference: ``KVStoreDistServer``).

    dist_sync: buffers pushes until all workers contributed, then applies
    the updater (or plain sum) once and wakes blocked pulls — the
    aggregate-then-update semantics.  dist_async: applies each push as it
    arrives.  The optimizer arrives from worker-0 as a serialized command
    (reference: the updater shipped via ``_send_command_to_servers``).
    """

    def __init__(self, host="127.0.0.1", port=0, num_workers=1,
                 sync_mode=True, exit_on_idle=False):
        self.num_workers = num_workers
        self.sync_mode = sync_mode
        # exit_on_idle: shut down once every worker has connected and all
        # connections have closed again (worker processes exited).  Used
        # by run_server under the mpi/slurm launcher, where no tracker
        # process exists to SIGTERM the server ranks — without it mpirun
        # would block forever on the immortal servers.
        self.exit_on_idle = exit_on_idle
        self._conn_seen = 0
        self._conn_active = 0
        # distinct worker ranks observed (from push messages): the
        # idle-exit path must not arm until every rank has connected,
        # or a dropped-and-reconnected worker could inflate a plain
        # connection count past num_workers and strand late workers
        self._ranks_seen = set()
        self.store: Dict[object, np.ndarray] = {}
        self._pending: Dict[object, list] = {}
        self._push_count: Dict[object, int] = {}
        self._version: Dict[object, int] = {}
        self._updater = None
        self._cv = threading.Condition()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(num_workers * 2 + 8)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        self._threads = []

    def serve_forever(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def start(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self):
        self._stop = True
        # close() alone does not wake a thread blocked in accept() on
        # Linux — shutdown the listening socket first (wakes accept with
        # an error), then close
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    # -- handlers ---------------------------------------------------------

    def _apply_push(self, key, agg):
        cur = self.store.get(key)
        if self._updater is not None and cur is not None:
            # updaters run on NDArrays (fused *_update ops)
            from .. import ndarray as nd
            w = nd.array(cur)
            g = nd.array(agg)
            idx = key if isinstance(key, int) else abs(hash(key)) % (2**31)
            self._updater(idx, g, w)
            self.store[key] = w.asnumpy()
        elif cur is not None:
            self.store[key] = cur + agg
        else:
            self.store[key] = agg
        self._version[key] = self._version.get(key, 0) + 1

    def _handle(self, conn):
        with self._cv:
            self._conn_seen += 1
            self._conn_active += 1
        try:
            self._handle_loop(conn)
        finally:
            with self._cv:
                self._conn_active -= 1
                idle = (self.exit_on_idle and self._conn_active == 0
                        and len(self._ranks_seen) >= self.num_workers)
            if idle:
                self.shutdown()

    def _handle_loop(self, conn):
        while True:
            msg = _recv(conn)
            if msg is None:
                break
            cmd = msg[0]
            if cmd == "init":
                _, key, value = msg
                with self._cv:
                    if key not in self.store:
                        self.store[key] = np.asarray(value)
                        self._version[key] = 1
                    self._cv.notify_all()
                _send(conn, ("ok",))
            elif cmd in ("push", "cpush"):
                # (cmd, key, value, rank, round): sync aggregation is
                # per-(key, round) keyed by worker rank, so a fast worker
                # pushing round N+1 before a slow worker finishes round N
                # cannot be double-counted into N (reference: ps-lite
                # timestamps serve the same purpose)
                if cmd == "cpush":
                    # 2-bit compressed push: payload is packed codes
                    _, key, (payload, shape, dtype, thr), rank, rnd = msg
                    from .compression import decompress
                    value = decompress(payload, shape, thr, dtype)
                else:
                    _, key, value, rank, rnd = msg
                value = np.asarray(value)
                with self._cv:
                    self._ranks_seen.add(rank)
                    if self.sync_mode:
                        bucket = self._pending.setdefault((key, rnd), {})
                        bucket[rank] = value
                        if len(bucket) == self.num_workers:
                            del self._pending[(key, rnd)]
                            agg = np.sum(list(bucket.values()), axis=0)
                            self._apply_push(key, agg)
                            self._cv.notify_all()
                    else:
                        self._apply_push(key, value)
                        self._cv.notify_all()
                _send(conn, ("ok",))
            elif cmd == "pull":
                _, key, min_version = msg
                with self._cv:
                    while (key not in self.store or
                           self._version.get(key, 0) < min_version):
                        self._cv.wait(timeout=60)
                    val = self.store[key]
                _send(conn, ("val", val))
            elif cmd == "version":
                _, key = msg
                with self._cv:
                    _send(conn, ("ver", self._version.get(key, 0)))
            elif cmd == "barrier":
                with self._cv:
                    gen = self._barrier_gen
                    self._barrier_count += 1
                    if self._barrier_count == self.num_workers:
                        self._barrier_count = 0
                        self._barrier_gen += 1
                        self._cv.notify_all()
                    else:
                        while self._barrier_gen == gen:
                            self._cv.wait(timeout=60)
                _send(conn, ("ok",))
            elif cmd == "optimizer":
                _, blob = msg
                from .. import optimizer as opt
                optimizer = pickle.loads(blob)
                self._updater = opt.get_updater(optimizer)
                _send(conn, ("ok",))
            elif cmd == "hello":
                # worker announces its rank on connect; the idle-exit
                # path arms only once every distinct rank has said hello
                # (a reconnecting worker cannot inflate the count, and
                # servers that never receive a push — shard-starved or
                # pull-only workloads — still learn the full roster)
                _, rank = msg
                with self._cv:
                    self._ranks_seen.add(rank)
                _send(conn, ("ok",))
            elif cmd == "stop":
                _send(conn, ("ok",))
                self.shutdown()
                break
            else:
                _send(conn, ("err", "unknown command %r" % (cmd,)))
        conn.close()


def run_server():
    """Entry point for the server role (reference: the process started by
    the tracker with DMLC_ROLE=server; ``kvstore_server.py``).

    Server ``i`` listens on ``DMLC_PS_ROOT_PORT + i`` (all servers co-locate
    with the root URI host; keys are sharded over them by stable hash —
    reference: ps-lite key-range sharding over server nodes)."""
    port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
    sid = int(os.environ.get("DMLC_SERVER_ID", "0"))
    nworkers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    sync = os.environ.get("MXNET_KVSTORE_MODE", "dist_sync") != "dist_async"
    # bind all interfaces: under the mpi launcher the server rank may land
    # on any node, and workers reach it via DMLC_PS_ROOT_URI — binding the
    # root URI here would EADDRNOTAVAIL on a different host
    server = DistServer(host="0.0.0.0", port=port + sid,
                        num_workers=nworkers, sync_mode=sync,
                        exit_on_idle=True)
    server.serve_forever()


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------

class DistKVStore:
    """Worker-side distributed store (reference: ``KVStoreDist``).

    Local multi-device reduce happens first (as in the reference, where
    gradients are reduced on-node before ZPush); the cross-process
    aggregate runs on the server."""

    def __init__(self, name="dist_sync"):
        self.type = name
        self._sync = "async" not in name
        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
        self._rank = int(os.environ.get("DMLC_WORKER_ID",
                                        os.environ.get("DMLC_RANK", "0")))
        self._num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        self._num_servers = max(1, int(os.environ.get("DMLC_NUM_SERVER",
                                                      "1")))
        # one connection per server; keys shard over servers by stable hash
        # (reference: ps-lite key-range partitioning over server nodes)
        self._socks = []
        deadline = time.time() + float(
            os.environ.get("MXNET_KVSTORE_CONNECT_TIMEOUT", "30"))
        for sid in range(self._num_servers):
            sock = None
            last_err = None
            while time.time() < deadline:
                try:
                    sock = socket.create_connection((host, port + sid),
                                                    timeout=60)
                    break
                except OSError as e:
                    last_err = e
                    time.sleep(0.05)
            if sock is None:
                raise MXNetError(
                    "cannot reach kvstore server %d at %s:%d (%s)"
                    % (sid, host, port + sid, last_err))
            self._socks.append(sock)
        self._lock = threading.Lock()
        for sock in self._socks:
            _send(sock, ("hello", self._rank))
            _recv(sock)
        self._pull_version: Dict[object, int] = {}
        self._push_round: Dict[object, int] = {}
        self._compressor = None
        # async push pipeline (reference: push/pull are engine ops whose
        # var deps let comm overlap backward compute — SURVEY.md §3.4).
        # push() enqueues the wire RPC to a background sender; pull/
        # barrier/init are sync points that drain the queue first.
        # Worker exceptions are deferred and rethrown at the next sync
        # (the engine's deferred-exception contract).
        self._async_push = os.environ.get(
            "MXNET_KVSTORE_ASYNC_PUSH", "1").lower() not in (
                "0", "false", "off")
        self._q: "queue.Queue" = queue.Queue()
        self._q_exc = None
        self._sender = None
        if self._async_push:
            self._sender = threading.Thread(target=self._sender_loop,
                                            daemon=True)
            self._sender.start()

    # -- async sender ------------------------------------------------------
    def _sender_loop(self):
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            msg, key = item
            try:
                # after a failure the store is poisoned (_drain raises
                # forever); skipping the rest of the queue is safe
                # because no later state can be trusted anyway
                if self._q_exc is None:
                    self._rpc(*msg, key=key)
            except Exception as e:  # noqa: BLE001 — deferred to sync
                self._q_exc = e
            finally:
                self._q.task_done()

    def _enqueue_rpc(self, *msg, key=None):
        if self._async_push:
            self._q.put((msg, key))
        else:
            self._rpc(*msg, key=key)

    def _drain(self):
        """Sync point: wait for queued pushes; rethrow deferred errors.

        A failed push POISONS the store permanently (the error rethrows
        on every later sync op): the worker's round counters have
        advanced past pushes the server never saw, so continuing would
        silently desynchronize dist_sync aggregation — the reference's
        ps-lite van likewise treats a dead transport as fatal.
        Recreate the store to recover."""
        if self._async_push:
            self._q.join()
        if self._q_exc is not None:
            raise MXNetError("async push failed (store is now "
                             "unusable, recreate it): %s"
                             % (self._q_exc,))

    def close(self):
        """Stop the sender thread and close the server connections.

        Hardened for the peer-SIGKILL path: a sender blocked on a dead
        server's socket unblocks once the sockets are shut down (reset
        reads as EOF via ``_recv_exact``), so the join is bounded even
        when the peer died mid-frame; ``shutdown()`` before ``close()``
        forces the half-closed case instead of leaving the fd to
        linger in the kernel."""
        if self._sender is not None and self._sender.is_alive():
            self._q.put(None)
            self._sender.join(timeout=5)
            if self._sender.is_alive():
                # sender wedged on a dead transport: shut the sockets
                # down under it (unblocks recv with reset-as-EOF) and
                # re-join bounded
                for s in self._socks:
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                self._sender.join(timeout=5)
            self._sender = None
        for s in self._socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass                     # already reset by a dead peer
            try:
                s.close()
            except OSError:
                pass

    def __del__(self):
        # interpreter teardown after a peer SIGKILL can raise nearly
        # anything out of close() (half-dead modules, reset sockets);
        # a destructor must never propagate
        try:
            self.close()
        except BaseException:
            pass

    # -- api --------------------------------------------------------------

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def num_workers(self) -> int:
        return self._num_workers

    def _server_of(self, key) -> int:
        import zlib
        return zlib.crc32(str(key).encode()) % self._num_servers

    def _rpc(self, *msg, key=None):
        """Send to the server owning ``key`` (or server 0 if keyless).
        A dead transport (peer SIGKILL → EPIPE/ECONNRESET) surfaces as
        :class:`MXNetError` — the same contract as the async path's
        deferred errors, so callers never see raw socket errors."""
        sock = self._socks[self._server_of(key) if key is not None else 0]
        with self._lock:
            try:
                _send(sock, msg)
                out = _recv(sock)
            except OSError as e:
                raise MXNetError(
                    "kvstore transport failed (server dead?): %s"
                    % (e,)) from e
            if out is None:               # reset-as-EOF mid-reply
                raise MXNetError("kvstore transport closed by peer "
                                 "mid-reply (server dead?)")
            return out

    def _rpc_all(self, *msg):
        """Send to every server; returns the replies (barrier/optimizer)."""
        out = []
        with self._lock:
            try:
                for sock in self._socks:
                    _send(sock, msg)
                for sock in self._socks:
                    reply = _recv(sock)
                    if reply is None:
                        raise MXNetError(
                            "kvstore transport closed by peer "
                            "mid-reply (server dead?)")
                    out.append(reply)
            except OSError as e:
                raise MXNetError(
                    "kvstore transport failed (server dead?): %s"
                    % (e,)) from e
        return out

    def init(self, key, value):
        self._drain()
        keys, values = _kv_lists(key, value)
        for k, v in zip(keys, values):
            if self._rank == 0:
                self._rpc("init", k, _to_numpy(v), key=k)
        self.barrier()

    def push(self, key, value, priority=0):
        keys, values = _kv_lists(key, value)
        for k, vlist in zip(keys, values):
            if not isinstance(vlist, (list, tuple)):
                vlist = [vlist]
            # local reduce across devices first
            reduced = vlist[0]
            for v in vlist[1:]:
                reduced = reduced + v
            rnd = self._push_round.get(k, 0)
            self._push_round[k] = rnd + 1
            if self._compressor is not None:
                payload, shape, dtype = self._compressor.compress(
                    k, _to_numpy(reduced))
                self._enqueue_rpc("cpush", k,
                                  (payload, shape, dtype,
                                   self._compressor.threshold),
                                  self._rank, rnd, key=k)
            else:
                self._enqueue_rpc("push", k, _to_numpy(reduced),
                                  self._rank, rnd, key=k)
            if self._sync:
                # one aggregate-update per round of pushes
                self._pull_version[k] = \
                    self._pull_version.get(k, 1) + 1

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        from ..ndarray.ndarray import NDArray
        from .. import ndarray as nd
        self._drain()
        keys, outs = _kv_lists(key, out)
        for k, olist in zip(keys, outs):
            if not isinstance(olist, (list, tuple)):
                olist = [olist]
            tag, val = self._rpc("pull", k,
                                 self._pull_version.get(k, 1), key=k)
            if tag != "val":
                raise MXNetError("pull failed for key %r" % (k,))
            for o in olist:
                if isinstance(o, NDArray):
                    o._set_data(nd.array(val)._data)
        return None

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        self.pull(key, out, priority)

    def set_optimizer(self, optimizer):
        """Ship the optimizer to the server (reference: serialized updater
        command from worker-0 → server applies updates)."""
        self._drain()
        if self._rank == 0:
            blob = pickle.dumps(optimizer,
                                protocol=pickle.HIGHEST_PROTOCOL)
            self._rpc_all("optimizer", blob)
        self.barrier()

    def set_gradient_compression(self, compression_params):
        """Enable 2-bit compression with error feedback on the push wire
        path (reference: ``KVStore::SetGradientCompression`` →
        ``gradient_compression.cc``)."""
        from .compression import create_compressor
        self._compressor = create_compressor(compression_params)

    def barrier(self):
        self._drain()
        self._rpc_all("barrier")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        raise MXNetError("Cannot save states on a distributed worker "
                         "(reference behavior)")

    def _send_command_to_servers(self, head, body):
        pass


def _kv_lists(key, value):
    if isinstance(key, (list, tuple)):
        return list(key), list(value)
    return [key], [value]


def _to_numpy(v):
    from ..ndarray.ndarray import NDArray
    if isinstance(v, NDArray):
        return v.asnumpy()
    return np.asarray(v)


def create_dist_kvstore(name: str):
    if os.environ.get("DMLC_ROLE", "worker") == "server":
        run_server()
        raise SystemExit(0)
    return DistKVStore(name)
