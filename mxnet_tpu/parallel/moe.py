"""Mixture-of-Experts with expert parallelism over an ``ep`` mesh axis.

No reference counterpart — MXNet 1.x predates MoE (SURVEY.md §2.4 marks
expert parallel ABSENT); this is a TPU-build extension following the
GShard/Switch recipe: a learned router picks top-k experts per token,
tokens are packed into per-expert capacity buffers with dense one-hot
dispatch/combine einsums (XLA-friendly — no gather/scatter, the MXU does
the packing), and the expert dimension of both the parameter tensors and
the dispatched activations is sharded over ``ep`` so GSPMD inserts the
all-to-alls over ICI.

Gradients flow through the gate probabilities in the combine tensor
(standard straight-through routing); an auxiliary load-balancing loss
(Switch eq. 4) keeps the router from collapsing onto few experts.
"""
from __future__ import annotations

import math
from typing import Optional

from ..base import MXNetError

__all__ = ["init_moe_ffn", "moe_ffn", "moe_param_specs",
           "moe_param_shardings"]


def init_moe_ffn(key, d_model, d_ff, n_experts, param_dtype="float32"):
    """Router + per-expert FFN params: leaves carry a leading E axis."""
    import jax
    import jax.numpy as jnp
    k = jax.random.split(key, 3)
    scale = 0.02
    return {
        "router": (jax.random.normal(k[0], (d_model, n_experts))
                   * scale).astype(param_dtype),
        "w1": (jax.random.normal(k[1], (n_experts, d_model, d_ff))
               * scale).astype(param_dtype),
        "b1": jnp.zeros((n_experts, d_ff), param_dtype),
        "w2": (jax.random.normal(k[2], (n_experts, d_ff, d_model))
               * scale).astype(param_dtype),
        "b2": jnp.zeros((n_experts, d_model), param_dtype),
    }


def moe_param_specs(tp="tp", ep="ep"):
    """Mesh-free ``PartitionSpec`` pytree matching init_moe_ffn:
    experts over ``ep``, FFN hidden dim over ``tp`` (pass ``None`` to
    drop an axis) — the spec twin ``moe_param_shardings`` binds."""
    from jax.sharding import PartitionSpec as P
    return {
        "router": P(),
        "w1": P(ep, None, tp),
        "b1": P(ep, tp),
        "w2": P(ep, tp, None),
        "b2": P(ep, None),
    }


def moe_param_shardings(mesh):
    """NamedSharding pytree matching init_moe_ffn: experts over ``ep``,
    FFN hidden dim over ``tp`` when present."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    specs = moe_param_specs(
        tp="tp" if "tp" in mesh.axis_names else None,
        ep="ep" if "ep" in mesh.axis_names else None)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))


def _top_k_gating(gates, k):
    """gates (G, S, E) softmax probs → per-slot expert index + gate value,
    shapes (G, S, k), slot 0 = highest gate."""
    import jax
    val, idx = jax.lax.top_k(gates, k)
    return idx, val


def moe_ffn(x, params, *, n_experts, top_k=2, capacity_factor=1.25,
            mesh=None, activation="gelu", dtype=None):
    """MoE FFN: x (G, S, D) → (y (G, S, D), aux_loss scalar).

    G = token groups (the batch dim), S = tokens per group.  Each group
    routes independently with expert capacity
    ``C = ceil(top_k * S * capacity_factor / E)``; overflow tokens fall
    through the residual (their y contribution is 0).
    """
    import jax
    import jax.numpy as jnp

    G, S, D = x.shape
    E = n_experts
    if top_k > E:
        raise MXNetError("moe_ffn: top_k=%d > n_experts=%d (lower "
                         "expert_top_k or add experts)" % (top_k, E))
    C = max(1, math.ceil(top_k * S * capacity_factor / E))
    cdt = dtype or x.dtype

    router_logits = (x.astype(jnp.float32)
                     @ params["router"].astype(jnp.float32))
    gates = jax.nn.softmax(router_logits, axis=-1)        # (G, S, E)

    # Switch aux loss: E * Σ_e (token-fraction_e · mean-prob_e)
    top1 = jnp.argmax(gates, axis=-1)
    frac = jnp.mean(jax.nn.one_hot(top1, E, dtype=jnp.float32),
                    axis=(0, 1))
    prob = jnp.mean(gates, axis=(0, 1))
    aux_loss = E * jnp.sum(frac * prob)

    idx, val = _top_k_gating(gates, top_k)                # (G, S, k)
    # renormalize selected gate values per token
    val = val / jnp.maximum(jnp.sum(val, -1, keepdims=True), 1e-9)

    # capacity assignment: position of each (token, slot) in its expert's
    # buffer, counted in slot-major order so slot-0 picks win capacity.
    onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)      # (G, S, k, E)
    flat = onehot.transpose(0, 2, 1, 3).reshape(G, top_k * S, E)
    pos_flat = jnp.cumsum(flat, axis=1) - flat            # (G, kS, E)
    pos = pos_flat.reshape(G, top_k, S, E).transpose(0, 2, 1, 3)
    pos = jnp.sum(pos * onehot, axis=-1)                  # (G, S, k)
    keep = pos < C

    # (G, S, k, E, C) slot one-hot; overflow slots map to the dropped
    # C-th class.  dispatch sums slots; combine weights them by gate.
    slot_oh = (jax.nn.one_hot(idx, E, dtype=jnp.float32)[..., None]
               * jax.nn.one_hot(jnp.where(keep, pos, C), C + 1,
                                dtype=jnp.float32)[..., None, :-1])
    disp = jnp.sum(slot_oh, axis=2)                       # (G, S, E, C)
    combine = jnp.sum(
        slot_oh * val[..., None, None].astype(jnp.float32),
        axis=2)                                           # (G, S, E, C)

    xin = jnp.einsum("gsec,gsd->egcd", disp.astype(cdt), x.astype(cdt))
    # constraints only along axes that actually partition — a trivial
    # (size-1) constraint is not free on every backend (docs/perf.md);
    # gate per-axis so dp stays constrained even when ep is trivial
    from .mesh import live_axis
    ep = live_axis(mesh, "ep")
    dp = live_axis(mesh, "dp")
    if ep or dp:
        from jax.sharding import NamedSharding, PartitionSpec as P
        # keep the token-group dim dp-sharded — pinning it replicated
        # would all-gather over dp and fold-duplicate the expert FLOPs
        xin = jax.lax.with_sharding_constraint(
            xin, NamedSharding(mesh, P(ep, dp, None, None)))

    h = jnp.einsum("egcd,edf->egcf", xin, params["w1"].astype(cdt))
    h = h + params["b1"][:, None, None, :].astype(cdt)
    if activation == "gelu":
        h = jax.nn.gelu(h, approximate=True)
    elif activation == "relu":
        h = jax.nn.relu(h)
    else:
        raise MXNetError("unknown activation %r" % activation)
    y = jnp.einsum("egcf,efd->egcd", h, params["w2"].astype(cdt))
    y = y + params["b2"][:, None, None, :].astype(cdt)
    if ep or dp:
        y = jax.lax.with_sharding_constraint(
            y, NamedSharding(mesh, P(ep, dp, None, None)))

    out = jnp.einsum("gsec,egcd->gsd", combine.astype(cdt), y)
    return out.astype(x.dtype), aux_loss
