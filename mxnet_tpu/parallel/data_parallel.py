"""Sharded data-parallel training for Gluon blocks.

Reference semantics: ``DataParallelExecutorGroup`` + KVStore allreduce
(SURVEY.md §2.4 row 1, §3.4).  TPU-native mechanism: ONE jitted train step
over a Mesh — params placed replicated, batch sharded over ``dp`` — and
XLA GSPMD emits the gradient psum over ICI.  This subsumes
``split_and_load`` + push/pull: no Python-level per-device loop, no
explicit collective calls.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ..base import MXNetError

__all__ = ["DataParallelTrainer"]


class DataParallelTrainer:
    """Compile a (block, loss, optimizer) triple into one sharded step.

    Usage::

        mesh = make_mesh({"dp": 8})
        dpt  = DataParallelTrainer(net, loss_fn, "sgd",
                                   {"learning_rate": 0.1}, mesh)
        loss = dpt.step(data_batch, label_batch)   # batch sharded on dp

    The Gluon block's parameters are read once into a pytree; updates run
    inside the jitted step (fused with the backward, like the reference's
    engine-overlapped ``*_update`` ops); ``sync_back()`` writes final
    values into the Parameter buffers for checkpointing.
    """

    def __init__(self, block, loss_fn, optimizer="sgd",
                 optimizer_params=None, mesh=None, grad_clip=None,
                 amp=False, shard_optimizer=False):
        import jax
        import optax
        from .mesh import default_mesh
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.block = block
        self.loss_fn = loss_fn
        # amp=True: the contrib/amp per-op cast hook runs during the
        # traced forward — MXU-bound ops (conv/FC/matmul) take bfloat16
        # inputs while the FP32_OPS list (BatchNorm, softmax, reductions,
        # losses) stays float32; params remain f32 masters.  No loss
        # scaler needed, bf16 exponent range matches f32.
        self.amp = amp
        self.mesh = mesh if mesh is not None else default_mesh()
        optimizer_params = dict(optimizer_params or {})
        lr = optimizer_params.pop("learning_rate", 0.01)
        momentum = optimizer_params.pop("momentum", 0.0)
        wd = optimizer_params.pop("wd", 0.0)
        if optimizer == "sgd":
            tx = optax.sgd(lr, momentum=momentum)
            if wd:
                tx = optax.chain(optax.add_decayed_weights(wd), tx)
        elif optimizer == "adam":
            tx = optax.adam(lr)
        elif optimizer == "adamw":
            tx = optax.adamw(lr, weight_decay=wd)
        elif optimizer == "lamb":
            tx = optax.lamb(lr, weight_decay=wd)
        else:
            raise MXNetError("DataParallelTrainer: unknown optimizer %r"
                             % optimizer)
        if grad_clip:
            tx = optax.chain(optax.clip_by_global_norm(grad_clip), tx)
        self.tx = tx

        self._param_objs = list(block.collect_params().values())
        # on a trivial (1-device) mesh, committing arrays to a
        # NamedSharding routes execution through the SPMD-partitioned
        # path — measured 130x slower on the tunneled chip here
        # (docs/perf.md "Methodology") — so skip all sharding commits
        self._trivial = self.mesh.size == 1
        self._rep = None if self._trivial else NamedSharding(self.mesh, P())
        # ZeRO-1: optimizer state sharded over the data axis — 'dp' if
        # present, else the mesh's first axis, matching how the batch is
        # sharded (SURVEY.md §2.4 — the PS server-side optimizer update)
        self._data_axis = ("dp" if "dp" in self.mesh.axis_names
                           else self.mesh.axis_names[0])
        self._shard_opt = (shard_optimizer
                           and self.mesh.shape[self._data_axis] > 1)
        self._batch_sharding = None
        self._state = None
        self._jit_step = None
        self._multi_jit = {}

    # -- param pytree <-> gluon Parameters --------------------------------
    def _gather_params(self):
        import jax
        vals = [p.data()._data for p in self._param_objs]
        if self._trivial:
            # Guardrail (round 4): on a trivial mesh no sharding commit
            # happens, so params initialized without ctx=mx.tpu() would
            # keep the whole train step on the HOST backend — resnet18
            # silently ran at 25 s/step on this 1-vCPU box while
            # looking like a TPU run.  Move host-platform params onto
            # the mesh device instead (one-time transfer, same place
            # sync_back reads from).
            dev = self.mesh.devices.ravel()[0]
            if dev.platform != "cpu":
                moved = False
                out = []
                for v in vals:
                    vdev = next(iter(v.devices()))
                    if vdev.platform == "cpu":
                        out.append(jax.device_put(v, dev))
                        moved = True
                    else:
                        out.append(v)
                if moved:
                    import logging
                    logging.getLogger(__name__).info(
                        "DataParallelTrainer: moved host-resident "
                        "params onto %s (initialize with ctx=mx.tpu() "
                        "to avoid the transfer)", dev)
                return out
            return vals
        from .multihost import host_staged_put
        return [host_staged_put(v, self._rep) for v in vals]

    def sync(self):
        """Block until every queued step has fully executed (the loss
        buffer alone can materialize before the tail of the donated-state
        pipeline — benchmark timing must drain the params too).

        ``block_until_ready`` alone is not trusted: some PjRt transports
        (e.g. the tunneled axon plugin in this environment) report buffers
        ready while the execution queue is still draining.  Fetching one
        element of the newest state output forces the last program to
        actually retire — the analog of the reference engine's
        ``WaitForAll`` (SURVEY.md §3.1 sync points)."""
        import jax
        if self._state is not None:
            jax.block_until_ready(self._state)
            leaf = jax.tree_util.tree_leaves(self._state)[0]
            jax.device_get(leaf.ravel()[:1])
        return self

    def sync_back(self):
        """Write trained values back into the Gluon Parameters."""
        if self._state is None:
            return
        params = self._state[0]
        for p, v in zip(self._param_objs, params):
            for c in p._data:
                p._data[c]._set_data(v)

    # -- the step ----------------------------------------------------------
    def _build(self, data, label):
        import jax
        import optax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..gluon.block import _CachedOp
        from .. import autograd

        block = self.block
        loss_fn = self.loss_fn
        params = self._param_objs
        tx = self.tx

        # trace block+loss into a pure function of (param_list, data, label)
        from ..ndarray.ndarray import NDArray
        from collections import OrderedDict
        from ..gluon.block import _TRACE_STATE

        # resolve any deferred-init parameter shapes before gathering
        if hasattr(block, "_resolve_deferred"):
            block._resolve_deferred(NDArray(data))

        amp = self.amp
        # filled during tracing: which params an op mutated in-place
        # (BatchNorm running stats via the mutate=(3,4) contract); those
        # carry their forward-computed value instead of an optimizer step.
        mutated_flags: List[bool] = []

        def pure_loss(param_vals, d, l):
            import jax.numpy as jnp
            from .. import random as mxrand
            from ..ops import registry as _registry
            mxrand.push_trace_key(jax.random.PRNGKey(0))
            _TRACE_STATE.active = getattr(_TRACE_STATE, "active", 0) + 1
            saved = [(p, dict(p._data)) for p in params]
            prev_hook = _registry._CAST_HOOK
            try:
                if amp:
                    from ..contrib.amp.amp import _make_hook
                    _registry.set_cast_hook(_make_hook("bfloat16"))
                wrapped = [NDArray(v) for v in param_vals]
                for p, w in zip(params, wrapped):
                    c = next(iter(p._data))
                    p._data = OrderedDict({c: w})
                with autograd._scope(False, True):
                    out = block.forward_raw(NDArray(d))
                    loss = loss_fn(out, NDArray(l))
                # capture in-place mutations (aux states) before restore
                del mutated_flags[:]
                new_vals = []
                for w, orig in zip(wrapped, param_vals):
                    mutated_flags.append(w._data is not orig)
                    new_vals.append(w._data)
                return loss._data.astype(jnp.float32).mean(), new_vals
            finally:
                _registry.set_cast_hook(prev_hook)
                for p, old in saved:
                    p._data = OrderedDict(old)
                _TRACE_STATE.active -= 1
                mxrand.pop_trace_key()

        def step(state, d, l):
            pvals, opt_state = state
            (loss, new_vals), grads = jax.value_and_grad(
                pure_loss, has_aux=True)(pvals, d, l)
            updates, opt_state = tx.update(grads, opt_state, pvals)
            pvals = optax.apply_updates(pvals, updates)
            # mutated aux (e.g. BN moving stats) take their in-forward
            # value — the reference's engine applies the same write
            pvals = [nv.astype(pv.dtype) if m else pv
                     for pv, nv, m in zip(pvals, new_vals,
                                          mutated_flags)]
            return (pvals, opt_state), loss

        pvals = self._gather_params()
        if self._shard_opt:
            from .mesh import init_sharded_opt_state
            opt_state = init_sharded_opt_state(
                self.tx, pvals, self.mesh, axis=self._data_axis)
        elif self._trivial:
            opt_state = self.tx.init(pvals)
        else:
            opt_state = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, self._rep),
                self.tx.init(pvals))
        self._state = (pvals, opt_state)
        self._batch_sharding = None if self._trivial else NamedSharding(
            self.mesh, P(self._data_axis))
        self._step_fn = step
        self._jit_step = jax.jit(step, donate_argnums=(0,))
        self._multi_jit = {}

    def _place_batch(self, d, l):
        """Batch placement: shard over the mesh, or (trivial mesh) move
        host arrays to the accelerator so they match the params the
        round-4 guardrail placed there."""
        import jax
        if not self._trivial:
            return (jax.device_put(d, self._batch_sharding),
                    jax.device_put(l, self._batch_sharding))
        dev = self.mesh.devices.ravel()[0]
        if dev.platform != "cpu":
            def plat(x):                 # numpy input counts as host
                try:
                    return next(iter(x.devices())).platform
                except AttributeError:
                    return "cpu"
            if plat(d) == "cpu":
                d = jax.device_put(d, dev)
            if plat(l) == "cpu":
                l = jax.device_put(l, dev)
        return d, l

    def step(self, data, label):
        """One data-parallel training step; returns scalar loss."""
        from ..ndarray.ndarray import NDArray, _wrap
        d = data._data if isinstance(data, NDArray) else data
        l = label._data if isinstance(label, NDArray) else label
        if self._jit_step is None:
            self._build(d, l)
        d, l = self._place_batch(d, l)
        self._state, loss = self._jit_step(self._state, d, l)
        return _wrap(loss)

    def run_steps(self, data, label, steps=None):
        """Run many training steps inside ONE jitted device loop.

        Per-dispatch latency (host→device RPC, graph launch) caps the
        step rate of :meth:`step` long before the MXU saturates — on the
        tunneled chip in this environment a single dispatch round-trip
        costs tens of milliseconds.  The TPU-native cure is the device
        loop: ``lax.scan`` over the train step, one dispatch for K steps
        (the same shape as the reference's engine-level op bulking,
        ``MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN`` — SURVEY.md §3.3 — and
        classic TPU infeed training loops).

        Two data modes:

        * ``steps=None`` — *superbatch*: ``data``/``label`` carry a
          leading ``K`` axis (``(K, batch, ...)``); step ``i`` trains on
          slice ``i``.
        * ``steps=K`` — *reuse*: the single batch is reused for every
          step (synthetic benchmarking).

        Returns the per-step losses as an NDArray of shape ``(K,)``.
        """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..ndarray.ndarray import NDArray, _wrap
        d = data._data if isinstance(data, NDArray) else data
        l = label._data if isinstance(label, NDArray) else label
        superbatch = steps is None
        if superbatch:
            if d.shape[0] != l.shape[0]:
                raise MXNetError("run_steps: superbatch leading dims "
                                 "disagree: %r vs %r"
                                 % (d.shape, l.shape))
            steps = int(d.shape[0])
        if self._jit_step is None:
            self._build(d[0] if superbatch else d,
                        l[0] if superbatch else l)
        key = (steps, superbatch)
        if key not in self._multi_jit:
            step_fn = self._step_fn

            def multi(state, d, l):
                def body(st, xs):
                    dd, ll = (d, l) if xs is None else xs
                    return step_fn(st, dd, ll)
                return jax.lax.scan(
                    body, state,
                    (d, l) if superbatch else None, length=steps)

            self._multi_jit[key] = jax.jit(multi, donate_argnums=(0,))
        if self._trivial:
            d, l = self._place_batch(d, l)
        elif superbatch:
            sb = NamedSharding(
                self.mesh, P(None, self._data_axis))
            d = jax.device_put(d, sb)
            l = jax.device_put(l, sb)
        else:
            d = jax.device_put(d, self._batch_sharding)
            l = jax.device_put(l, self._batch_sharding)
        self._state, losses = self._multi_jit[key](self._state, d, l)
        return _wrap(losses)
