"""2-bit gradient compression with error feedback.

Reference: ``src/kvstore/gradient_compression.cc`` (SURVEY.md §2.1 KVStore
row, §2.4 "Gradient compression"): each gradient element is quantized to
one of {-threshold, 0, +threshold} encoded in 2 bits (16x smaller wire
payload than f32), and the quantization error is kept in a worker-local
*residual* that is added to the next round's gradient — so the error
feeds back instead of being lost, and the long-run sum of decompressed
gradients tracks the true sum.

TPU-native split: the multi-chip THROUGHPUT path (GSPMD psum over ICI)
never sees this code — on-chip interconnect does not want host round
trips.  Compression applies to the *host-side wire paths* that mirror the
reference's use of it: the TCP parameter server (``parallel/dist.py``)
and the local kvstore's cross-device aggregate (``kvstore/kvstore.py``),
where payloads actually traverse host memory / sockets.

Codes: ``0b00`` → 0, ``0b01`` → +threshold, ``0b10`` → -threshold,
packed four-per-byte little-end-first (matching the reference's
quantize_2bit kernel layout of 16 values per int32 word).
"""
from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

__all__ = ["TwoBitCompressor", "create_compressor"]


class TwoBitCompressor:
    """Stateful 2-bit quantizer (state = per-key error-feedback residual).

    One instance lives on each *sender* (worker); the receiver only needs
    the stateless :meth:`decompress`.
    """

    def __init__(self, threshold: float = 0.5):
        if threshold <= 0:
            raise ValueError("threshold must be positive, got %r"
                             % (threshold,))
        self.threshold = float(threshold)
        self._residual: Dict[object, np.ndarray] = {}

    # -- sender -----------------------------------------------------------

    def compress(self, key, grad: np.ndarray) -> Tuple[bytes, tuple, str]:
        """grad → (packed 2-bit codes, shape, dtype-name).

        Adds the stored residual first, then quantizes and keeps the new
        residual (reference: ``Quantize2BitKernel`` + the error-feedback
        buffer held in ``GradientCompression``).
        """
        grad = np.asarray(grad)
        flat = grad.astype(np.float32).ravel()
        res = self._residual.get(key)
        if res is None or res.shape != flat.shape:
            res = np.zeros_like(flat)
        adj = flat + res
        t = self.threshold
        codes = np.zeros(flat.shape, dtype=np.uint8)
        codes[adj >= t] = 1
        codes[adj <= -t] = 2
        deq = np.where(codes == 1, t, 0.0) + np.where(codes == 2, -t, 0.0)
        self._residual[key] = adj - deq.astype(np.float32)
        pad = (-len(codes)) % 4
        if pad:
            codes = np.concatenate([codes, np.zeros(pad, np.uint8)])
        c = codes.reshape(-1, 4)
        packed = (c[:, 0] | (c[:, 1] << 2) | (c[:, 2] << 4)
                  | (c[:, 3] << 6)).astype(np.uint8)
        return packed.tobytes(), grad.shape, str(grad.dtype)

    # -- receiver ---------------------------------------------------------

    def decompress(self, payload: bytes, shape: tuple,
                   dtype: str = "float32") -> np.ndarray:
        return decompress(payload, shape, self.threshold, dtype)


def decompress(payload: bytes, shape: tuple, threshold: float,
               dtype: str = "float32") -> np.ndarray:
    """Stateless unpack — all a receiver needs (no residual lives on the
    server side)."""
    packed = np.frombuffer(payload, dtype=np.uint8)
    n = int(np.prod(shape)) if shape else 1
    codes = np.empty((len(packed), 4), dtype=np.uint8)
    codes[:, 0] = packed & 0x3
    codes[:, 1] = (packed >> 2) & 0x3
    codes[:, 2] = (packed >> 4) & 0x3
    codes[:, 3] = (packed >> 6) & 0x3
    codes = codes.ravel()[:n]
    t = threshold
    out = np.where(codes == 1, t, 0.0) + np.where(codes == 2, -t, 0.0)
    return out.astype(dtype).reshape(shape)


def create_compressor(params) -> TwoBitCompressor:
    """``set_gradient_compression`` params → compressor (reference:
    ``GradientCompression::SetParams``; only type='2bit' exists there
    too)."""
    params = dict(params or {})
    ctype = params.pop("type", "2bit")
    if ctype != "2bit":
        raise ValueError("unsupported gradient compression type %r "
                         "(the reference supports '2bit' only)" % ctype)
    threshold = float(params.pop("threshold", 0.5))
    if params:
        raise ValueError("unknown gradient compression params %r"
                         % (sorted(params),))
    return TwoBitCompressor(threshold)
