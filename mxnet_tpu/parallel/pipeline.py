"""Pipeline parallelism: GPipe microbatch schedule over a ``pp`` mesh axis.

No reference counterpart — MXNet 1.x has only manual model parallelism
(``group2ctx`` + the nnvm ``place_device`` pass, SURVEY.md §2.4); pipeline
parallelism is a TPU-build extension.  Design is the collective-pipelining
recipe: each ``pp`` shard holds a contiguous block of layers ("stage"),
activations hop one stage per step with ``lax.ppermute`` over ICI, and a
``lax.scan`` runs the ``n_microbatches + n_stages - 1`` step GPipe
schedule.  Everything is scan + ppermute + where, so reverse-mode AD
yields the mirrored backward pipeline for free.

The ``pp`` axis is the ONLY manual axis (``shard_map(axis_names={axis})``);
``dp``/``tp`` stay auto, so GSPMD still lays out the in-stage matmuls and
inserts the gradient psum over ``dp``.
"""
from __future__ import annotations

import functools

from ..base import MXNetError

__all__ = ["pipeline_apply", "stack_layer_params"]


def stack_layer_params(layers):
    """List of per-layer param pytrees (same structure) → one pytree whose
    leaves gain a leading ``n_layers`` axis.  This is the layout pipeline
    stages index into; shard the leading axis over ``pp``."""
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *layers)


def _tree_index(tree, i):
    import jax
    return jax.tree_util.tree_map(lambda a: a[i], tree)


def pipeline_apply(stage_fn, stacked_params, x, aux=None, *, mesh,
                   axis="pp", n_microbatches, has_aux=False):
    """Run ``x`` through a layer stack pipelined over ``mesh`` axis ``axis``.

    stage_fn(stage_params, x_mub, aux_mub, stage_idx, mub_idx) -> x_out
        applies ONE stage's layers to one microbatch.  ``stage_params``
        leaves have leading dim ``n_layers // n_stages``; ``stage_idx`` /
        ``mub_idx`` are traced int32 scalars (use ``jax.random.fold_in``
        for per-site dropout keys).  With ``has_aux=True`` it instead
        returns ``(x_out, aux_scalar)`` (e.g. a MoE load-balancing loss).
    stacked_params : pytree with leading ``n_layers`` axis
        (see :func:`stack_layer_params`).
    x : (B, ...) global batch; B must divide by ``n_microbatches``.
    aux : optional pytree of (B, ...) per-example tensors that travel with
        their microbatch unchanged (attention masks, per-row keys, ...).

    Returns (B, ...) output of the final stage — or, with ``has_aux``,
    ``(output, aux_total)`` where ``aux_total`` is the microbatch-mean of
    the per-stage aux scalars summed over stages (matching what a
    sequential full-batch pass would report).  Differentiable.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    if axis not in mesh.axis_names:
        raise MXNetError("mesh has no axis %r" % axis)
    n_stages = mesh.shape[axis]
    B = x.shape[0]
    if B % n_microbatches:
        raise MXNetError("batch %d %% n_microbatches %d != 0"
                         % (B, n_microbatches))
    mub = B // n_microbatches
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_layers % n_stages:
        raise MXNetError("n_layers %d %% pp %d != 0" % (n_layers, n_stages))
    per_stage = n_layers // n_stages

    # (n_layers, ...) -> (n_stages, per_stage, ...); P(axis) on dim 0 gives
    # each pp shard exactly its stage block.
    staged = jax.tree_util.tree_map(
        lambda a: a.reshape((n_stages, per_stage) + a.shape[1:]),
        stacked_params)
    xm = x.reshape((n_microbatches, mub) + x.shape[1:])
    auxm = jax.tree_util.tree_map(
        lambda a: a.reshape((n_microbatches, mub) + a.shape[1:]), aux)

    n_iter = n_microbatches + n_stages - 1
    fwd_perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    # XLA:CPU workaround: AllReducePromotion crashes ("Invalid binary
    # instruction opcode copy") cloning the bf16 gradient all-reduce
    # this partial-manual shard_map produces — reduced repro committed
    # at docs/xla_cpu_bf16_pp_repro.py.  Keep bf16 PARAM leaves f32
    # across the shard_map boundary on CPU (their grad psum then runs
    # f32, which the pass leaves alone) and cast back inside the manual
    # region; activations and compute stay bf16.  TPU takes the direct
    # path.
    cpu_bf16_fix = mesh.devices.flat[0].platform == "cpu"
    p_dtypes = jax.tree_util.tree_map(lambda a: a.dtype, staged)
    x_dtype = xm.dtype
    aux_dtypes = jax.tree_util.tree_map(lambda a: a.dtype, auxm)

    def _widen(t):
        return jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float32)
            if a.dtype == jnp.bfloat16 else a, t)

    def _narrow(t, dtypes):
        return jax.tree_util.tree_map(lambda a, d: a.astype(d), t,
                                      dtypes)

    if cpu_bf16_fix:
        # every input replicated over the manual axis whose grad needs
        # a pp all-reduce must cross the boundary as f32 (params AND
        # activations/aux) — see the repro note above
        staged, xm, auxm = _widen(staged), _widen(xm), _widen(auxm)

    def per_shard(staged_p, xm, auxm):
        if cpu_bf16_fix:
            staged_p = _narrow(staged_p, p_dtypes)
            xm = xm.astype(x_dtype)
            auxm = _narrow(auxm, aux_dtypes)
        stage_p = _tree_index(staged_p, 0)      # squeeze P(axis) block
        s = jax.lax.axis_index(axis)

        def body(carry, t):
            state, out_acc, aux_acc = carry
            m = jnp.clip(t - s, 0, n_microbatches - 1)
            # stage 0 injects microbatch t; others take the ppermuted
            # activation handed over from stage s-1 last step.
            inject = jax.lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, n_microbatches - 1), keepdims=False)
            cur = jnp.where(s == 0, inject, state)
            aux_mub = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, m, keepdims=False),
                auxm)
            res = stage_fn(stage_p, cur, aux_mub, s, m)
            y, aux_s = res if has_aux else (res, 0.0)
            y = y.astype(xm.dtype)
            active = (t - s >= 0) & (t - s < n_microbatches)
            is_last = s == n_stages - 1
            out_acc = jnp.where(
                active & is_last,
                jax.lax.dynamic_update_index_in_dim(out_acc, y, m, 0),
                out_acc)
            aux_acc = aux_acc + jnp.where(active, aux_s, 0.0)
            state = jax.lax.ppermute(y, axis, fwd_perm)
            return (state, out_acc, aux_acc), ()

        state0 = jnp.zeros_like(xm[0])
        out0 = jnp.zeros_like(xm)
        aux0 = jnp.zeros((), jnp.float32)
        (_, out_acc, aux_acc), _ = jax.lax.scan(
            body, (state0, out0, aux0), jnp.arange(n_iter))
        # emit per-stage accumulators; only the last stage's out is real,
        # aux sums across stages.
        return out_acc[None], aux_acc[None]

    from .mesh import shard_map_compat
    sharded = shard_map_compat(
        per_shard, mesh=mesh,
        in_specs=(P(axis), P(), P()),
        out_specs=(P(axis), P(axis)),
        axis_names={axis}, check_vma=False,
    )
    # Partial-manual shard_map (axis_names ⊂ mesh axes) only lowers
    # correctly under jit in jax 0.9 — the eager impl path re-enters
    # shard_map with full-mesh manual axes and rejects the specs.  Under
    # an outer jit this inner jit is inlined.
    out, aux_out = jax.jit(sharded)(staged, xm, auxm)
    # (n_stages, n_microbatches, mub, ...) — last stage holds the output.
    result = out[-1].reshape((B,) + out.shape[3:])
    if has_aux:
        return result, aux_out.sum() / n_microbatches
    return result
