"""Parallelism subsystem — mesh, sharded training, collectives.

Replaces the reference's KVStore/NCCL/ps-lite stack (SURVEY.md §2.4) with
XLA collectives over a ``jax.sharding.Mesh``.
"""
from .mesh import (make_mesh, default_mesh, serving_mesh, current_mesh,
                   mesh_scope, live_axis)
from .data_parallel import DataParallelTrainer
from .ring_attention import (ring_attention, ulysses_attention,
                             sequence_parallel_attention)
from .pipeline import pipeline_apply, stack_layer_params
from .moe import init_moe_ffn, moe_ffn, moe_param_shardings
from .checkpoint import save_sharded, restore_sharded, latest_step
from . import multihost
