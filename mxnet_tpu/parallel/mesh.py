"""Device-mesh utilities — the substrate for all parallelism.

No reference counterpart: MXNet 1.x scales via per-device replicas + NCCL
(SURVEY.md §2.4).  The TPU-native design replaces that with one logical
array sharded over a ``jax.sharding.Mesh``; XLA GSPMD inserts the ICI
collectives (psum/all-gather/reduce-scatter) that ``kvstore_nccl.h``
issued by hand.  Axes follow scaling-book conventions:

* ``dp`` — data parallel (batch dim)
* ``tp`` — tensor parallel (hidden dims of attention/FFN weights)
* ``pp`` — pipeline stages
* ``sp`` — sequence/context parallel (ring attention)
* ``ep`` — expert parallel (MoE)
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

from ..base import MXNetError

__all__ = ["make_mesh", "default_mesh", "current_mesh", "mesh_scope",
           "live_axis"]

_CURRENT = []


def make_mesh(shape: Optional[dict] = None, devices=None):
    """Create a Mesh.  ``shape`` maps axis name -> size; sizes must
    multiply to the device count.  ``{"dp": -1}`` means "all devices"."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if not shape:
        shape = {"dp": n}
    names = list(shape.keys())
    sizes = list(shape.values())
    n_auto = sizes.count(-1)
    if n_auto > 1:
        raise MXNetError("At most one mesh axis may be -1")
    if n_auto == 1:
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        if n % known:
            raise MXNetError("Mesh %s does not divide %d devices"
                             % (shape, n))
        sizes[sizes.index(-1)] = n // known
    total = 1
    for s in sizes:
        total *= s
    if total != n:
        raise MXNetError("Mesh %s needs %d devices but %d are visible"
                         % (dict(zip(names, sizes)), total, n))
    dev_array = np.array(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def default_mesh():
    """All devices on one ``dp`` axis."""
    return make_mesh()


class mesh_scope:
    """Context manager setting the current mesh."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        _CURRENT.append(self.mesh)
        return self.mesh

    def __exit__(self, *a):
        _CURRENT.pop()


def current_mesh():
    return _CURRENT[-1] if _CURRENT else None


def live_axis(mesh, name):
    """``name`` if the mesh has that axis AND it actually partitions
    (size > 1), else None.  Sharding constraints over trivial axes are
    semantically no-ops but not free on every backend — on the tunneled
    chip here they materialize a copy per constraint (docs/perf.md
    "Methodology") — so constraint sites build specs from live axes
    only."""
    if mesh is None or name not in mesh.axis_names:
        return None
    return name if mesh.shape[name] > 1 else None


def zero1_sharding(leaf, mesh, axis="dp"):
    """ZeRO-1 placement for one optimizer-state leaf: shard over the
    data axis on the leading dim when it divides; small/indivisible
    leaves replicate (SURVEY.md §2.4 — the PS server-side optimizer
    update)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis]
    if hasattr(leaf, "ndim") and leaf.ndim >= 1 \
            and leaf.shape[0] % n == 0 and leaf.shape[0] > 0:
        return NamedSharding(mesh, P(axis, *([None] * (leaf.ndim - 1))))
    return NamedSharding(mesh, P())


def init_sharded_opt_state(tx, params, mesh, axis="dp"):
    """Initialize an optax state directly INTO its ZeRO-1 shards —
    init-then-reshard would peak at full replicated size, defeating the
    reason to shard."""
    import jax
    placements = jax.tree_util.tree_map(
        lambda l: zero1_sharding(l, mesh, axis=axis),
        jax.eval_shape(tx.init, params))
    return jax.jit(tx.init, out_shardings=placements)(params)
