"""Device-mesh utilities — the substrate for all parallelism.

No reference counterpart: MXNet 1.x scales via per-device replicas + NCCL
(SURVEY.md §2.4).  The TPU-native design replaces that with one logical
array sharded over a ``jax.sharding.Mesh``; XLA GSPMD inserts the ICI
collectives (psum/all-gather/reduce-scatter) that ``kvstore_nccl.h``
issued by hand.  Axes follow scaling-book conventions:

* ``dp`` — data parallel (batch dim)
* ``tp`` — tensor parallel (hidden dims of attention/FFN weights)
* ``pp`` — pipeline stages
* ``sp`` — sequence/context parallel (ring attention)
* ``ep`` — expert parallel (MoE)
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

from ..base import MXNetError

__all__ = ["make_mesh", "default_mesh", "serving_mesh", "current_mesh",
           "mesh_scope", "live_axis", "shard_map_compat"]

_CURRENT = []


def shard_map_compat(fn, *, mesh, in_specs, out_specs, axis_names=None,
                     check_vma=True):
    """``jax.shard_map`` across the jax version drift (round 6, same
    class as the ``enable_x64`` spelling fixes): jax >= 0.5 exposes
    ``jax.shard_map(..., axis_names=..., check_vma=...)``; 0.4.x has
    ``jax.experimental.shard_map.shard_map(..., auto=..., check_rep=…)``
    where ``auto`` is the complement of ``axis_names`` (the axes left
    automatic) and ``check_rep`` is the old name for the replication
    check.

    Caveat: on 0.4.x the FULL-manual form lowers fine (ring attention),
    but the partial-manual form (``axis_names`` a strict subset — the
    pipeline's ``pp``-only mapping with ``dp`` auto) hits a GSPMD
    tile-assignment bug under scan; those paths need the >= 0.5-era
    lowering (tests/test_pipeline_moe.py documents the failure)."""
    import jax

    if hasattr(jax, "shard_map"):
        kw = {"check_vma": check_vma}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm
    kw = {"check_rep": check_vma}
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - set(axis_names)
        if auto:
            kw["auto"] = auto
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               **kw)


def make_mesh(shape: Optional[dict] = None, devices=None):
    """Create a Mesh.  ``shape`` maps axis name -> size; sizes must
    multiply to the device count.  ``{"dp": -1}`` means "all devices"."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if not shape:
        shape = {"dp": n}
    names = list(shape.keys())
    sizes = list(shape.values())
    n_auto = sizes.count(-1)
    if n_auto > 1:
        raise MXNetError("At most one mesh axis may be -1")
    if n_auto == 1:
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        if n % known:
            raise MXNetError("Mesh %s does not divide %d devices"
                             % (shape, n))
        sizes[sizes.index(-1)] = n // known
    total = 1
    for s in sizes:
        total *= s
    if total != n:
        raise MXNetError("Mesh %s needs %d devices but %d are visible"
                         % (dict(zip(names, sizes)), total, n))
    dev_array = np.array(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def default_mesh():
    """All devices on one ``dp`` axis."""
    return make_mesh()


def serving_mesh(tp=1, devices=None):
    """Serving-shaped mesh: one ``tp`` axis over the first ``tp``
    devices.  The serving engine is single-program (no batch axis to
    data-parallelize inside one replica — scale-out is the
    ``ServingCluster``'s job), so its mesh is one tensor-parallel axis
    and nothing else; the megatron rules in ``models/transformer.py``
    and the engine's pool/row specs (``serving/engine.py
    step_input_specs``) name only ``tp``.  Devices beyond ``tp`` stay
    free for other replicas/work."""
    import jax

    if devices is None:
        devices = jax.devices()
    if tp < 1:
        raise MXNetError("serving_mesh: tp must be >= 1, got %r"
                         % (tp,))
    if tp > len(devices):
        raise MXNetError(
            "serving_mesh: tp=%d needs %d devices but only %d are "
            "visible (CPU hosts: set XLA_FLAGS="
            "--xla_force_host_platform_device_count=N before jax "
            "initializes — the virtual mesh the MULTICHIP dry-runs "
            "use)" % (tp, tp, len(devices)))
    return make_mesh({"tp": tp}, devices=list(devices)[:tp])


class mesh_scope:
    """Context manager setting the current mesh."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        _CURRENT.append(self.mesh)
        return self.mesh

    def __exit__(self, *a):
        _CURRENT.pop()


def current_mesh():
    return _CURRENT[-1] if _CURRENT else None


def live_axis(mesh, name):
    """``name`` if the mesh has that axis AND it actually partitions
    (size > 1), else None.  Sharding constraints over trivial axes are
    semantically no-ops but not free on every backend — on the tunneled
    chip here they materialize a copy per constraint (docs/perf.md
    "Methodology") — so constraint sites build specs from live axes
    only."""
    if mesh is None or name not in mesh.axis_names:
        return None
    return name if mesh.shape[name] > 1 else None


def zero1_sharding(leaf, mesh, axis="dp", base=None):
    """ZeRO-1 placement for one optimizer-state leaf: COMPOSE the data
    axis onto the param's own sharding (SURVEY.md §2.4 — the PS
    server-side optimizer update).

    ``base`` is the param's PartitionSpec/NamedSharding (tp etc.).  The
    dp axis is added on the first dimension the base leaves free and
    that divides — keeping the tp entries intact.  Dropping them (the
    round-1 design, P(dp, None, ...)) forced GSPMD into "Involuntary
    full rematerialization" on every gradient all-reduce: the grads
    arrive tp-sharded and the tp→dp transition has no efficient
    collective.  With the composed spec the transition is a plain
    reduce-scatter on the free dim.  Leaves where no dim divides keep
    the base sharding (replicated over dp — no ZeRO for that leaf, but
    no reshard either)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if hasattr(base, "spec"):
        base = base.spec
    ndim = getattr(leaf, "ndim", 0)
    entries = list(base) if base is not None else []
    entries = entries[:ndim] + [None] * (ndim - len(entries))
    # FSDP (round 19): the param's own sharding may already carry the
    # data axis — then the moment takes the param placement verbatim
    # (state is ALREADY ÷dp; composing dp twice would be a spec error)
    for e in entries:
        if e == axis or (isinstance(e, tuple) and axis in e):
            return NamedSharding(mesh, P(*entries))
    n = mesh.shape[axis]
    for i in range(ndim):
        if entries[i] is None and leaf.shape[i] > 0 \
                and leaf.shape[i] % n == 0:
            entries[i] = axis
            break
    return NamedSharding(mesh, P(*entries))


def opt_state_shardings(tx, params, mesh, axis="dp",
                        param_shardings=None):
    """Placement tree for ``tx.init(params)`` under ZeRO-1/FSDP:
    param-shaped state leaves compose the data axis with the param's
    own sharding (or take it verbatim when it already carries the
    axis — the FSDP case); non-param leaves (step counts) replicate.
    ``params`` may be live arrays or abstract shapes — round 19 also
    hands this tree to ``jax.jit(in_shardings=...)`` so state
    donation is provable at lowering."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    shapes = jax.eval_shape(tx.init, params)
    if param_shardings is None:
        return jax.tree_util.tree_map(
            lambda l: zero1_sharding(l, mesh, axis=axis), shapes)
    import optax
    rep = NamedSharding(mesh, P())
    return optax.tree_map_params(
        tx,
        lambda l, s: zero1_sharding(l, mesh, axis=axis, base=s),
        shapes, param_shardings,
        transform_non_params=lambda l: rep)


def init_sharded_opt_state(tx, params, mesh, axis="dp",
                           param_shardings=None):
    """Initialize an optax state directly INTO its ZeRO-1 shards —
    init-then-reshard would peak at full replicated size, defeating the
    reason to shard.  ``param_shardings`` (a tree aligned with
    ``params``) lets param-shaped state leaves compose dp with the
    param's own tp/sp sharding; non-param leaves (step counts)
    replicate."""
    import jax

    placements = opt_state_shardings(tx, params, mesh, axis=axis,
                                     param_shardings=param_shardings)
    return jax.jit(tx.init, out_shardings=placements)(params)
