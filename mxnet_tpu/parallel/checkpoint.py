"""Sharded checkpoint save/restore for mesh-distributed training state.

Reference: the `.params` container (``NDArray::Save/Load``) is the
single-host format (SURVEY.md §5.4, kept in ``ndarray.save/load``); the
survey marks a "sharded multi-host variant" as the TPU extension — this
is it, built on orbax: each host writes only its shards, restore
re-shards to the target mesh layout, so checkpoints of tp/dp/pp/ep
-sharded (params, opt_state) pytrees round-trip without gathering to
one host.
"""
from __future__ import annotations

import os
from typing import Any, Optional

from ..base import MXNetError

__all__ = ["save_sharded", "restore_sharded", "latest_step"]


_CKPT = None


def _checkpointer():
    # one process-wide checkpointer: orbax's async machinery owns a
    # background thread per instance, so per-call construction leaks
    global _CKPT
    if _CKPT is None:
        import orbax.checkpoint as ocp
        _CKPT = ocp.StandardCheckpointer()
    return _CKPT


def save_sharded(path, state, step: Optional[int] = None, force=True):
    """Write ``state`` (a pytree of jax arrays, arbitrary shardings) to
    ``path`` (or ``path/step_N`` when ``step`` is given)."""
    import orbax.checkpoint  # noqa: F401 — fail early with ImportError
    path = os.path.abspath(path)
    if step is not None:
        path = os.path.join(path, "step_%d" % step)
    ckpt = _checkpointer()
    ckpt.save(path, state, force=force)
    ckpt.wait_until_finished()
    return path


def restore_sharded(path, template, step: Optional[int] = None):
    """Restore into the structure/shardings of ``template`` — either a
    live state pytree (its values supply shapes/dtypes/shardings) or a
    pytree of ``jax.ShapeDtypeStruct`` with shardings attached."""
    import jax
    path = os.path.abspath(path)
    if step is not None:
        path = os.path.join(path, "step_%d" % step)
    if not os.path.exists(path):
        raise MXNetError("checkpoint path %r does not exist" % path)

    from jax.sharding import NamedSharding, PartitionSpec

    # the template's mesh (from any NamedSharding leaf): single-device
    # leaves (e.g. optimizer step counters created eagerly) restore as
    # mesh-replicated so the whole state shares one device set — a
    # committed single-device leaf next to mesh-sharded params makes
    # jit reject the state
    mesh = None
    for leaf in jax.tree_util.tree_leaves(template):
        s = getattr(leaf, "sharding", None)
        if isinstance(s, NamedSharding):
            mesh = s.mesh
            break

    def as_abstract(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        s = getattr(x, "sharding", None)
        if mesh is not None and not isinstance(s, NamedSharding):
            s = NamedSharding(mesh, PartitionSpec())
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)

    abstract = jax.tree_util.tree_map(as_abstract, template)
    return _checkpointer().restore(path, abstract)


def latest_step(path):
    """Largest N among ``path/step_N`` subdirectories, or None."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return None
    steps = []
    for d in os.listdir(path):
        if d.startswith("step_"):
            try:
                steps.append(int(d[5:]))
            except ValueError:
                pass
    return max(steps) if steps else None
