"""Detection-task image augmenters + iterator.

Reference: ``python/mxnet/image/detection.py`` (SURVEY.md §2.2 "IO/image"
row: ``image/detection.py``).  Labels are (N, 5+) float arrays of
``[class_id, xmin, ymin, xmax, ymax, ...]`` with coordinates normalized
to [0, 1]; every augmenter transforms image AND label together.  Crops
follow the reference's SSD-style sampling: random area/aspect patches
accepted only when min-IoU (or center-in-patch) constraints hold.
"""
from __future__ import annotations

import json
import random as pyrandom

import numpy as _np

from ..base import MXNetError
from . import image as _img

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Detection augmenter base (reference: ``DetAugmenter``): called as
    ``aug(src, label) -> (src, label)``."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap a classification :class:`~mxnet_tpu.image.Augmenter` that
    does not move pixels (color jitter, cast, normalize) so it can run in
    a detection pipeline (reference: ``DetBorrowAug``)."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, _img.Augmenter):
            raise MXNetError("DetBorrowAug needs an image.Augmenter")
        super().__init__(augmenter=type(augmenter).__name__)
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly select one augmenter from a list (or skip entirely with
    ``1 - skip_prob`` … reference: ``DetRandomSelectAug``)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image and x-coordinates with probability p
    (reference: ``DetHorizontalFlipAug``)."""

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = src[:, ::-1, :]
            label = label.copy()
            valid = label[:, 0] >= 0
            xmin = 1.0 - label[valid, 3]
            xmax = 1.0 - label[valid, 1]
            label[valid, 1], label[valid, 3] = xmin, xmax
        return src, label


class DetRandomCropAug(DetAugmenter):
    """SSD-style random crop with IoU constraint
    (reference: ``DetRandomCropAug``): sample a patch of relative area in
    ``area_range`` and aspect in ``aspect_ratio_range``; accept when every
    kept object's IoU with the patch ≥ ``min_object_covered``.  Objects
    whose centers fall outside the patch are dropped (id set to -1)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75,
                 1.33), area_range=(0.05, 1.0), max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def _sample_patch(self, label):
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range)
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            w = min(1.0, (area * ratio) ** 0.5)
            h = min(1.0, (area / ratio) ** 0.5)
            x0 = pyrandom.uniform(0, 1 - w)
            y0 = pyrandom.uniform(0, 1 - h)
            patch = _np.array([x0, y0, x0 + w, y0 + h], _np.float32)
            valid = label[:, 0] >= 0
            if not valid.any():
                return patch
            # sample_distorted_bounding_box semantics: accept when the
            # patch contains >= min_object_covered of SOME object's area
            # (intersection / box area, not symmetric IoU)
            boxes = label[valid, 1:5]
            ix = (_np.minimum(boxes[:, 2], patch[2])
                  - _np.maximum(boxes[:, 0], patch[0])).clip(min=0)
            iy = (_np.minimum(boxes[:, 3], patch[3])
                  - _np.maximum(boxes[:, 1], patch[1])).clip(min=0)
            box_area = ((boxes[:, 2] - boxes[:, 0])
                        * (boxes[:, 3] - boxes[:, 1])).clip(min=1e-12)
            coverage = ix * iy / box_area
            if (coverage >= self.min_object_covered).any():
                return patch
        return None

    def __call__(self, src, label):
        patch = self._sample_patch(label)
        if patch is None:
            return src, label
        H, W = src.shape[:2]
        x0, y0, x1, y1 = patch
        px0, py0 = int(x0 * W), int(y0 * H)
        pw, ph = max(1, int((x1 - x0) * W)), max(1, int((y1 - y0) * H))
        src = _img.fixed_crop(src, px0, py0, pw, ph)
        out = label.copy()
        valid = out[:, 0] >= 0
        b = out[valid, 1:5]
        cx = (b[:, 0] + b[:, 2]) / 2
        cy = (b[:, 1] + b[:, 3]) / 2
        inside = ((cx >= x0) & (cx <= x1) & (cy >= y0) & (cy <= y1))
        # re-express surviving boxes in patch coordinates
        b[:, [0, 2]] = ((b[:, [0, 2]] - x0) / (x1 - x0)).clip(0, 1)
        b[:, [1, 3]] = ((b[:, [1, 3]] - y0) / (y1 - y0)).clip(0, 1)
        out[valid, 1:5] = b
        ids = out[valid, 0]
        ids[~inside] = -1
        out[valid, 0] = ids
        return src, out


class DetRandomPadAug(DetAugmenter):
    """Expand the canvas by a random factor, filling with ``fill``
    (reference: ``DetRandomPadAug``) — the zoom-out augmentation."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.pad_val = pad_val

    def __call__(self, src, label):
        H, W = src.shape[:2]
        scale = pyrandom.uniform(*self.area_range)
        if scale <= 1.0:
            return src, label
        ratio = pyrandom.uniform(*self.aspect_ratio_range)
        nw = min(int(W * (scale * ratio) ** 0.5), int(W * scale))
        nh = min(int(H * (scale / ratio) ** 0.5), int(H * scale))
        nw, nh = max(nw, W), max(nh, H)
        ox = pyrandom.randint(0, nw - W)
        oy = pyrandom.randint(0, nh - H)
        arr = src.asnumpy() if hasattr(src, "asnumpy") else _np.asarray(src)
        canvas = _np.empty((nh, nw, arr.shape[2]), dtype=arr.dtype)
        canvas[...] = _np.asarray(self.pad_val, dtype=arr.dtype)
        canvas[oy:oy + H, ox:ox + W] = arr
        out = label.copy()
        valid = out[:, 0] >= 0
        out[valid, 1] = (out[valid, 1] * W + ox) / nw
        out[valid, 3] = (out[valid, 3] * W + ox) / nw
        out[valid, 2] = (out[valid, 2] * H + oy) / nh
        out[valid, 4] = (out[valid, 4] * H + oy) / nh
        from ..ndarray import array as nd_array
        return nd_array(canvas), out


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None,
                       std=None, brightness=0, contrast=0, saturation=0,
                       pca_noise=0, hue=0, inter_method=2,
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Standard detection augmenter list
    (reference: ``CreateDetAugmenter``)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(_img.ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])),
                                max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (1.0, max(1.0, area_range[1])),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    color = []
    if brightness or contrast or saturation:
        color.append(_img.ColorJitterAug(brightness, contrast, saturation))
    if hue:
        color.append(_img.HueJitterAug(hue))
    if pca_noise > 0:
        color.append(_img.LightingAug(
            pca_noise,
            _np.asarray([55.46, 4.794, 1.148]),
            _np.asarray([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]])))
    for c in color:
        auglist.append(DetBorrowAug(c))
    auglist.append(DetBorrowAug(_img.ForceResizeAug(
        (data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(_img.CastAug()))
    if mean is not None or std is not None:
        if mean is True:
            mean = _np.asarray([123.68, 116.28, 103.53])
        if std is True:
            std = _np.asarray([58.395, 57.12, 57.375])
        auglist.append(DetBorrowAug(_img.ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(_img.ImageIter):
    """Detection iterator (reference: ``ImageDetIter``): like
    ``ImageIter`` but labels are per-image (N, 5+) box lists padded to
    the batch's max object count with -1 rows, emitted as a
    (batch, max_objects, label_width) array."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", path_imgidx=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, object_width=5, max_objects=None,
                 data_name="data", label_name="label",
                 last_batch_handle="pad", **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape)
        self.object_width = object_width
        self._max_objects = max_objects  # resolved after super().__init__
        self._explicit_max = max_objects is not None
        self._overflow_warned = False
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec,
                         path_imglist=path_imglist, path_root=path_root,
                         path_imgidx=path_imgidx, shuffle=shuffle,
                         part_index=part_index, num_parts=num_parts,
                         aug_list=[], imglist=imglist,
                         data_name=data_name, label_name=label_name,
                         last_batch_handle=last_batch_handle, **kwargs)
        self.det_aug_list = aug_list
        if self._max_objects is None:
            self._max_objects = self._estimate_max_objects()

    def _estimate_max_objects(self, sample=256):
        """Scan up to ``sample`` labels for the dataset's max object
        count, so batches pad to ONE static shape (reference estimates
        the label shape up front; static shapes keep the consumer
        jit-cache warm).  Under-estimates are never lossy — ``next()``
        grows the pad size when a batch exceeds it."""
        from .. import recordio
        best = 1
        if self.imglist is not None:
            for k in list(self.imglist)[:sample]:
                best = max(best,
                           self._parse_label(self.imglist[k][0]).shape[0])
        elif self.imgrec is not None and self.seq is not None:
            for k in self.seq[:sample]:
                hdr, _ = recordio.unpack(self.imgrec.read_idx(k))
                best = max(best, self._parse_label(hdr.label).shape[0])
        elif self.imgrec is not None:
            for _ in range(sample):
                s = self.imgrec.read()
                if s is None:
                    break
                hdr, _ = recordio.unpack(s)
                best = max(best, self._parse_label(hdr.label).shape[0])
            self.imgrec.reset()
        return best

    @property
    def provide_label(self):
        from .. import io as mxio
        return [mxio.DataDesc(self.label_name,
                              (self.batch_size, self._max_objects,
                               self.object_width))]

    def _parse_label(self, label):
        """Flat label vector → (N, w) box array (reference:
        ``ImageDetIter._parse_label``: header ``[A, w, extras...,
        objects...]`` where A = header length, w = per-object width;
        plain ``N*object_width`` vectors are accepted too)."""
        raw = _np.asarray(label, dtype=_np.float32).ravel()
        if raw.size >= 2:
            a, w = int(raw[0]), int(raw[1])
            if (raw[0] == a and raw[1] == w and a >= 2 and w >= 5
                    and raw.size > a and (raw.size - a) % w == 0):
                return raw[a:].reshape(-1, w)
        w = self.object_width
        n = raw.size // w
        if n == 0:
            raise MXNetError("label too short for object_width=%d" % w)
        return raw[:n * w].reshape(n, w)

    def next(self):
        from .. import io as mxio
        from ..ndarray import array as nd_array
        samples = []
        try:
            while len(samples) < self.batch_size:
                label, s = self.next_sample()
                img = _img.imdecode(s)
                boxes = self._parse_label(label)
                for aug in self.det_aug_list:
                    img, boxes = aug(img, boxes)
                samples.append((img, boxes))
        except StopIteration:
            if not samples:
                raise
        pad = self.batch_size - len(samples)
        if pad and self.last_batch_handle == "discard":
            raise StopIteration
        while len(samples) < self.batch_size:
            samples.append(samples[-1])
        # batches pad to one static (B, max_objects, w) shape.  An
        # ESTIMATED pad size grows on under-estimate (shape changes,
        # one-time warning) rather than dropping ground truth; an
        # EXPLICIT max_objects= is a shape contract the consumer bound
        # to, so overflow there clamps with a warning instead.
        batch_max = max(s[1].shape[0] for s in samples)
        if batch_max > self._max_objects:
            import logging
            log = logging.getLogger("mxnet_tpu")
            if self._explicit_max:
                if not self._overflow_warned:
                    log.warning(
                        "ImageDetIter: batch holds %d objects > "
                        "max_objects=%d; extra objects are dropped "
                        "(raise max_objects=)", batch_max,
                        self._max_objects)
                    self._overflow_warned = True
            else:
                if not self._overflow_warned:
                    log.warning(
                        "ImageDetIter: batch holds %d objects > "
                        "estimated max_objects=%d; growing the label "
                        "pad (pass max_objects= to fix the shape up "
                        "front)", batch_max, self._max_objects)
                    self._overflow_warned = True
                self._max_objects = batch_max
        max_obj = self._max_objects
        w = samples[0][1].shape[1]
        lab = _np.full((self.batch_size, max_obj, w), -1.0, _np.float32)
        dat = _np.stack([_np.transpose(
            s[0].asnumpy() if hasattr(s[0], "asnumpy")
            else _np.asarray(s[0]), (2, 0, 1)) for s in samples])
        for i, (_, b) in enumerate(samples):
            n = min(b.shape[0], max_obj)
            lab[i, :n] = b[:n]
        return mxio.DataBatch(data=[nd_array(dat)],
                              label=[nd_array(lab)], pad=pad)
