"""Image API (reference: ``python/mxnet/image/``)."""
from .image import *
