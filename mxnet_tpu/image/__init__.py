"""Image API (reference: ``python/mxnet/image/``)."""
from .image import *
from .detection import *
from . import detection
