"""Image decode / augmentation / ImageIter.

Reference: ``python/mxnet/image/image.py`` + the C++ augmenters in
``src/io/image_aug_default.cc`` (SURVEY.md §2.1 "Data IO", §3.5).
Decode/augment runs on host via cv2 (the reference used OpenCV too); the
augmented batch lands on device once per batch — one transfer, TPU-friendly.
"""
from __future__ import annotations

import os
import random as pyrandom
from typing import List, Optional

import numpy as _np

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray
from .. import io as mxio
from .. import recordio

__all__ = ["imdecode", "imread", "imresize", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "random_size_crop", "color_normalize",
           "HorizontalFlipAug", "CastAug", "ColorNormalizeAug",
           "RandomCropAug", "CenterCropAug", "ResizeAug", "ForceResizeAug",
           "SequentialAug", "RandomOrderAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "HueJitterAug",
           "ColorJitterAug", "LightingAug", "RandomSizedCropAug",
           "CreateAugmenter", "Augmenter", "ImageIter"]


def _cv2():
    import cv2
    return cv2


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an image byte buffer to an NDArray (HWC, RGB by default).
    Reference: ``mx.image.imdecode``."""
    cv2 = _cv2()
    if isinstance(buf, (bytes, bytearray)):
        buf = _np.frombuffer(buf, dtype=_np.uint8)
    img = cv2.imdecode(buf, flag)
    if img is None:
        raise MXNetError("Decoding failed; invalid image data")
    if to_rgb and img.ndim == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return nd.array(img, dtype="uint8")


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    cv2 = _cv2()
    img = src.asnumpy() if isinstance(src, NDArray) else src
    out = cv2.resize(img, (w, h), interpolation=_interp(interp))
    return nd.array(out, dtype=str(out.dtype))


def _interp(interp):
    cv2 = _cv2()
    table = {0: cv2.INTER_NEAREST, 1: cv2.INTER_LINEAR,
             2: cv2.INTER_CUBIC, 3: cv2.INTER_AREA,
             4: cv2.INTER_LANCZOS4}
    if interp == 9:  # auto
        return cv2.INTER_LINEAR
    if interp == 10:
        return pyrandom.choice(list(table.values()))
    return table.get(interp, cv2.INTER_LINEAR)


def resize_short(src, size, interp=2):
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        new_ratio = _np.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round(_np.sqrt(target_area * new_ratio)))
        new_h = int(round(_np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    if mean is not None:
        src = src - mean
    if std is not None:
        src = src / std
    return src


class Augmenter:
    """Image augmenter base (reference: ``mx.image.Augmenter``)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return src.flip(axis=1)
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return src * alpha


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast
        self.coef = nd.array([[[0.299, 0.587, 0.114]]])

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        gray = src * self.coef
        gray = (3.0 * (1.0 - alpha) / gray.size) * nd.sum(gray)
        return src * alpha + gray


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation
        self.coef = nd.array([[[0.299, 0.587, 0.114]]])

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        gray = src * self.coef
        gray = nd.sum(gray, axis=2, keepdims=True)
        return src * alpha + gray * (1.0 - alpha)


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = _np.array([[0.299, 0.587, 0.114],
                               [0.596, -0.274, -0.321],
                               [0.211, -0.523, 0.311]])
        self.ityiq = _np.array([[1.0, 0.956, 0.621],
                                [1.0, -0.272, -0.647],
                                [1.0, -1.107, 1.705]])

    def __call__(self, src):
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u = _np.cos(alpha * _np.pi)
        w = _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]])
        t = _np.dot(_np.dot(self.ityiq, bt), self.tyiq).T
        return nd.dot(src, nd.array(t))


class ColorJitterAug(Augmenter):
    def __init__(self, brightness, contrast, saturation):
        super().__init__(brightness=brightness, contrast=contrast,
                         saturation=saturation)
        self.augs = []
        if brightness > 0:
            self.augs.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            self.augs.append(ContrastJitterAug(contrast))
        if saturation > 0:
            self.augs.append(SaturationJitterAug(saturation))

    def __call__(self, src):
        augs = list(self.augs)
        pyrandom.shuffle(augs)
        for aug in augs:
            src = aug(src)
        return src


class LightingAug(Augmenter):
    """AlexNet-style PCA lighting noise."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval)
        self.eigvec = _np.asarray(eigvec)

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,))
        rgb = _np.dot(self.eigvec * alpha, self.eigval)
        return src + nd.array(rgb)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = nd.array(mean) if mean is not None else None
        self.std = nd.array(std) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for aug in self.ts:
            src = aug(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (reference:
    ``mx.image.CreateAugmenter`` — same knobs as ``ImageRecordIter``)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None and _np.any(_np.asarray(mean) > 0):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(mxio.DataIter):
    """Image iterator over .rec files or .lst+folder (reference:
    ``mx.image.ImageIter``); per-worker sharding via
    ``part_index/num_parts`` like the C++ ``ImageRecordIter``."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 path_imgidx=None, shuffle=False, part_index=0,
                 num_parts=1, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label",
                 last_batch_handle="pad", **kwargs):
        super().__init__(batch_size)
        assert path_imgrec or path_imglist or isinstance(imglist, list)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.path_root = path_root
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle

        self.imgrec = None
        self.imglist = None
        self.seq = None
        if path_imgrec:
            idx_path = path_imgidx or (path_imgrec[:-4] + ".idx")
            if os.path.exists(idx_path):
                self.imgrec = recordio.MXIndexedRecordIO(
                    idx_path, path_imgrec, "r")
                self.seq = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
                self.seq = None
        elif path_imglist:
            self.imglist = {}
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    label = _np.asarray(parts[1:-1], dtype=_np.float32)
                    self.imglist[int(parts[0])] = (label, parts[-1])
            self.seq = list(self.imglist.keys())
        elif imglist is not None:
            self.imglist = {}
            for i, rec in enumerate(imglist):
                self.imglist[i] = (_np.asarray(rec[0],
                                               dtype=_np.float32).reshape(-1),
                                   rec[1])
            self.seq = list(self.imglist.keys())

        if self.seq is not None and num_parts > 1:
            per = len(self.seq) // num_parts
            self.seq = self.seq[part_index * per:(part_index + 1) * per]

        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list
        self.cur = 0
        self._cache = None
        self.data_name = data_name
        self.label_name = label_name
        self.reset()

    @property
    def provide_data(self):
        return [mxio.DataDesc(self.data_name,
                              (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [mxio.DataDesc(self.label_name, shape)]

    def reset(self):
        if self.shuffle and self.seq is not None:
            _np.random.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(s)
                return header.label, img
            label, fname = self.imglist[idx]
            with open(os.path.join(self.path_root, fname), "rb") as f:
                return label, f.read()
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = recordio.unpack(s)
        return header.label, img

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = _np.zeros((batch_size, h, w, c), dtype=_np.float32)
        batch_label = _np.zeros((batch_size, self.label_width),
                                dtype=_np.float32)
        i = 0
        try:
            while i < batch_size:
                label, s = self.next_sample()
                img = imdecode(s)
                for aug in self.auglist:
                    img = aug(img)
                batch_data[i] = img.asnumpy()
                batch_label[i] = label
                i += 1
        except StopIteration:
            if i == 0:
                raise
        pad = batch_size - i
        if pad and self.last_batch_handle == "discard":
            raise StopIteration
        data = nd.array(batch_data.transpose(0, 3, 1, 2))
        label = nd.array(batch_label.reshape(-1)
                         if self.label_width == 1 else batch_label)
        return mxio.DataBatch(data=[data], label=[label], pad=pad)


@mxio.register_iter("ImageRecordIter")
def _image_record_iter(**kwargs):
    """ImageRecordIter parity entry (reference C++:
    ``src/io/iter_image_recordio_2.cc``) — maps the C++ iterator kwargs to
    ImageIter + background prefetch."""
    batch_size = kwargs.pop("batch_size")
    data_shape = kwargs.pop("data_shape")
    mapped = dict(
        path_imgrec=kwargs.pop("path_imgrec", None),
        path_imgidx=kwargs.pop("path_imgidx", None),
        shuffle=kwargs.pop("shuffle", False),
        part_index=kwargs.pop("part_index", 0),
        num_parts=kwargs.pop("num_parts", 1),
        rand_crop=kwargs.pop("rand_crop", False),
        rand_mirror=kwargs.pop("rand_mirror", False),
        label_width=kwargs.pop("label_width", 1),
    )
    mean = None
    if all(k in kwargs for k in ("mean_r", "mean_g", "mean_b")):
        mean = _np.array([kwargs.pop("mean_r"), kwargs.pop("mean_g"),
                          kwargs.pop("mean_b")])
    std = None
    if all(k in kwargs for k in ("std_r", "std_g", "std_b")):
        std = _np.array([kwargs.pop("std_r"), kwargs.pop("std_g"),
                         kwargs.pop("std_b")])
    it = ImageIter(batch_size, data_shape, mean=mean, std=std, **mapped)
    if kwargs.pop("prefetch", True):
        return mxio.PrefetchingIter(it)
    return it
