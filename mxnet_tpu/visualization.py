"""Network visualization (reference: ``python/mxnet/visualization.py``).

``print_summary`` renders the layer table with parameter counts;
``plot_network`` emits a graphviz digraph when the optional ``graphviz``
package is installed (gated import — not baked into this image)."""
from __future__ import annotations

import json
from typing import Dict, Optional, Tuple

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def _node_shape_map(symbol, shape=None):
    """Output shape per node name via infer_shape (best effort)."""
    if not shape:
        return {}
    try:
        internals = symbol.get_internals()
        _, out_shapes, _ = internals.infer_shape(**shape)
        return dict(zip(internals.list_outputs(), out_shapes))
    except Exception:
        return {}


def print_summary(symbol, shape: Optional[Dict[str, Tuple]] = None,
                  line_length: int = 120, positions=(.44, .64, .74, 1.)):
    """Print a Keras-style layer summary table (reference:
    ``visualization.print_summary``): layer name/type, output shape,
    parameter count, previous layers; totals at the bottom."""
    shape_map = _node_shape_map(symbol, shape)
    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(cols):
        line = ""
        for i, c in enumerate(cols):
            line += str(c)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(fields)
    print("=" * line_length)

    # parameter sizes: arg shapes from infer_shape, minus the data args
    # the caller provided in ``shape``
    arg_sizes = {}
    if shape:
        try:
            arg_shapes, _, aux_shapes = symbol.infer_shape(**shape)
            names = symbol.list_arguments()
            sizes = {n: s for n, s in zip(names, arg_shapes)}
            sizes.update(zip(symbol.list_auxiliary_states(), aux_shapes))
            for n, s in sizes.items():
                # data args come from the caller; auto-created label
                # variables are inputs, not parameters
                if n not in shape and s and not n.endswith("_label"):
                    p = 1
                    for d in s:
                        p *= int(d)
                    arg_sizes[n] = p
        except Exception:
            pass

    total = 0
    for node in symbol._nodes():
        if node.is_var:
            continue
        n_params = 0
        prevs = []
        for inp, _ in node.inputs:
            if inp.is_var:
                n_params += arg_sizes.get(inp.name, 0)
            else:
                prevs.append(inp.name)
        total += n_params
        out_shape = shape_map.get(node.name + "_output", "")
        print_row(["%s (%s)" % (node.name, node.op.name),
                   str(out_shape), n_params, ",".join(prevs)])
        print("_" * line_length)
    print("Total params: %d" % total)
    print("_" * line_length)
    return total


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 dtype=None, node_attrs=None, hide_weights=True):
    """Build a ``graphviz.Digraph`` of the symbol graph (reference:
    ``visualization.plot_network``).  Requires the optional ``graphviz``
    package; raises a clear error when it is unavailable."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise MXNetError(
            "plot_network requires the optional 'graphviz' package, "
            "which is not installed in this environment; use "
            "print_summary for a text rendering") from e

    node_attrs = dict(node_attrs or {})
    node_attr = {"shape": "box", "fixedsize": "false", "style": "filled"}
    node_attr.update(node_attrs)
    dot = Digraph(name=title, format=save_format)

    fill = {"Convolution": "#fb8072", "FullyConnected": "#fb8072",
            "BatchNorm": "#bebada", "Activation": "#ffffb3",
            "Pooling": "#80b1d3", "Concat": "#fdb462",
            "Softmax": "#fccde5", "SoftmaxOutput": "#fccde5"}

    def is_weight(n):
        return n.is_var and (n.name.endswith("_weight")
                             or n.name.endswith("_bias")
                             or n.name.endswith("_gamma")
                             or n.name.endswith("_beta")
                             or n.name.endswith("_moving_mean")
                             or n.name.endswith("_moving_var"))

    nodes = symbol._nodes()
    for n in nodes:
        if hide_weights and is_weight(n):
            continue
        if n.is_var:
            dot.node(n.name, n.name, **dict(node_attr,
                                            fillcolor="#8dd3c7"))
        else:
            color = fill.get(n.op.name, "#b3de69")
            dot.node(n.name, "%s\n%s" % (n.name, n.op.name),
                     **dict(node_attr, fillcolor=color))
    for n in nodes:
        if n.is_var:
            continue
        for inp, _ in n.inputs:
            if hide_weights and is_weight(inp):
                continue
            dot.edge(inp.name, n.name)
    return dot
