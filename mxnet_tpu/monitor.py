"""Monitor — per-layer output statistics for training debugging.

Reference: ``python/mxnet/monitor.py`` (SURVEY.md §5.5): collects stats
(e.g. abs-mean) of layer outputs/weights/gradients matching a regex every
``interval`` batches.

TPU-native caveat: inside a compiled executor XLA fuses intermediate ops
away, so per-internal-op observation would force a debug recompile.  The
Monitor therefore reports the observable arrays — bound arguments,
gradients, auxiliary states and outputs — which covers the reference's
main uses (weight/grad/output health).  Gluon Blocks can register eager
forward hooks for internals when needed.
"""
from __future__ import annotations

import logging
import re
from typing import Callable, List, Optional, Tuple

from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["Monitor"]


def _default_stat(x):
    return nd.norm(x) / (x.size ** 0.5)


class Monitor:
    """``registry`` (round 8): pass an ``obs.MetricsRegistry`` to also
    publish each scalar stat as a ``monitor_<name>`` gauge at ``toc``
    time — the same telemetry surface the serving engine and
    ``callback.MetricsCallback`` feed, scraped by
    ``obs.prometheus_text()``."""

    def __init__(self, interval: int, stat_func: Optional[Callable] = None,
                 pattern: str = ".*", sort: bool = False, registry=None):
        self.interval = interval
        self.stat_func = stat_func or _default_stat
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self.queue: List[Tuple[int, str, NDArray]] = []
        self._execs = []
        self.registry = registry

    def install(self, exe):
        """Attach to an Executor (called by Module.install_monitor)."""
        self._execs.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self) -> List[Tuple[int, str, str]]:
        if not self.activated:
            return []
        self.activated = False
        for exe in self._execs:
            for name, arr in exe.arg_dict.items():
                if self.re_pattern.match(name):
                    self.queue.append((self.step, name, self.stat_func(arr)))
            for name, arr in exe.aux_dict.items():
                if self.re_pattern.match(name):
                    self.queue.append((self.step, name, self.stat_func(arr)))
            for name, arr in getattr(exe, "grad_dict", {}).items():
                gname = name + "_grad"
                if self.re_pattern.match(gname):
                    self.queue.append((self.step, gname,
                                       self.stat_func(arr)))
            for name, arr in zip(exe.output_names, exe.outputs):
                if self.re_pattern.match(name):
                    self.queue.append((self.step, name, self.stat_func(arr)))
        res = []
        queue = sorted(self.queue, key=lambda q: q[1]) if self.sort \
            else self.queue
        if self.registry is not None:
            from .obs import sanitize_name
        for n, k, v_arr in queue:
            scalar = None
            if isinstance(v_arr, NDArray):
                v = v_arr.asnumpy()
                s = str(v.reshape(-1)[0]) if v.size == 1 else str(v)
                if v.size == 1:
                    scalar = float(v.reshape(-1)[0])
            else:
                s = str(v_arr)
            if self.registry is not None and scalar is not None:
                self.registry.gauge(
                    "monitor_" + sanitize_name(k)).set(scalar)
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        for n, k, v in self.toc():
            logging.info("Batch: %7d %30s %s", n, k, v)
