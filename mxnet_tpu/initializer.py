"""Weight initializers.

Reference: ``python/mxnet/initializer.py`` (SURVEY.md §2.2 "Metrics & train
utils" row — Xavier, MSRAPrelu, Orthogonal, …).  Behavior preserved: an
``InitDesc``-named dispatch where ``*_bias``/``*_gamma``/``*_beta``/
``*_running_*`` attributes get their canonical defaults regardless of the
configured weight initializer.
"""
from __future__ import annotations

import math
import numpy as _np

from .base import Registry, MXNetError

__all__ = ["Initializer", "Uniform", "Normal", "Zero", "One", "Constant",
           "Xavier", "MSRAPrelu", "Orthogonal", "Bilinear", "LSTMBias",
           "Mixed", "InitDesc", "register", "create"]

_REG = Registry("initializer")
register = _REG.register


class InitDesc(str):
    """Name + attrs describing a parameter being initialized."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer; ``__call__(desc, arr)`` fills ``arr`` in place."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(str(desc))
        init = desc.attrs.get("__init__", "")
        if init:
            create(init)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        elif name.endswith("min") or name.endswith("max"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # -- fill helpers (write via buffer swap) ------------------------------
    @staticmethod
    def _set(arr, np_value):
        from .ndarray import array
        arr._set_data(array(np_value.astype(arr.dtype))._data)

    def _init_zero(self, desc, arr):
        self._set(arr, _np.zeros(arr.shape))

    def _init_one(self, desc, arr):
        self._set(arr, _np.ones(arr.shape))

    def _init_bias(self, desc, arr):
        self._init_zero(desc, arr)

    def _init_gamma(self, desc, arr):
        self._init_one(desc, arr)

    def _init_beta(self, desc, arr):
        self._init_zero(desc, arr)

    def _init_weight(self, desc, arr):
        raise NotImplementedError

    def _init_default(self, desc, arr):
        self._init_weight(desc, arr)

    def __repr__(self):
        return "%s(%s)" % (type(self).__name__, self._kwargs)


def _rng():
    from . import random as mxrand
    import numpy as np
    # derive a numpy RNG from the framework seed state for reproducibility
    return np.random


@register("uniform")
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, desc, arr):
        self._set(arr, _np.random.uniform(-self.scale, self.scale,
                                          arr.shape))


@register("normal")
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, desc, arr):
        self._set(arr, _np.random.normal(0, self.sigma, arr.shape))


@register("zeros", aliases=["zero"])
class Zero(Initializer):
    def _init_weight(self, desc, arr):
        self._init_zero(desc, arr)


@register("ones", aliases=["one"])
class One(Initializer):
    def _init_weight(self, desc, arr):
        self._init_one(desc, arr)


@register("constant")
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, desc, arr):
        self._set(arr, _np.full(arr.shape, self.value))


@register("xavier")
class Xavier(Initializer):
    """Xavier/Glorot initialization (reference defaults preserved)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError("Xavier requires ndim >= 2: %s %s"
                             % (desc, shape))
        if len(shape) > 2:
            hw_scale = _np.prod(shape[2:])
        fan_in = shape[1] * hw_scale
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError("Incorrect factor type")
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            self._set(arr, _np.random.uniform(-scale, scale, shape))
        elif self.rnd_type == "gaussian":
            self._set(arr, _np.random.normal(0, scale, shape))
        else:
            raise MXNetError("Unknown random type")


@register("msraprelu")
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register("orthogonal")
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, desc, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        self._set(arr, (self.scale * q).reshape(arr.shape))


@register("bilinear")
class Bilinear(Initializer):
    def _init_weight(self, desc, arr):
        weight = _np.zeros(arr.shape).reshape(-1)
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(_np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight.reshape(shape))


@register("lstmbias")
class LSTMBias(Initializer):
    """Forget-gate bias = 1, others 0 (cuDNN gate order [i,f,c,o])."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        b = _np.zeros(arr.shape)
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias
        self._set(arr, b)

    def _init_bias(self, desc, arr):
        self._init_weight(desc, arr)


class Mixed:
    """Patterned dispatch over multiple initializers."""

    def __init__(self, patterns, initializers):
        import re
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers mismatch")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(str(name)):
                init(name, arr)
                return
        raise MXNetError("Parameter %s did not match any pattern" % name)


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    return _REG.create(name, **kwargs)
