"""``mx.operator`` — user-defined operators in Python (CustomOp).

Reference: ``python/mxnet/operator.py`` + ``src/operator/custom/custom.cc``
(SURVEY.md §2.1 "Operator library" row, ``custom/custom.cc``): users
subclass ``CustomOpProp`` (declares arguments/outputs/shape inference and
creates the runtime op) and ``CustomOp`` (imperative ``forward`` /
``backward`` writing results through ``assign``), register the prop under
a name, and call ``nd.Custom(..., op_type=name)`` / ``sym.Custom(...)``.

TPU-native design: the user's ``forward``/``backward`` receive NDArrays
and compute with ``mx.nd`` ops, so a CustomOp is *traceable* — under
``hybridize()``/``jit`` it lowers into the surrounding XLA program
instead of breaking the graph the way the reference's C++ custom-op
bridge breaks engine bulking.  The custom ``backward`` is honored by
wrapping the registry impl in ``jax.custom_vjp`` (the reference routes
this through the nnvm ``FGradient`` of the Custom node)."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Type

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register",
           "get_all_registered_operators"]

_PROPS: Dict[str, Type["CustomOpProp"]] = {}


class CustomOp:
    """Base class for the runtime operator (reference:
    ``mx.operator.CustomOp``)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write ``src`` into ``dst`` honoring the write request."""
        if req == "null":
            return
        if req == "add":
            dst[:] = dst + src
        else:  # write / inplace
            dst[:] = src


class CustomOpProp:
    """Operator properties: names, shapes, types, and op creation
    (reference: ``mx.operator.CustomOpProp``)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError


def register(reg_name: str):
    """Class decorator registering a ``CustomOpProp`` under ``reg_name``
    (reference: ``mx.operator.register``)."""
    def _wrap(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register(%r): expected a CustomOpProp "
                             "subclass" % reg_name)
        _PROPS[reg_name] = prop_cls
        return prop_cls
    return _wrap


def get_all_registered_operators() -> List[str]:
    return sorted(_PROPS)


def _get_prop(op_type: str, attrs) -> CustomOpProp:
    if op_type not in _PROPS:
        raise MXNetError(
            "Custom: op_type %r is not registered (have: %s)"
            % (op_type, ", ".join(sorted(_PROPS)) or "<none>"))
    return _PROPS[op_type](**attrs)


def _custom_impl(*arrays, op_type=None, **attrs):
    """Registry impl behind ``nd.Custom`` / ``sym.Custom``."""
    import jax
    from .ndarray.ndarray import NDArray
    from . import autograd

    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    prop = _get_prop(op_type, attrs)
    n_args = len(prop.list_arguments())
    n_out = len(prop.list_outputs())
    n_aux = len(prop.list_auxiliary_states())
    if len(arrays) != n_args + n_aux:
        raise MXNetError(
            "Custom(%s): expected %d arguments + %d aux states, got %d "
            "inputs" % (op_type, n_args, n_aux, len(arrays)))

    in_shapes = [tuple(a.shape) for a in arrays[:n_args]]
    _, out_shapes, _ = prop.infer_shape([list(s) for s in in_shapes])
    in_types = [a.dtype for a in arrays[:n_args]]
    _, out_types, _ = prop.infer_type(list(in_types))
    op = prop.create_operator(None, in_shapes, in_types)

    def _run_forward(raw):
        from . import nd
        in_nd = [NDArray(a) for a in raw[:n_args]]
        aux_nd = [NDArray(a) for a in raw[n_args:]]
        out_nd = [nd.zeros(s, dtype=str(jax.numpy.dtype(t)))
                  for s, t in zip(out_shapes, out_types)]
        with autograd.pause():
            op.forward(is_train=autograd.is_training(),
                       req=["write"] * n_out, in_data=in_nd,
                       out_data=out_nd, aux=aux_nd)
        return tuple(o._data for o in out_nd)

    @jax.custom_vjp
    def fn(*raw):
        outs = _run_forward(raw)
        return outs[0] if n_out == 1 else outs

    def fwd(*raw):
        outs = _run_forward(raw)
        return (outs[0] if n_out == 1 else outs), (raw, outs)

    def bwd(res, gs):
        raw, outs = res
        gs = (gs,) if n_out == 1 else tuple(gs)
        in_nd = [NDArray(a) for a in raw[:n_args]]
        aux_nd = [NDArray(a) for a in raw[n_args:]]
        out_nd = [NDArray(o) for o in outs]
        grad_nd = [NDArray(g) for g in gs]
        from . import nd
        in_grad = [nd.zeros(x.shape, dtype=str(x.dtype)) for x in in_nd]
        with autograd.pause():
            op.backward(req=["write"] * n_args, out_grad=grad_nd,
                        in_data=in_nd, out_data=out_nd,
                        in_grad=in_grad, aux=aux_nd)
        zero_aux = tuple(jax.numpy.zeros_like(a) for a in raw[n_args:])
        return tuple(g._data for g in in_grad) + zero_aux

    fn.defvjp(fwd, bwd)
    return fn(*arrays)


def _register_custom_op():
    from .ops.registry import register as _reg

    @_reg("Custom", num_outputs=-1)
    def Custom(*arrays, op_type=None, **attrs):  # noqa: N802
        """User-defined Python operator (reference:
        ``src/operator/custom/custom.cc``).  See ``mx.operator``."""
        return _custom_impl(*arrays, op_type=op_type, **attrs)


_register_custom_op()
