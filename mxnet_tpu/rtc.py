"""Runtime kernel compilation — user Pallas kernels from Python.

Reference: ``python/mxnet/rtc.py`` + ``src/common/rtc.cc`` (SURVEY.md §2.1
"Init/runtime misc": user CUDA kernels compiled with NVRTC at runtime and
launched from Python as ``CudaModule``/``CudaKernel``).

The TPU analog compiles **Pallas** kernels instead of CUDA: the source
string defines kernel functions against ``pl.BlockSpec``-style refs; the
module evaluates it with jax/jnp/pallas in scope and wraps each exported
function in ``pl.pallas_call`` at launch time.  Like the reference, this
is the escape hatch for hand-written kernels without rebuilding the
framework — and the same object also accepts an already-imported Python
function, for kernels defined inline.

Example::

    mod = rtc.PallasModule(r'''
    def scale(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0
    ''', exports=["scale"])
    k = mod.get_kernel("scale")
    y = k(x)                       # same shape/dtype out
"""
from __future__ import annotations

from typing import Optional, Sequence

from .base import MXNetError

__all__ = ["PallasModule", "PallasKernel"]


class PallasKernel:
    """A launchable kernel (reference: ``CudaKernel.launch``).

    Calling it runs ``pl.pallas_call`` with out_shape defaulting to the
    first input's shape/dtype; pass ``out_shape=(shape, dtype)`` to
    override, and ``grid``/``interpret`` for tiled launches and CPU
    debugging.  Inputs/outputs are jax arrays or mxnet_tpu NDArrays.
    """

    def __init__(self, fn, name):
        self._fn = fn
        self.name = name

    def __call__(self, *inputs, out_shape=None, grid=None,
                 interpret=None, **pallas_kw):
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from .ndarray import NDArray

        unwrapped = []
        want_nd = False
        for a in inputs:
            if isinstance(a, NDArray):
                want_nd = True
                unwrapped.append(a._data)
            else:
                unwrapped.append(jnp.asarray(a))
        if out_shape is None:
            ref = unwrapped[0]
            out = jax.ShapeDtypeStruct(ref.shape, ref.dtype)
        else:
            shape, dtype = out_shape
            out = jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))
        if interpret is None:
            # interpret mode keeps kernels runnable on CPU (tests /
            # debugging); on TPU run the compiled path.
            interpret = jax.default_backend() != "tpu"
        kw = dict(out_shape=out, interpret=interpret, **pallas_kw)
        if grid is not None:
            kw["grid"] = grid
        result = pl.pallas_call(self._fn, **kw)(*unwrapped)
        if want_nd:
            from . import ndarray as nd
            return nd.array(result)
        return result


class PallasModule:
    """Compile a source string of Pallas kernels
    (reference: ``CudaModule``).

    ``source`` is Python executed with ``jax``, ``jnp``, ``pl`` (pallas)
    pre-imported; ``exports`` names the kernel functions to expose.
    """

    def __init__(self, source: str, options=(),
                 exports: Sequence[str] = ()):
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        self._namespace = {"jax": jax, "jnp": jnp, "pl": pl}
        try:
            exec(compile(source, "<rtc.PallasModule>", "exec"),
                 self._namespace)
        except Exception as e:
            raise MXNetError("rtc source failed to compile: %s" % e)
        self._exports = list(exports) or [
            k for k, v in self._namespace.items()
            if callable(v) and not k.startswith("_")
            and k not in ("jax", "jnp", "pl")]
        for name in self._exports:
            if name not in self._namespace:
                raise MXNetError("export %r not defined in rtc source"
                                 % name)

    def get_kernel(self, name: str, signature: Optional[str] = None):
        """Kernel by name.  ``signature`` is accepted for reference-API
        compatibility and ignored (shapes/dtypes are traced, not
        declared)."""
        if name not in self._exports:
            raise MXNetError("unknown kernel %r; exports: %s"
                             % (name, self._exports))
        return PallasKernel(self._namespace[name], name)
