"""Autograd — imperative differentiation on the XLA substrate.

Reference: ``python/mxnet/autograd.py`` + ``src/imperative/imperative.cc``
(``Imperative::Record/Backward``, per-op ``FGradient``, ``AGInfo`` —
SURVEY.md §2.1 "Imperative runtime + autograd", §3.2).

TPU-native design: the reference hand-writes a gradient function per op and
builds a backward nnvm graph.  Here the tape records, per executed op, its
(pure JAX) impl plus the concrete input buffers; ``backward()`` replays the
recorded subgraph as a *pure function of the requested variables* and calls
``jax.vjp`` on it once.  Consequences:

* every op's gradient comes from JAX AD — no per-op FGradient to maintain;
* ``create_graph=True`` (higher-order grad, reference
  ``test_autograd.py`` higher-order tests) nests naturally;
* randomness replays exactly because RNG keys are recorded as tape
  constants (random ops take their key as an explicit input);
* the whole backward is one traceable function — it can be jitted.

Semantics preserved from the reference: ``record``/``pause`` context
managers with ``train_mode``/``predict_mode`` variants, ``mark_variables``,
``grad_req`` write/add/null, ``retain_graph``, ``head_grads``, and
``backward`` accumulating into ``NDArray.grad`` buffers.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad", "Function",
           "set_recording", "set_training"]


class _AGState(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False


_STATE = _AGState()


def is_recording() -> bool:
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


def set_recording(flag: bool) -> bool:
    old = _STATE.recording
    _STATE.recording = flag
    return old


def set_training(flag: bool) -> bool:
    old = _STATE.training
    _STATE.training = flag
    return old


@contextlib.contextmanager
def _scope(recording: Optional[bool], training: Optional[bool]):
    old_r = _STATE.recording
    old_t = _STATE.training
    if recording is not None:
        _STATE.recording = recording
    if training is not None:
        _STATE.training = training
    try:
        yield
    finally:
        _STATE.recording = old_r
        _STATE.training = old_t


def record(train_mode: bool = True):
    """Scope in which executed ops are recorded for differentiation."""
    return _scope(True, train_mode)


def pause(train_mode: bool = False):
    """Scope in which recording is suspended."""
    return _scope(False, train_mode)


def train_mode():
    return _scope(None, True)


def predict_mode():
    return _scope(None, False)


# ---------------------------------------------------------------------------
# Tape structure
# ---------------------------------------------------------------------------

class _Node:
    """One recorded op application.

    ``inputs`` entries are either ``("n", node, out_idx)`` — produced by an
    earlier node — or ``("c", jax_array)`` — a tape constant (leaf value or
    non-grad input).  Leaves are represented by :class:`_Leaf` nodes.
    """

    __slots__ = ("op", "pos_attrs", "attrs", "inputs", "n_out", "__weakref__")

    def __init__(self, op, pos_attrs, attrs, inputs, n_out):
        self.op = op
        self.pos_attrs = pos_attrs
        self.attrs = attrs
        self.inputs = inputs
        self.n_out = n_out


class _Leaf:
    """A variable (``attach_grad``-ed NDArray).

    ``value`` snapshots the buffer at record time so that a mutation of the
    variable between ``record()`` and ``backward()`` does not change the
    gradient (reference engine-var versioning semantics)."""

    __slots__ = ("array_ref", "value", "__weakref__")

    def __init__(self, array_ref):
        self.array_ref = array_ref  # the NDArray; holds .grad and grad_req
        self.value = None


def record_op(op, pos_attrs, attrs, nd_inputs, raw_arrays, outputs):
    """Called from ops.registry.invoke when recording."""
    entries = []
    any_grad = False
    for nd, raw in zip(nd_inputs, raw_arrays):
        ag = getattr(nd, "_ag", None)
        if ag is not None:
            if isinstance(ag[0], _Leaf):
                ag[0].value = raw  # snapshot at record time
            entries.append(("n", ag[0], ag[1]))
            any_grad = True
        else:
            entries.append(("c", raw))
    if not any_grad:
        return
    node = _Node(op, pos_attrs, attrs, entries, len(outputs))
    for i, o in enumerate(outputs):
        o._ag = (node, i)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Reference: ``autograd.mark_variables`` — attach grad buffers."""
    if not isinstance(variables, (list, tuple)):
        variables = [variables]
        gradients = [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req
        v._ag = (_Leaf(v), 0)


# ---------------------------------------------------------------------------
# Backward = replay + jax.vjp
# ---------------------------------------------------------------------------

def _collect(heads) -> Tuple[List[Any], List[Any]]:
    """Topologically order the sub-tape reachable from ``heads``.

    Returns (ordered nodes, leaves encountered)."""
    order: List[Any] = []
    seen = set()

    def visit(root):
        # iterative DFS: tapes from long unrolled loops exceed Python's
        # recursion limit
        stack = [(root, False)]
        while stack:
            node, expanded = stack.pop()
            if expanded:
                order.append(node)
                continue
            if id(node) in seen:
                continue
            seen.add(id(node))
            stack.append((node, True))
            if isinstance(node, _Node):
                for e in node.inputs:
                    if e[0] == "n" and id(e[1]) not in seen:
                        stack.append((e[1], False))

    for h in heads:
        ag = getattr(h, "_ag", None)
        if ag is None:
            raise MXNetError(
                "Cannot differentiate: output is not on the autograd tape "
                "(was it computed under autograd.record()?)")
        visit(ag[0])
    leaves = [n for n in order if isinstance(n, _Leaf)]
    return order, leaves


def _replay_fn(order, leaves, heads):
    """Build a pure function leaf_values -> head_values by replaying the
    tape.  This is the function handed to jax.vjp."""
    from .ops.registry import invoke_impl
    head_keys = []
    for h in heads:
        node, idx = h._ag
        head_keys.append((id(node), idx))

    def fn(*leaf_values):
        env: Dict[int, Tuple] = {}
        for leaf, v in zip(leaves, leaf_values):
            env[id(leaf)] = (v,)
        for node in order:
            if isinstance(node, _Leaf):
                continue
            args = []
            for e in node.inputs:
                if e[0] == "n":
                    args.append(env[id(e[1])][e[2]])
                else:
                    args.append(e[1])
            res = invoke_impl(node.op, args, node.pos_attrs, node.attrs)
            if not isinstance(res, (tuple, list)):
                res = (res,)
            env[id(node)] = tuple(res)
        return tuple(env[k][i] for (k, i) in head_keys)

    return fn


def _run_backward(heads, head_grads, variables=None, create_graph=False,
                  retain_graph=False):
    import jax
    import jax.numpy as jnp
    from .ndarray.ndarray import NDArray, _wrap

    heads = [h for h in heads]
    order, leaves = _collect(heads)
    if variables is not None:
        var_leaves = []
        for v in variables:
            ag = getattr(v, "_ag", None)
            if ag is None or not isinstance(ag[0], _Leaf):
                raise MXNetError("grad() variables must be marked "
                                 "(attach_grad/mark_variables)")
            var_leaves.append(ag[0])
        leaves_used = var_leaves
    else:
        leaves_used = leaves

    if not leaves_used:
        raise MXNetError("No differentiable variables reachable from heads "
                         "(did you call attach_grad()?)")

    # Treat non-requested leaves as constants by folding their current
    # values into the environment via closure.
    other = [l for l in order if isinstance(l, _Leaf) and l not in leaves_used]

    def _leaf_val(l):
        return l.value if l.value is not None else l.array_ref._data

    def fn(*vals):
        all_leaves = list(leaves_used) + other
        all_vals = list(vals) + [_leaf_val(l) for l in other]
        return _replay_fn(order, all_leaves, heads)(*all_vals)

    leaf_vals = [_leaf_val(l) for l in leaves_used]

    if head_grads is None:
        hg = tuple(jnp.ones(h.shape, h._data.dtype) for h in heads)
    else:
        hg = tuple(
            (jnp.ones(h.shape, h._data.dtype) if g is None else
             (g._data if isinstance(g, NDArray) else jnp.asarray(g)))
            for h, g in zip(heads, head_grads))

    _, vjp_fn = jax.vjp(fn, *leaf_vals)
    grads = vjp_fn(hg)

    if not retain_graph and not create_graph:
        for h in heads:
            pass  # tape nodes are GC'd with the arrays; nothing to free

    out = []
    for leaf, g in zip(leaves_used, grads):
        nd = leaf.array_ref
        req = getattr(nd, "_grad_req", "write")
        if variables is not None:
            gnd = _wrap(g)
            if create_graph:
                # Recording the grad as a tape op would require symbolic
                # replay of the vjp; instead mark it differentiable by
                # re-recording through a synthetic identity whose inputs are
                # the same leaves.  Implemented via jax.grad nesting in
                # grad_and_loss; plain create_graph marks outputs back onto
                # the tape.
                _record_grad_outputs(leaves_used, leaf_vals, fn, hg, gnd,
                                     len(out))
            out.append(gnd)
        else:
            if req == "null" or nd._grad is None:
                continue
            if req == "add":
                nd._grad._set_data(nd._grad._data + g)
            else:
                nd._grad._set_data(g)
    return out


def _record_grad_outputs(leaves_used, leaf_vals, fn, hg, gnd, idx):
    """Put a grad output back on the tape so it can itself be
    differentiated (create_graph=True)."""
    from .ops.registry import OpDef
    import jax

    def grad_impl(*vals):
        _, vjp_fn = jax.vjp(fn, *vals)
        return vjp_fn(hg)[idx]

    op = OpDef("_grad_of", grad_impl, num_outputs=1)
    node_inputs = [("n", l, 0) for l in leaves_used]
    node = _Node(op, (), {}, node_inputs, 1)
    gnd._ag = (node, 0)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of ``heads`` w.r.t. all attached variables and
    accumulate into their ``.grad`` buffers (reference:
    ``MXAutogradBackwardEx``)."""
    if not isinstance(heads, (list, tuple)):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    _run_backward(heads, head_grads, variables=None,
                  retain_graph=retain_graph)


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Functional gradient API (reference: ``autograd.grad``), returns grads
    instead of writing ``.grad``; supports higher order via
    ``create_graph=True``."""
    single = False
    if not isinstance(heads, (list, tuple)):
        heads = [heads]
        single = False
    if not isinstance(variables, (list, tuple)):
        variables = [variables]
        single = True
    if head_grads is not None and not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]
    if retain_graph is None:
        retain_graph = create_graph
    out = _run_backward(heads, head_grads, variables=variables,
                        create_graph=create_graph, retain_graph=retain_graph)
    if single:
        return out[0]
    return out


def get_symbol(x):
    raise MXNetError("autograd.get_symbol is not supported; use "
                     "HybridBlock.export() for graph extraction.")


class Function:
    """Custom differentiable function (reference: ``autograd.Function``).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` operating on NDArrays.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray
        from .ops.registry import OpDef
        import jax.numpy as jnp

        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (tuple, list))
        outs = [outputs] if single else list(outputs)

        if is_recording():
            func = self

            def impl(*arrays, **attrs):
                # forward replay on raw arrays
                nds = [NDArray(a) for a in arrays]
                with pause():
                    res = func.forward(*nds)
                res = [res] if not isinstance(res, (tuple, list)) else res
                return tuple(r._data for r in res)

            import jax

            @jax.custom_vjp
            def wrapped(*arrays):
                return impl(*arrays)

            def fwd(*arrays):
                return impl(*arrays), arrays

            def bwd(residual, gs):
                nds = [NDArray(g) for g in gs]
                with pause():
                    igrads = func.backward(*nds)
                igrads = ([igrads] if not isinstance(igrads, (tuple, list))
                          else igrads)
                return tuple(g._data for g in igrads)

            wrapped.defvjp(fwd, bwd)
            op = OpDef(type(self).__name__, lambda *a, **k: wrapped(*a),
                       num_outputs=len(outs))
            record_op(op, (), {}, list(inputs),
                      [i._data for i in inputs], outs)

        return outs[0] if single else tuple(outs)
