"""Global random state — MXNet's stateful RNG API on JAX's explicit keys.

Reference: ``mx.random.seed`` + per-ctx PRNG resources
(``src/resource.cc`` ``ResourceManager``, SURVEY.md §2.1 "Init/runtime
misc").

TPU-native design: JAX randomness is functional (explicit keys).  This
module owns a process-global key that random *ops* consume by splitting —
each consumed key is recorded on the autograd tape / passed as a traced
argument, so:

* eager replay (autograd backward) reproduces the forward sample exactly;
* under ``hybridize()`` the CachedOp threads a fresh key argument per call
  (``push_trace_key``), so compiled dropout gets new randomness every step
  without retracing.
"""
from __future__ import annotations

import threading
from typing import List, Optional

__all__ = ["seed", "next_key", "push_trace_key", "pop_trace_key"]


class _RandomState(threading.local):
    def __init__(self):
        self.key = None
        self.trace_stack: List = []


_STATE = _RandomState()
_SEED_LOCK = threading.Lock()
_GLOBAL_SEED = [0]


def seed(seed_state: int, ctx="all"):
    """Seed the global RNG (reference: ``mx.random.seed``)."""
    import jax
    with _SEED_LOCK:
        _GLOBAL_SEED[0] = int(seed_state)
    _STATE.key = jax.random.PRNGKey(int(seed_state))


def _ensure_key():
    import jax
    if _STATE.key is None:
        _STATE.key = jax.random.PRNGKey(_GLOBAL_SEED[0])
    return _STATE.key


def next_key():
    """Return a fresh PRNG key.

    Inside a CachedOp trace, splits from the traced key argument so that the
    compiled function re-randomizes per call; otherwise splits the global
    stateful key.
    """
    import jax
    if _STATE.trace_stack:
        cur = _STATE.trace_stack[-1]
        new, sub = jax.random.split(cur)
        _STATE.trace_stack[-1] = new
        return sub
    cur = _ensure_key()
    new, sub = jax.random.split(cur)
    _STATE.key = new
    return sub


def push_trace_key(key):
    _STATE.trace_stack.append(key)


def pop_trace_key():
    return _STATE.trace_stack.pop()


def uses_rng_in_trace() -> bool:
    return bool(_STATE.trace_stack)
