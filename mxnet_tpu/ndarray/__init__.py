"""``mx.nd`` — the imperative NDArray API.

Reference: ``python/mxnet/ndarray/`` (SURVEY.md §2.2 "NDArray API").
"""
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange,
                      concat, stack, save, load, waitall, from_numpy)
from . import ndarray as _ndmod
from . import register as _register
from .. import ops as _ops  # ensure registry populated

# creation-op conveniences with MXNet names
import sys as _sys

_register.populate(globals())
_ndmod._install_methods()

from . import contrib  # noqa: E402  (control flow: foreach/while_loop/cond)
from . import sparse  # noqa: E402  (row_sparse / csr storage types)


def eye(N, M=0, k=0, ctx=None, dtype="float32"):
    from ..ops.registry import get_op, invoke
    return invoke(get_op("_eye"), [], attrs={"N": N, "M": M, "k": k,
                                             "dtype": dtype}, ctx=ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype="float32"):
    from ..ops.registry import get_op, invoke
    return invoke(get_op("_linspace"), [],
                  attrs={"start": start, "stop": stop, "num": num,
                         "endpoint": endpoint, "dtype": dtype}, ctx=ctx)


def zeros_like(data, **kw):
    from ..ops.registry import get_op, invoke
    return invoke(get_op("zeros_like"), [data])


def ones_like(data, **kw):
    from ..ops.registry import get_op, invoke
    return invoke(get_op("ones_like"), [data])
