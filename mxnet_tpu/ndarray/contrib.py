"""``mx.nd.contrib`` — control-flow operators.

Reference: ``src/operator/control_flow.cc`` + ``python/mxnet/ndarray/
contrib.py`` (``foreach`` / ``while_loop`` / ``cond`` — SURVEY.md §2.1
"Operator library" row).

TPU-native design: the reference interprets the body with per-step
executors; here each construct lowers to the corresponding XLA structured
control-flow primitive (``lax.scan`` / ``lax.cond``), so the loop compiles
to ONE fused computation with static shapes — the idiom jit requires
(task brief: "no data-dependent Python control flow inside jit").
``while_loop`` deliberately lowers to a masked ``lax.scan`` over
``max_iterations`` instead of ``lax.while_loop``: bounded iteration keeps
it reverse-mode differentiable (XLA's while is not), matching the
reference's requirement that callers provide ``max_iterations`` anyway.

Each construct is invoked as an ephemeral op through the registry, so the
autograd tape records one node whose replay re-traces the body — gradients
flow through ``jax.vjp`` of the whole scan.
"""
from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple, Union

from ..base import MXNetError
from ..ops.registry import OpDef, invoke
from .ndarray import NDArray, _wrap

__all__ = ["foreach", "while_loop", "cond"]


def _as_list(x) -> Tuple[List, bool]:
    """Returns (list, was_list)."""
    if isinstance(x, (list, tuple)):
        return list(x), True
    return [x], False


def _unwrap(x):
    return x._data if isinstance(x, NDArray) else x


def _wrap_all(arrs):
    return [_wrap(a) for a in arrs]


def _run_body_pure(body, *nd_args):
    """Call user body with NDArray views over tracers, autograd paused
    (the scan itself is the single tape node)."""
    from .. import autograd
    with autograd.pause():
        return body(*nd_args)


def foreach(body: Callable, data, init_states):
    """Iterate ``body(data_slice, states) -> (outputs, new_states)`` over
    axis 0 of ``data`` (reference: mx.nd.contrib.foreach).

    Returns (outputs, final_states) with per-step outputs stacked on
    axis 0.  Compiles to one ``lax.scan``.
    """
    data_list, data_was_list = _as_list(data)
    states_list, states_was_list = _as_list(init_states)
    n_data, n_states = len(data_list), len(states_list)
    meta = {}

    def impl(*arrays):
        import jax.numpy as jnp
        from jax import lax

        xs = tuple(arrays[:n_data])
        init = tuple(arrays[n_data:])

        def step(carry, x_slice):
            x_nd = _wrap_all(x_slice)
            s_nd = _wrap_all(carry)
            outs, new_states = _run_body_pure(
                body,
                x_nd if data_was_list else x_nd[0],
                s_nd if states_was_list else s_nd[0])
            outs_l, outs_was_list = _as_list(outs)
            ns_l, _ = _as_list(new_states)
            if len(ns_l) != n_states:
                raise MXNetError("foreach: body returned %d states, "
                                 "expected %d" % (len(ns_l), n_states))
            meta["n_out"] = len(outs_l)
            meta["outs_was_list"] = outs_was_list
            return (tuple(_unwrap(s) for s in ns_l),
                    tuple(_unwrap(o) for o in outs_l))

        final, ys = lax.scan(step, init, xs)
        return tuple(ys) + tuple(final)

    op = OpDef("_foreach", impl, num_outputs=-1)
    results = invoke(op, data_list + states_list)
    rlist = list(results) if isinstance(results, (tuple, list)) else [results]
    n_out = meta["n_out"]
    outputs = rlist[:n_out]
    final_states = rlist[n_out:]
    if not meta["outs_was_list"]:
        outputs = outputs[0]
    if not states_was_list:
        final_states = final_states[0]
    return outputs, final_states


def while_loop(cond: Callable, func: Callable, loop_vars,
               max_iterations: int):
    """``while cond(*loop_vars): outputs, loop_vars = func(*loop_vars)``
    (reference: mx.nd.contrib.while_loop).

    Returns (outputs, final_loop_vars); outputs are stacked buffers of
    length ``max_iterations`` (steps beyond termination hold zeros, as in
    the reference's padded semantics).
    """
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    lv_list, was_list = _as_list(loop_vars)
    n_vars = len(lv_list)
    meta = {}

    def impl(*arrays):
        import jax
        import jax.numpy as jnp
        from jax import lax

        init = tuple(arrays)

        def pred(vars_):
            r = _run_body_pure(cond, *_wrap_all(vars_))
            r = _unwrap(r)
            return jnp.reshape(r.astype(bool), ())

        def step(carry, _):
            vars_, alive = carry

            def take(v):
                outs, new_vars = _run_body_pure(func, *_wrap_all(v))
                outs_l, outs_was_list = _as_list(outs)
                nv_l, _ = _as_list(new_vars)
                if len(nv_l) != n_vars:
                    raise MXNetError(
                        "while_loop: func returned %d loop_vars, "
                        "expected %d" % (len(nv_l), n_vars))
                meta["n_out"] = len(outs_l)
                meta["outs_was_list"] = outs_was_list
                return (tuple(_unwrap(x) for x in nv_l),
                        tuple(_unwrap(o) for o in outs_l))

            alive_now = alive & pred(vars_)
            new_vars, outs = take(vars_)
            new_vars = tuple(
                jnp.where(alive_now, nv, v) for nv, v in zip(new_vars,
                                                             vars_))
            outs = tuple(jnp.where(alive_now, o, jnp.zeros_like(o))
                         for o in outs)
            return (new_vars, alive_now), outs + (alive_now,)

        (final_vars, _), ys = lax.scan(
            step, (init, jnp.asarray(True)), None, length=max_iterations)
        n_out = meta["n_out"]
        n_steps = jnp.sum(ys[-1].astype(jnp.int32))
        return tuple(ys[:n_out]) + tuple(final_vars) + (n_steps,)

    op = OpDef("_while_loop", impl, num_outputs=-1)
    results = invoke(op, lv_list)
    rlist = list(results)
    n_out = meta["n_out"]
    outputs = rlist[:n_out]
    final_vars = rlist[n_out:n_out + n_vars]
    if not meta["outs_was_list"]:
        outputs = outputs[0]
    if not was_list:
        final_vars = final_vars[0]
    return outputs, final_vars


def cond(pred, then_func: Callable, else_func: Callable, inputs=None):
    """``then_func() if pred else else_func()`` compiled as ``lax.cond``
    (reference: mx.nd.contrib.cond).  Both branches must return the same
    shapes/dtypes."""
    in_list, _ = _as_list(inputs if inputs is not None else [])
    meta = {}

    def impl(*arrays):
        import jax.numpy as jnp
        from jax import lax

        p = arrays[0]
        rest = arrays[1:]

        def mk(branch):
            def run(ops):
                r = _run_body_pure(branch, *_wrap_all(ops)) \
                    if ops else _run_body_pure(branch)
                r_l, was_list = _as_list(r)
                meta["was_list"] = was_list
                return tuple(_unwrap(x) for x in r_l)
            return run

        return lax.cond(jnp.reshape(p.astype(bool), ()),
                        mk(then_func), mk(else_func), rest)

    op = OpDef("_cond", impl, num_outputs=-1)
    pred_nd = pred if isinstance(pred, NDArray) else _wrap(pred)
    results = invoke(op, [pred_nd] + in_list)
    rlist = list(results) if isinstance(results, (tuple, list)) else [results]
    if not meta["was_list"]:
        return rlist[0]
    return rlist


# ---------------------------------------------------------------------------
# Surface every ``_contrib_*`` registry op as ``nd.contrib.<short name>``
# (reference: the generated ``python/mxnet/ndarray/contrib.py`` namespace).
# ---------------------------------------------------------------------------

def _populate_contrib():
    from ..ops import registry as _registry
    from .register import _make_stub
    for _name in _registry.list_ops():
        if _name.startswith("_contrib_"):
            _short = _name[len("_contrib_"):]
            if _short not in globals():
                globals()[_short] = _make_stub(_registry.get_op(_name))


_populate_contrib()
