"""Generated ``nd`` namespace — stubs created by walking the op registry.

Reference: ``python/mxnet/ndarray/register.py`` (SURVEY.md §1: "Python op
functions are generated at import time by walking the registry").
"""
from __future__ import annotations

import sys
from typing import Any

from ..ops import registry as _registry
from .ndarray import NDArray


def _make_stub(op: "_registry.OpDef"):
    def stub(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)
        ctx = kwargs.pop("ctx", None)
        arrays = []
        pos_attrs = []
        flat_args = []
        for a in args:
            if isinstance(a, (list, tuple)) and a and \
                    all(isinstance(x, NDArray) for x in a):
                flat_args.extend(a)
            else:
                flat_args.append(a)
        seen_attr = False
        for a in flat_args:
            if isinstance(a, NDArray) and not seen_attr:
                arrays.append(a)
            else:
                seen_attr = True
                pos_attrs.append(a)
        return _registry.invoke(op, arrays, tuple(pos_attrs), kwargs,
                                out=out, ctx=ctx)

    stub.__name__ = op.name
    stub.__doc__ = op.doc
    return stub


def populate(namespace: dict, symbol_mode: bool = False):
    """Install a stub for every registered op into ``namespace``."""
    seen = set()
    for name in _registry.list_ops():
        op = _registry.get_op(name)
        if name in namespace:
            continue
        namespace[name] = _make_stub(op)
        seen.add(name)
    return seen
