"""NDArray — imperative, mutable, asynchronously-evaluated array on XLA.

Reference: ``src/ndarray/ndarray.cc`` + ``python/mxnet/ndarray/ndarray.py``
(SURVEY.md §2.1 "NDArray core", §2.2 "NDArray API", §7 hard-part #1
"Mutation semantics on immutable XLA buffers").

TPU-native design: an NDArray owns a *chunk* holding a ``jax.Array``.
Mutation (``+=``, slice-assign, optimizer updates, ``out=``) computes a new
buffer functionally and swaps the chunk, bumping a version counter — the
same observable semantics as the reference's engine-var versioning, with
XLA/PjRt supplying the async ordering that the reference's ThreadedEngine
provided (every op returns immediately; ``wait_to_read``/``asnumpy`` are the
sync points).  Basic-slice *views* are therefore copies here (documented
divergence: reference basic slices alias; ``__setitem__`` on the base array
is the supported mutation path and matches reference behavior).
"""
from __future__ import annotations

import numpy as _np
from typing import Any, List, Optional, Sequence, Tuple, Union

from ..base import MXNetError, numeric_types, integer_types
from ..context import Context, current_context, cpu

__all__ = ["NDArray", "_wrap", "array", "zeros", "ones", "full", "empty",
           "arange", "concat", "stack", "save", "load", "waitall",
           "from_numpy", "from_dlpack", "to_dlpack_for_read"]


def _jnp():
    import jax.numpy as jnp
    return jnp


# Set by profiler._mem_start() when ``profile_memory=True`` is active:
# called with every chunk buffer entering the NDArray layer (construction
# and chunk-swap mutation).  None → zero overhead on the hot path.
_MEM_HOOK = None


def _dev_of(data):
    try:
        devs = data.devices()
        return next(iter(devs))
    except Exception:
        return None


def _ctx_of(data) -> Context:
    dev = _dev_of(data)
    if dev is None:
        return current_context()
    if dev.platform == "cpu":
        import jax
        try:
            accel = jax.devices()[0].platform != "cpu"
        except Exception:
            accel = False
        if accel:
            return Context("cpu", dev.id)
        # CPU-only harness: report the virtual device as tpu ctx only if
        # user asked; default to cpu ctx with matching id.
        return Context("cpu", dev.id)
    return Context("tpu", dev.id)


class NDArray:
    """Multi-dimensional array with imperative mutation semantics."""

    __slots__ = ("_data", "_version", "_grad", "_grad_req", "_ag",
                 "_ctx_hint", "__weakref__")

    def __init__(self, data, ctx: Optional[Context] = None):
        jnp = _jnp()
        if isinstance(data, NDArray):
            data = data._data
        if not hasattr(data, "dtype") or isinstance(data, _np.ndarray):
            data = jnp.asarray(data)
        self._data = data
        self._version = 0
        self._grad = None
        self._grad_req = "null"
        self._ag = None
        self._ctx_hint = ctx
        if _MEM_HOOK is not None:
            _MEM_HOOK(data)

    # ------------------------------------------------------------------
    # chunk swap = mutation
    # ------------------------------------------------------------------
    def _set_data(self, new_data):
        """Swap the underlying buffer (the mutation primitive).  Bumps the
        version counter — reference: engine write-var version++."""
        self._data = new_data
        self._version += 1
        if _MEM_HOOK is not None:
            _MEM_HOOK(new_data)

    # ------------------------------------------------------------------
    # properties
    # ------------------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(str(self._data.dtype))

    @property
    def size(self) -> int:
        s = 1
        for d in self.shape:
            s *= d
        return s

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def context(self) -> Context:
        if self._ctx_hint is not None:
            return self._ctx_hint
        return _ctx_of(self._data)

    ctx = context

    @property
    def stype(self) -> str:
        return "default"

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    @property
    def T(self) -> "NDArray":
        return self.transpose()

    @property
    def version(self) -> int:
        return self._version

    # ------------------------------------------------------------------
    # sync / host transfer
    # ------------------------------------------------------------------
    def wait_to_read(self):
        """Block until the value is computed (reference:
        ``Engine::WaitForVar``); deferred device errors surface here."""
        self._data.block_until_ready()
        return self

    def asnumpy(self) -> _np.ndarray:
        return _np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def tolist(self):
        return self.asnumpy().tolist()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("Ambiguous truth value of multi-element NDArray; "
                         "use .any() or .all()")

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # ------------------------------------------------------------------
    # context movement
    # ------------------------------------------------------------------
    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self.context:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context

    def copyto(self, other: Union[Context, "NDArray"]) -> "NDArray":
        import jax
        if isinstance(other, Context):
            moved = jax.device_put(self._data, other.jax_device)
            out = NDArray(moved, ctx=other)
            return out
        if isinstance(other, NDArray):
            moved = jax.device_put(self._data, _dev_of(other._data))
            other._set_data(moved)
            return other
        raise MXNetError("copyto target must be Context or NDArray")

    def copy(self) -> "NDArray":
        jnp = _jnp()
        return NDArray(jnp.array(self._data), ctx=self._ctx_hint)

    def astype(self, dtype, copy: bool = True) -> "NDArray":
        if not copy and _np.dtype(dtype) == self.dtype:
            return self
        return _wrap(self._data.astype(_np.dtype(dtype).name))

    # ------------------------------------------------------------------
    # autograd
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype=None):
        """Allocate a gradient buffer (on this array's device) and mark
        this array as a variable."""
        from .. import autograd
        import jax
        jnp = _jnp()
        with jax.default_device(_dev_of(self._data)):
            grad = NDArray(jnp.zeros(self.shape, self._data.dtype))
        autograd.mark_variables([self], [grad], [grad_req])

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    def detach(self) -> "NDArray":
        out = NDArray(self._data)
        return out

    # ------------------------------------------------------------------
    # operator sugar — routed through registered scalar/broadcast ops so
    # everything lands on the autograd tape uniformly.
    # ------------------------------------------------------------------
    def _binop(self, other, op_name, scalar_op, reverse=False):
        from ..ops.registry import get_op, invoke
        if isinstance(other, NDArray):
            return invoke(get_op(op_name), [self, other])
        if isinstance(other, numeric_types + (bool, _np.generic)):
            return invoke(get_op(scalar_op), [self],
                          attrs={"scalar": float(other)})
        return NotImplemented

    def __add__(self, other):
        return self._binop(other, "broadcast_add", "_plus_scalar")

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return self._binop(other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        return self._binop(other, "broadcast_sub", "_rminus_scalar")

    def __mul__(self, other):
        return self._binop(other, "broadcast_mul", "_mul_scalar")

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return self._binop(other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        return self._binop(other, "broadcast_div", "_rdiv_scalar")

    def __floordiv__(self, other):
        return self._binop(other, "_broadcast_floordiv", "_floordiv_scalar")

    def __mod__(self, other):
        return self._binop(other, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, other):
        return self._binop(other, "broadcast_mod", "_rmod_scalar")

    def __pow__(self, other):
        return self._binop(other, "broadcast_power", "_power_scalar")

    def __rpow__(self, other):
        return self._binop(other, "broadcast_power", "_rpower_scalar")

    def __matmul__(self, other):
        from ..ops.registry import get_op, invoke
        return invoke(get_op("_npi_matmul"), [self, other])

    def __neg__(self):
        from ..ops.registry import get_op, invoke
        return invoke(get_op("negative"), [self])

    def __abs__(self):
        from ..ops.registry import get_op, invoke
        return invoke(get_op("abs"), [self])

    def __eq__(self, other):
        if other is None:
            return False
        r = self._binop(other, "broadcast_equal", "_equal_scalar")
        return r

    def __ne__(self, other):
        if other is None:
            return True
        return self._binop(other, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, other):
        return self._binop(other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return self._binop(other, "broadcast_greater_equal",
                           "_greater_equal_scalar")

    def __lt__(self, other):
        return self._binop(other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return self._binop(other, "broadcast_lesser_equal",
                           "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    # in-place: functional compute + chunk swap
    def _inplace(self, other, op_name, scalar_op):
        from .. import autograd
        if autograd.is_recording() and self._ag is not None:
            raise MXNetError("Inplace update on a recorded array is not "
                             "allowed under autograd.record()")
        res = self._binop(other, op_name, scalar_op)
        self._set_data(res._data)
        return self

    def __iadd__(self, other):
        return self._inplace(other, "broadcast_add", "_plus_scalar")

    def __isub__(self, other):
        return self._inplace(other, "broadcast_sub", "_minus_scalar")

    def __imul__(self, other):
        return self._inplace(other, "broadcast_mul", "_mul_scalar")

    def __itruediv__(self, other):
        return self._inplace(other, "broadcast_div", "_div_scalar")

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    def _int64_index_scope(self):
        """x64 scope for indexing arrays whose element count exceeds
        int32 range: without it JAX truncates slice starts/scatter
        indices to int32 — reads past 2^31 raise OverflowError and
        writes silently land nowhere (reference:
        tests/nightly/test_large_array.py, the INT64_TENSOR_SIZE build
        flag; SURVEY.md §4.7)."""
        import contextlib
        if self.size >= 2**31:
            # jax.enable_x64 (deprecated alias) was removed; the
            # experimental context manager is the stable spelling
            from jax.experimental import enable_x64
            return enable_x64(True)
        return contextlib.nullcontext()

    def _widen_index_arrays(self, k):
        """Inside the int64 scope, integer index ARRAYS must also be
        int64 — XLA computes gather/scatter offsets in the index dtype,
        so int32 indices overflow on >=2^31-element arrays even with
        x64 on."""
        jnp = _jnp()

        def widen(x):
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype,
                                                      jnp.integer):
                return x.astype(jnp.int64)
            return x

        if isinstance(k, tuple):
            return tuple(widen(e) for e in k)
        return widen(k)

    def __getitem__(self, key):
        key = _clean_index(key)
        from ..ops.registry import OpDef, invoke
        idx_arrays = _extract_index_arrays(key)

        # cacheable lane: basic/int-fancy indexing on <2^31-element
        # arrays goes through a stable op with the index as a hashable
        # attr, so the eager-jit cache applies (slicing is the data
        # pipeline's hottest imperative op).  Bool masks (data-dependent
        # output shape) and int64-widening cases use the direct path.
        if self.size < 2**31:
            tmpl = _index_template(key)
            if tmpl is not None and not any(
                    a.dtype == _np.bool_ for a in idx_arrays):
                return invoke(_getitem_op(), [self] + idx_arrays,
                              attrs={"key_tmpl": tmpl})

        def impl(data, *idx_arrs):
            k = _rebuild_index(key, list(idx_arrs))
            with self._int64_index_scope():
                if self.size >= 2**31:
                    k = self._widen_index_arrays(k)
                return data[k]

        op = OpDef("_getitem", impl, num_outputs=1)
        return invoke(op, [self] + idx_arrays)

    def __setitem__(self, key, value):
        from .. import autograd
        if autograd.is_recording() and self._ag is not None:
            raise MXNetError("Slice-assign on a recorded array is not "
                             "allowed under autograd.record()")
        jnp = _jnp()
        key = _clean_index(key)
        idx_arrays = _extract_index_arrays(key)
        k = _rebuild_index(key, [a._data for a in idx_arrays])
        if isinstance(value, NDArray):
            v = value._data
        else:
            v = value
        with self._int64_index_scope():
            if self.size >= 2**31:
                k = self._widen_index_arrays(k)
            new = self._data.at[k].set(v)
        self._set_data(new)
        return self

    # ------------------------------------------------------------------
    # misc reference-API methods
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        from ..ops.registry import get_op, invoke
        return invoke(get_op("reshape"), [self], attrs={"shape": shape})

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def expand_dims(self, axis):
        from ..ops.registry import get_op, invoke
        return invoke(get_op("expand_dims"), [self], attrs={"axis": axis})

    def squeeze(self, axis=None):
        from ..ops.registry import get_op, invoke
        return invoke(get_op("squeeze"), [self], attrs={"axis": axis})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        from ..ops.registry import get_op, invoke
        return invoke(get_op("transpose"), [self],
                      attrs={"axes": axes if axes else None})

    def flatten(self):
        from ..ops.registry import get_op, invoke
        return invoke(get_op("Flatten"), [self])

    def flip(self, axis):
        from ..ops.registry import get_op, invoke
        return invoke(get_op("flip"), [self], attrs={"axis": axis})

    def tile(self, reps):
        from ..ops.registry import get_op, invoke
        return invoke(get_op("tile"), [self], attrs={"reps": reps})

    def broadcast_to(self, shape):
        from ..ops.registry import get_op, invoke
        return invoke(get_op("broadcast_to"), [self], attrs={"shape": shape})

    def broadcast_like(self, other):
        return self.broadcast_to(other.shape)

    def tostype(self, stype):
        if stype == "default":
            return self.copy()  # reference tostype always returns a new array
        from .sparse import cast_storage
        return cast_storage(self, stype)

    def todense(self):
        return self.copy()

    def __repr__(self):
        return "\n%s\n<NDArray %s @%s>" % (
            str(self.asnumpy()), "x".join(str(d) for d in self.shape),
            self.context)


# Install op-delegating methods (sum, mean, max, ... — reference NDArray has
# method mirrors for common ops, generated alongside the function stubs).
_METHOD_OPS = [
    "sum", "mean", "max", "min", "prod", "argmax", "argmin", "abs", "exp",
    "log", "sqrt", "square", "clip", "round", "floor", "ceil", "sign",
    "relu", "sigmoid", "tanh", "softmax", "log_softmax", "norm", "sort",
    "argsort", "topk", "one_hot", "take", "pick", "dot", "split",
    "slice_axis", "slice_like", "swapaxes", "repeat", "pad", "nansum",
    "nanprod", "cumsum", "diag", "zeros_like", "ones_like",
]


def _install_methods():
    from ..ops import registry as _r

    def make(opname):
        def method(self, *args, **kwargs):
            op = _r.get_op(opname)
            extra = [a for a in args if isinstance(a, NDArray)]
            pos = tuple(a for a in args if not isinstance(a, NDArray))
            return _r.invoke(op, [self] + extra, pos_attrs=pos, attrs=kwargs)
        method.__name__ = opname
        return method

    for opname in _METHOD_OPS:
        if not hasattr(NDArray, opname) and _r.op_exists(opname):
            setattr(NDArray, opname, make(opname))


_SCALAR_REVERSIBLE = {}


def _wrap(data) -> NDArray:
    return NDArray(data)


# ---------------------------------------------------------------------------
# indexing helpers
# ---------------------------------------------------------------------------

def _clean_index(key):
    if isinstance(key, NDArray):
        return key
    if isinstance(key, tuple):
        return tuple(_clean_index(k) for k in key)
    return key


def _extract_index_arrays(key) -> List[NDArray]:
    out = []
    if isinstance(key, NDArray):
        out.append(key)
    elif isinstance(key, tuple):
        for k in key:
            if isinstance(k, NDArray):
                out.append(k)
    return out


def _rebuild_index(key, arrays: List[Any]):
    it = iter(arrays)
    if isinstance(key, NDArray):
        return next(it)
    if isinstance(key, tuple):
        return tuple(next(it) if isinstance(k, NDArray) else k for k in key)
    return key


# --- cacheable __getitem__ lane -------------------------------------------

class _Arr:
    """Hashable placeholder marking an index-array position in a key
    template (the arrays themselves travel as op inputs)."""
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<ARR>"


_ARR = _Arr()


def _index_template(key):
    """Hashable template of a cleaned index, or None if the index uses
    constructs the cacheable lane does not handle (lists — whose
    fancy-index semantics a tuple template would corrupt — np arrays,
    or anything unhashable)."""
    def one(k):
        if isinstance(k, NDArray):
            return _ARR
        if k is None or k is Ellipsis or type(k) is slice:
            return k
        if isinstance(k, (int, _np.integer)) and not isinstance(k, bool):
            return int(k)
        return _INVALID

    _INVALID = object()
    if isinstance(key, tuple):
        out = tuple(one(k) for k in key)
        return None if any(o is _INVALID for o in out) else out
    o = one(key)
    return None if o is _INVALID else o


def _rebuild_index_tmpl(tmpl, arrays: List[Any]):
    it = iter(arrays)
    if tmpl is _ARR:
        return next(it)
    if isinstance(tmpl, tuple):
        return tuple(next(it) if k is _ARR else k for k in tmpl)
    return tmpl


def _getitem_cacheable_impl(*args, key_tmpl=None):
    data = args[0]
    return data[_rebuild_index_tmpl(key_tmpl, list(args[1:]))]


_GETITEM_OP = None


def _getitem_op():
    global _GETITEM_OP
    if _GETITEM_OP is None:
        from ..ops.registry import OpDef
        # module-lifetime OpDef → safe to mark cacheable (id is stable)
        _GETITEM_OP = OpDef("_getitem", _getitem_cacheable_impl,
                            num_outputs=1, cacheable=True)
    return _GETITEM_OP


# ---------------------------------------------------------------------------
# creation API (reference: mx.nd.zeros/ones/array/...)
# ---------------------------------------------------------------------------

def _creation_ctx(ctx):
    return ctx if ctx is not None else current_context()


def array(source_array, ctx=None, dtype=None) -> NDArray:
    import jax
    ctx = _creation_ctx(ctx)
    if isinstance(source_array, NDArray):
        source_array = source_array.asnumpy()
    np_arr = _np.asarray(source_array, dtype=dtype)
    if np_arr.dtype == _np.float64 and dtype is None:
        np_arr = np_arr.astype(_np.float32)
    # device_put the NUMPY buffer directly: wrapping it in jnp.asarray
    # first would materialize it on the DEFAULT device and then move it
    # — under the tunneled TPU backend that turned every cpu-context
    # nd.array() into a full wire round trip (measured 4.3 s for a
    # 38 MB batch; docs/perf.md "End-to-end input pipeline")
    data = jax.device_put(np_arr, ctx.jax_device)
    return NDArray(data, ctx=ctx)


def from_numpy(np_array, zero_copy=False) -> NDArray:
    return array(np_array)


def empty(shape, ctx=None, dtype="float32") -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype="float32", **kwargs) -> NDArray:
    import jax
    jnp = _jnp()
    ctx = _creation_ctx(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    with jax.default_device(ctx.jax_device):
        data = jnp.zeros(shape, dtype or "float32")
    return NDArray(data, ctx=ctx)


def ones(shape, ctx=None, dtype="float32", **kwargs) -> NDArray:
    import jax
    jnp = _jnp()
    ctx = _creation_ctx(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    with jax.default_device(ctx.jax_device):
        data = jnp.ones(shape, dtype or "float32")
    return NDArray(data, ctx=ctx)


def full(shape, val, ctx=None, dtype="float32") -> NDArray:
    import jax
    jnp = _jnp()
    ctx = _creation_ctx(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    with jax.default_device(ctx.jax_device):
        data = jnp.full(shape, val, dtype or "float32")
    return NDArray(data, ctx=ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None,
           dtype="float32") -> NDArray:
    import jax
    jnp = _jnp()
    ctx = _creation_ctx(ctx)
    with jax.default_device(ctx.jax_device):
        data = jnp.arange(start, stop, step, dtype)
        if repeat > 1:
            data = jnp.repeat(data, repeat)
    return NDArray(data, ctx=ctx)


def concat(*arrays, dim=1) -> NDArray:
    from ..ops.registry import get_op, invoke
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = arrays[0]
    return invoke(get_op("Concat"), list(arrays), attrs={"dim": dim})


def stack(*arrays, axis=0) -> NDArray:
    from ..ops.registry import get_op, invoke
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = arrays[0]
    return invoke(get_op("stack"), list(arrays), attrs={"axis": axis})


def waitall():
    from ..engine import Engine
    Engine.get().wait_for_all()


# ---------------------------------------------------------------------------
# save / load — the ``.params`` container format.
#
# Reference: ``NDArray::Save/Load`` binary container (SURVEY.md §5.4).  The
# reference mount was empty this round, so byte-level compatibility could not
# be verified; this container uses a documented magic-tagged format of our
# own ("MXTP0001") with an identical API surface.
# ---------------------------------------------------------------------------

_PARAMS_MAGIC = b"MXTP0001"


def save(fname: str, data):
    import struct
    if isinstance(data, NDArray):
        data = [("", data)]
    if isinstance(data, dict):
        data = list(data.items())
    elif isinstance(data, (list, tuple)) and not (
            data and isinstance(data[0], tuple)):
        data = [("", d) for d in data]
    with open(fname, "wb") as f:
        f.write(_PARAMS_MAGIC)
        f.write(struct.pack("<Q", len(data)))
        for name, arr in data:
            nb = name.encode("utf-8")
            np_arr = arr.asnumpy() if isinstance(arr, NDArray) else _np.asarray(arr)
            dt = np_arr.dtype.str.encode()
            f.write(struct.pack("<I", len(nb)))
            f.write(nb)
            f.write(struct.pack("<I", len(dt)))
            f.write(dt)
            f.write(struct.pack("<I", np_arr.ndim))
            for d in np_arr.shape:
                f.write(struct.pack("<q", d))
            payload = np_arr.tobytes()
            f.write(struct.pack("<Q", len(payload)))
            f.write(payload)


def load(fname: str):
    import struct
    with open(fname, "rb") as f:
        magic = f.read(8)
        if magic != _PARAMS_MAGIC:
            raise MXNetError("Invalid parameter file %s (bad magic %r)"
                             % (fname, magic))
        (count,) = struct.unpack("<Q", f.read(8))
        entries = []
        for _ in range(count):
            (nlen,) = struct.unpack("<I", f.read(4))
            name = f.read(nlen).decode("utf-8")
            (dlen,) = struct.unpack("<I", f.read(4))
            dt = _np.dtype(f.read(dlen).decode())
            (ndim,) = struct.unpack("<I", f.read(4))
            shape = tuple(struct.unpack("<q", f.read(8))[0]
                          for _ in range(ndim))
            (plen,) = struct.unpack("<Q", f.read(8))
            buf = f.read(plen)
            np_arr = _np.frombuffer(buf, dtype=dt).reshape(shape)
            entries.append((name, array(np_arr, dtype=dt)))
        if any(name for name, _ in entries):
            return dict(entries)
        return [arr for _, arr in entries]
