"""Sparse NDArray storage types — ``row_sparse`` and ``csr``.

Reference: ``python/mxnet/ndarray/sparse.py`` + the stype machinery in
``src/ndarray/ndarray.cc`` / per-op ``FInferStorageType`` (SURVEY.md §2.1
"NDArray core", §7 hard-part #7).

TPU-native stance: sparse storage is host-describable metadata (row ids /
col ids / indptr) around dense *value* blocks that live on device.  The ops
that are genuinely sparse-friendly on TPU — ``dot(csr, dense)`` via
gather + ``segment_sum``, ``retain``, lazy row-wise optimizer updates,
storage casts — run as real sparse kernels (XLA maps gather/scatter/segment
ops onto the hardware well).  General elementwise math *falls back to dense*
with a one-time warning, mirroring the reference's own stype-fallback
machinery (``operator/elemwise_op_common.h`` dispatches to dense when no
``FComputeEx`` matches).  Structure discovery (nonzero detection, index
union/intersection) happens eagerly on host — these arrays are concrete in
the imperative API, never traced.
"""
from __future__ import annotations

import warnings

import numpy as _np

from ..base import MXNetError
from ..context import Context
from .ndarray import NDArray, _wrap

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "csr_matrix", "row_sparse_array", "array", "empty", "zeros",
           "cast_storage", "retain", "dot", "add_n", "elemwise_add",
           "elemwise_sub", "elemwise_mul", "sgd_update", "sgd_mom_update",
           "adam_update"]


def _jnp():
    import jax.numpy as jnp
    return jnp


_warned_fallback = set()


def _fallback_warn(opname):
    if opname not in _warned_fallback:
        _warned_fallback.add(opname)
        warnings.warn(
            "sparse %s executes as a dense fallback on TPU (reference "
            "behavior: stype fallback when no FComputeEx is registered)"
            % opname, stacklevel=3)


class BaseSparseNDArray(NDArray):
    """Common base of :class:`CSRNDArray` / :class:`RowSparseNDArray`.

    ``_data`` holds the compact value block (device array); aux index
    arrays live in ``_aux``; the logical dense shape in ``_sparse_shape``.
    """

    __slots__ = ("_aux", "_sparse_shape")

    def __init__(self, values, aux, shape, ctx=None):
        super().__init__(values, ctx=ctx)
        self._aux = aux
        self._sparse_shape = tuple(int(d) for d in shape)

    # -- overridden dense-NDArray surface ------------------------------
    @property
    def shape(self):
        return self._sparse_shape

    @property
    def data(self) -> NDArray:
        """The compact values block."""
        return _wrap(self._data)

    @property
    def indices(self) -> NDArray:
        return _wrap(self._aux["indices"])

    def asnumpy(self):
        return _np.asarray(self._to_dense_jax())

    def wait_to_read(self):
        self._data.block_until_ready()
        return self

    def todense(self) -> NDArray:
        return _wrap(self._to_dense_jax())

    to_dense = todense

    def tostype(self, stype):
        return cast_storage(self, stype)

    def copy(self):
        return self.__class__(self._data, dict(self._aux),
                              self._sparse_shape)

    def copyto(self, other):
        if isinstance(other, Context):
            return self.copy()
        if isinstance(other, BaseSparseNDArray):
            other._set_data(self._data)
            other._aux = dict(self._aux)
            other._sparse_shape = self._sparse_shape
            return other
        if isinstance(other, NDArray):
            other._set_data(self._to_dense_jax())
            return other
        raise MXNetError("copyto: unsupported target %r" % (other,))

    def _dense(self) -> NDArray:
        return _wrap(self._to_dense_jax())

    # dense fallbacks for arithmetic (one-time warning per op) ----------
    def _fb(self, opname, fn, *others):
        _fallback_warn(opname)
        args = [o._dense() if isinstance(o, BaseSparseNDArray) else o
                for o in others]
        return fn(self._dense(), *args)

    def __add__(self, other):
        if isinstance(other, BaseSparseNDArray) and other.stype == self.stype:
            return elemwise_add(self, other)
        return self._fb("add", lambda a, b: a + b, other)

    def __sub__(self, other):
        if isinstance(other, BaseSparseNDArray) and other.stype == self.stype:
            return elemwise_sub(self, other)
        return self._fb("sub", lambda a, b: a - b, other)

    def __mul__(self, other):
        if isinstance(other, (int, float)):
            return self.__class__(self._data * other, dict(self._aux),
                                  self._sparse_shape)
        if isinstance(other, BaseSparseNDArray) and other.stype == self.stype:
            return elemwise_mul(self, other)
        return self._fb("mul", lambda a, b: a * b, other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        if isinstance(other, (int, float)):
            return self.__class__(self._data / other, dict(self._aux),
                                  self._sparse_shape)
        return self._fb("div", lambda a, b: a / b, other)

    def __repr__(self):
        return "\n<%s %s @%s>" % (
            type(self).__name__,
            "x".join(str(d) for d in self._sparse_shape), self.context)

    def check_format(self, full_check=True):
        raise NotImplementedError


class RowSparseNDArray(BaseSparseNDArray):
    """``row_sparse``: a subset of rows stored densely.

    ``indices``: sorted unique int32 row ids (int32 is the TPU-native index
    dtype; the reference uses int64), shape ``(nnz_rows,)``;
    ``data``: shape ``(nnz_rows,) + shape[1:]``.  The storage type used by
    the reference for sparse gradients (Embedding ``sparse_grad``) and
    kvstore ``row_sparse_pull``.
    """

    @property
    def stype(self):
        return "row_sparse"

    def _to_dense_jax(self):
        jnp = _jnp()
        dense = jnp.zeros(self._sparse_shape, dtype=self._data.dtype)
        if self._data.shape[0] == 0:
            return dense
        return dense.at[self._aux["indices"]].set(self._data)

    def retain(self, row_ids):
        return retain(self, row_ids)

    def check_format(self, full_check=True):
        idx = _np.asarray(self._aux["indices"])
        if idx.ndim != 1:
            raise MXNetError("row_sparse indices must be 1-D")
        if idx.size and ((idx[1:] <= idx[:-1]).any() or idx[0] < 0
                         or idx[-1] >= self._sparse_shape[0]):
            raise MXNetError("row_sparse indices must be sorted, unique and "
                             "within [0, num_rows)")
        if tuple(self._data.shape) != (idx.size,) + self._sparse_shape[1:]:
            raise MXNetError("row_sparse data shape mismatch")

    def __getitem__(self, key):
        if isinstance(key, int):
            return self._dense()[key]
        raise MXNetError("row_sparse only supports integer row indexing")


class CSRNDArray(BaseSparseNDArray):
    """``csr``: compressed sparse row, 2-D only.

    ``data``: nnz values; ``indices``: nnz column ids; ``indptr``: row
    pointer of length ``num_rows + 1``.
    """

    @property
    def stype(self):
        return "csr"

    @property
    def indptr(self) -> NDArray:
        return _wrap(self._aux["indptr"])

    def _to_dense_jax(self):
        jnp = _jnp()
        dense = jnp.zeros(self._sparse_shape, dtype=self._data.dtype)
        nnz = self._data.shape[0]
        if nnz == 0:
            return dense
        rows = _csr_row_of_nnz(self._aux["indptr"], nnz)
        return dense.at[rows, self._aux["indices"]].set(self._data)

    def asscipy(self):
        import scipy.sparse as sps
        return sps.csr_matrix(
            (_np.asarray(self._data), _np.asarray(self._aux["indices"]),
             _np.asarray(self._aux["indptr"])), shape=self._sparse_shape)

    def check_format(self, full_check=True):
        indptr = _np.asarray(self._aux["indptr"])
        idx = _np.asarray(self._aux["indices"])
        if len(self._sparse_shape) != 2:
            raise MXNetError("csr must be 2-D")
        if indptr.shape != (self._sparse_shape[0] + 1,):
            raise MXNetError("csr indptr length must be num_rows+1")
        if indptr[0] != 0 or indptr[-1] != idx.size or \
                (indptr[1:] < indptr[:-1]).any():
            raise MXNetError("csr indptr must be monotone from 0 to nnz")
        if idx.size and (idx.min() < 0 or idx.max() >= self._sparse_shape[1]):
            raise MXNetError("csr indices out of range")

    def __getitem__(self, key):
        if isinstance(key, int):
            key = slice(key, key + 1)
        if isinstance(key, slice):
            start, stop, step = key.indices(self._sparse_shape[0])
            if step != 1:
                raise MXNetError("csr slicing requires step 1")
            indptr = _np.asarray(self._aux["indptr"])
            lo, hi = int(indptr[start]), int(indptr[stop])
            jnp = _jnp()
            new_indptr = jnp.asarray(indptr[start:stop + 1] - indptr[start])
            return CSRNDArray(self._data[lo:hi],
                              {"indices": self._aux["indices"][lo:hi],
                               "indptr": new_indptr},
                              (stop - start, self._sparse_shape[1]))
        raise MXNetError("csr supports int/slice row indexing only")


def _csr_row_of_nnz(indptr, nnz):
    """Row id of each nnz entry (device op: searchsorted over indptr)."""
    jnp = _jnp()
    return jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1


# ---------------------------------------------------------------------------
# constructors
# ---------------------------------------------------------------------------

def _as_jax(x, dtype=None):
    jnp = _jnp()
    if isinstance(x, NDArray):
        x = x._data
    return jnp.asarray(x, dtype=dtype)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a :class:`RowSparseNDArray` from ``(data, indices)``, a dense
    source, or another row_sparse array."""
    if isinstance(arg1, RowSparseNDArray):
        return arg1.copy()
    if isinstance(arg1, (tuple, list)) and len(arg1) == 2 and not \
            _np.isscalar(arg1[0]):
        data = _as_jax(arg1[0], dtype)
        indices = _np.asarray(_as_jax(arg1[1])).astype(_np.int64)
        order = _np.argsort(indices, kind="stable")
        jnp = _jnp()
        if not (indices[:-1] < indices[1:]).all():
            indices = indices[order]
            data = data[jnp.asarray(order)]
        if shape is None:
            nrows = int(indices[-1]) + 1 if indices.size else 0
            shape = (nrows,) + tuple(data.shape[1:])
        return RowSparseNDArray(data, {"indices": jnp.asarray(indices)},
                                shape, ctx=ctx)
    # dense source
    dense = _as_jax(arg1, dtype)
    return cast_storage(_wrap(dense), "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Create a :class:`CSRNDArray` from ``(data, indices, indptr)``, a
    dense 2-D source, a scipy.sparse matrix, or ``(data, (row, col))``."""
    jnp = _jnp()
    try:
        import scipy.sparse as sps
        if sps.issparse(arg1):
            m = arg1.tocsr()
            return CSRNDArray(jnp.asarray(m.data, dtype=dtype),
                              {"indices": jnp.asarray(m.indices, dtype=jnp.int32),
                               "indptr": jnp.asarray(m.indptr, dtype=jnp.int32)},
                              m.shape, ctx=ctx)
    except ImportError:
        pass
    if isinstance(arg1, (tuple, list)) and len(arg1) == 3:
        data, indices, indptr = arg1
        indptr = _as_jax(indptr).astype(jnp.int32)
        if shape is None:
            ncols = int(_np.asarray(indices).max()) + 1 if len(indices) else 0
            shape = (int(indptr.shape[0]) - 1, ncols)
        return CSRNDArray(_as_jax(data, dtype),
                          {"indices": _as_jax(indices).astype(jnp.int32),
                           "indptr": indptr}, shape, ctx=ctx)
    if isinstance(arg1, (tuple, list)) and len(arg1) == 2 and \
            isinstance(arg1[1], (tuple, list)):
        import scipy.sparse as sps
        data, (row, col) = arg1
        m = sps.csr_matrix((_np.asarray(data), (_np.asarray(row),
                                                _np.asarray(col))),
                           shape=shape)
        return csr_matrix(m, ctx=ctx, dtype=dtype)
    return cast_storage(_wrap(_as_jax(arg1, dtype)), "csr")


def array(source_array, ctx=None, dtype=None):
    """Sparse-aware ``array``: scipy matrices → csr, sparse NDArrays copy."""
    if isinstance(source_array, BaseSparseNDArray):
        return source_array.copy()
    try:
        import scipy.sparse as sps
        if sps.issparse(source_array):
            return csr_matrix(source_array, ctx=ctx, dtype=dtype)
    except ImportError:
        pass
    raise MXNetError("sparse.array expects a sparse source; use nd.array "
                     "for dense")


def zeros(stype, shape, ctx=None, dtype=None):
    jnp = _jnp()
    dtype = dtype or "float32"
    if isinstance(shape, int):
        shape = (shape,)
    if stype == "row_sparse":
        data = jnp.zeros((0,) + tuple(shape[1:]), dtype=dtype)
        return RowSparseNDArray(data,
                                {"indices": jnp.zeros((0,), jnp.int32)},
                                shape, ctx=ctx)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dtype=dtype),
                          {"indices": jnp.zeros((0,), jnp.int32),
                           "indptr": jnp.zeros((shape[0] + 1,), jnp.int32)},
                          shape, ctx=ctx)
    if stype == "default":
        from . import ndarray as _nd
        return _nd.zeros(shape, ctx=ctx, dtype=dtype)
    raise MXNetError("unknown stype %r" % (stype,))


def empty(stype, shape, ctx=None, dtype=None):
    return zeros(stype, shape, ctx=ctx, dtype=dtype)


# ---------------------------------------------------------------------------
# storage casts (reference: cast_storage op, src/operator/tensor/cast_storage*)
# ---------------------------------------------------------------------------

def cast_storage(arr, stype):
    jnp = _jnp()
    cur = arr.stype
    if cur == stype:
        return arr.copy() if isinstance(arr, BaseSparseNDArray) else arr
    if stype == "default":
        return arr.todense()
    if cur != "default":
        return cast_storage(arr.todense(), stype)
    if stype == "row_sparse":
        # row mask reduces on device; only the (nrows,) bool vector
        # crosses to host, the row gather stays on device
        d = arr._data
        mask = jnp.any(d != 0, axis=tuple(range(1, d.ndim))) \
            if d.ndim > 1 else d != 0
        nz = _np.nonzero(_np.asarray(mask))[0]
        data = d[jnp.asarray(nz)]
        return RowSparseNDArray(data,
                                {"indices": jnp.asarray(nz, jnp.int32)},
                                tuple(d.shape))
    dense_np = arr.asnumpy()
    if stype == "csr":
        if dense_np.ndim != 2:
            raise MXNetError("csr requires 2-D")
        import scipy.sparse as sps
        return csr_matrix(sps.csr_matrix(dense_np))
    raise MXNetError("unknown stype %r" % (stype,))


# ---------------------------------------------------------------------------
# sparse kernels
# ---------------------------------------------------------------------------

def retain(rsp, indices):
    """Keep only the rows of ``rsp`` whose ids appear in ``indices``
    (reference: ``_retain`` — the kvstore row_sparse_pull primitive)."""
    if not isinstance(rsp, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    jnp = _jnp()
    want = _np.unique(_np.asarray(_as_jax(indices)).astype(_np.int64))
    have = _np.asarray(rsp._aux["indices"])
    mask = _np.isin(have, want)
    pos = _np.nonzero(mask)[0]
    data = rsp._data[jnp.asarray(pos)] if pos.size else \
        rsp._data[:0]
    return RowSparseNDArray(data, {"indices": jnp.asarray(have[pos])},
                            rsp.shape)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse matrix product.  TPU fast paths:

    * ``dot(csr, dense)`` → dense: gather rhs rows by col id, multiply by
      values, ``segment_sum`` by row id — all on device.
    * ``dot(csr.T, dense)`` → row_sparse: ``segment_sum`` by col id; the
      output keeps only columns that appear in the csr structure.

    Anything else falls back to dense matmul with a warning.
    """
    import jax
    jnp = _jnp()
    if isinstance(lhs, CSRNDArray) and not isinstance(rhs, BaseSparseNDArray):
        if transpose_b:
            raise MXNetError("dot(csr, dense, transpose_b=True) unsupported")
        rd = rhs._data if isinstance(rhs, NDArray) else jnp.asarray(rhs)
        vec_rhs = rd.ndim == 1
        if vec_rhs:
            rd = rd[:, None]
        vals, cols = lhs._data, lhs._aux["indices"]
        nnz = vals.shape[0]
        nrows, ncols = lhs.shape
        if nnz == 0:
            tail = () if vec_rhs else tuple(rd.shape[1:])
            if transpose_a:
                return zeros("row_sparse", (ncols,) + tail,
                             dtype=vals.dtype)
            return _wrap(jnp.zeros((nrows,) + tail, dtype=vals.dtype))
        rows = _csr_row_of_nnz(lhs._aux["indptr"], nnz)
        if not transpose_a:
            prod = vals[:, None] * rd[cols]
            out = jax.ops.segment_sum(prod, rows, num_segments=nrows)
            return _wrap(out[:, 0] if vec_rhs else out)
        # csr.T @ dense → row_sparse over the csr's column ids
        prod = vals[:, None] * rd[rows]
        out = jax.ops.segment_sum(prod, cols, num_segments=ncols)
        nz_cols = _np.unique(_np.asarray(cols))
        data = out[jnp.asarray(nz_cols)]
        if vec_rhs:
            data = data[:, 0]
        return RowSparseNDArray(data,
                                {"indices": jnp.asarray(nz_cols, jnp.int32)},
                                (ncols,) + tuple(rd.shape[1:])
                                if not vec_rhs else (ncols,))
    _fallback_warn("dot")
    ld = lhs._dense() if isinstance(lhs, BaseSparseNDArray) else lhs
    rd = rhs._dense() if isinstance(rhs, BaseSparseNDArray) else rhs
    a = ld._data.T if transpose_a else ld._data
    b = rd._data.T if transpose_b else rd._data
    return _wrap(jnp.matmul(a, b))


def _merge_rowsparse(arrs):
    """Union-merge row_sparse arrays: concat + host-unique + segment_sum."""
    import jax
    jnp = _jnp()
    shape = arrs[0].shape
    all_idx = _np.concatenate([_np.asarray(a._aux["indices"]) for a in arrs])
    if all_idx.size == 0:
        return zeros("row_sparse", shape, dtype=str(arrs[0].dtype))
    uniq, inverse = _np.unique(all_idx, return_inverse=True)
    vals = jnp.concatenate([a._data for a in arrs], axis=0)
    merged = jax.ops.segment_sum(vals, jnp.asarray(inverse),
                                 num_segments=uniq.size)
    return RowSparseNDArray(merged, {"indices": jnp.asarray(uniq, jnp.int32)},
                            shape)


def add_n(*arrs):
    """Sum of arrays; all-row_sparse stays row_sparse (the gradient
    aggregation path for sparse grads)."""
    arrs = list(arrs[0]) if len(arrs) == 1 and isinstance(arrs[0], (list, tuple)) \
        else list(arrs)
    if all(isinstance(a, RowSparseNDArray) for a in arrs):
        return _merge_rowsparse(arrs)
    _fallback_warn("add_n")
    jnp = _jnp()
    out = None
    for a in arrs:
        d = a._dense()._data if isinstance(a, BaseSparseNDArray) else a._data
        out = d if out is None else out + d
    return _wrap(out)


def elemwise_add(lhs, rhs):
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        return _merge_rowsparse([lhs, rhs])
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        return csr_matrix(lhs.asscipy() + rhs.asscipy())
    _fallback_warn("elemwise_add")
    return _wrap(lhs._dense()._data + rhs._dense()._data)


def elemwise_sub(lhs, rhs):
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        return _merge_rowsparse([lhs, rhs * -1.0])
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        return csr_matrix(lhs.asscipy() - rhs.asscipy())
    _fallback_warn("elemwise_sub")
    return _wrap(lhs._dense()._data - rhs._dense()._data)


def elemwise_mul(lhs, rhs):
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        return csr_matrix(lhs.asscipy().multiply(rhs.asscipy()).tocsr())
    _fallback_warn("elemwise_mul")
    ld = lhs._dense()._data if isinstance(lhs, BaseSparseNDArray) else lhs._data
    rd = rhs._dense()._data if isinstance(rhs, BaseSparseNDArray) else rhs._data
    return _wrap(ld * rd)


# ---------------------------------------------------------------------------
# lazy (row-wise) optimizer updates — reference: sgd_update FComputeEx with
# row_sparse grad + lazy_update=True touches only the grad's rows.
# ---------------------------------------------------------------------------

def _rows_and_grad(grad, rescale_grad, clip_gradient):
    jnp = _jnp()
    rows = grad._aux["indices"]
    g = grad._data * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return rows, g


def _row_view(x, rows):
    """``(values_at_rows, write)`` for global row ids ``rows`` of ``x``.

    ``x`` may be a dense NDArray (direct gather/scatter) or a
    RowSparseNDArray (kvstore keeps server-side weights/states
    row_sparse): its compact block is grown with zero rows for ids not
    yet present, so missing rows read as implicit zeros and updates to
    them materialize — the reference's FComputeEx rsp-weight kernels
    behave the same way."""
    jnp = _jnp()
    if isinstance(x, RowSparseNDArray):
        rows_np = _np.asarray(rows)
        idx_np = _np.asarray(x._aux["indices"])
        union = _np.union1d(idx_np, rows_np)
        if union.shape[0] != idx_np.shape[0]:
            block = jnp.zeros((union.shape[0],) + x._data.shape[1:],
                              x._data.dtype)
            if idx_np.shape[0]:
                block = block.at[
                    jnp.asarray(_np.searchsorted(union, idx_np))].set(
                    x._data)
            x._aux = dict(x._aux,
                          indices=jnp.asarray(union, jnp.int32))
            x._set_data(block)
        else:
            block = x._data
        pos = jnp.asarray(_np.searchsorted(union, rows_np))
    else:
        block = x._data
        pos = rows

    def write(new_vals):
        x._set_data(block.at[pos].set(new_vals))

    return block[pos], write


def sgd_update(weight, grad, out=None, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True, **kw):
    """Row-lazy SGD: only rows present in the row_sparse grad are touched
    (matches reference lazy_update semantics: wd applies to touched rows)."""
    assert isinstance(grad, RowSparseNDArray)
    if out is not None and out is not weight:
        raise MXNetError("lazy sparse updates write in place (out=weight)")
    rows, g = _rows_and_grad(grad, rescale_grad, clip_gradient)
    wr, write_w = _row_view(weight, rows)
    write_w(wr - lr * (g + wd * wr))
    return weight


def sgd_mom_update(weight, grad, mom, out=None, lr=0.01, momentum=0.0,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   lazy_update=True, **kw):
    assert isinstance(grad, RowSparseNDArray)
    if out is not None and out is not weight:
        raise MXNetError("lazy sparse updates write in place (out=weight)")
    rows, g = _rows_and_grad(grad, rescale_grad, clip_gradient)
    wr, write_w = _row_view(weight, rows)
    mr, write_m = _row_view(mom, rows)
    new_m = momentum * mr - lr * (g + wd * wr)
    write_m(new_m)
    write_w(wr + new_m)
    return weight


def adam_update(weight, grad, mean, var, out=None, lr=0.001, beta1=0.9,
                beta2=0.999, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, lazy_update=True, **kw):
    jnp = _jnp()
    assert isinstance(grad, RowSparseNDArray)
    if out is not None and out is not weight:
        raise MXNetError("lazy sparse updates write in place (out=weight)")
    rows, g = _rows_and_grad(grad, rescale_grad, clip_gradient)
    wr, write_w = _row_view(weight, rows)
    mr, write_m = _row_view(mean, rows)
    vr, write_v = _row_view(var, rows)
    g = g + wd * wr
    new_m = beta1 * mr + (1 - beta1) * g
    new_v = beta2 * vr + (1 - beta2) * jnp.square(g)
    write_m(new_m)
    write_v(new_v)
    write_w(wr - lr * new_m / (jnp.sqrt(new_v) + epsilon))
    return weight
