"""Symbol naming scopes (reference: ``python/mxnet/name.py``).

``NameManager`` assigns automatic names (``hint%d``) to anonymous
symbols; ``Prefix`` prepends a scope prefix.  Managers nest as context
managers; ``current()`` returns the innermost active one (a default
module-level manager when none is active) — the same contract the
reference's thread-local ``NameManager.current`` provides."""
from __future__ import annotations

import threading
from typing import Optional

__all__ = ["NameManager", "Prefix", "current"]

_STACK = threading.local()


def _stack():
    if not hasattr(_STACK, "v"):
        _STACK.v = []
    return _STACK.v


class NameManager:
    """Automatic ``hint%d`` naming for anonymous symbols."""

    def __init__(self):
        self._counter = {}
        self._old = None

    def get(self, name: Optional[str], hint: str) -> str:
        if name:
            return name
        hint = hint.lower()
        n = self._counter.get(hint, 0)
        self._counter[hint] = n + 1
        return "%s%d" % (hint, n)

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *args):
        _stack().pop()


class Prefix(NameManager):
    """NameManager that prepends ``prefix`` to every auto name
    (reference: ``mx.name.Prefix``)."""

    def __init__(self, prefix: str):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)


_DEFAULT = NameManager()


def current() -> NameManager:
    s = _stack()
    return s[-1] if s else _DEFAULT
