"""Symbol attribute scopes (reference: ``python/mxnet/attribute.py``).

``with AttrScope(ctx_group='dev1'):`` attaches attributes to every
symbol created inside the scope — the reference's mechanism for
``group2ctx`` manual model parallelism (SURVEY.md §2.4 row 3) and for
tagging subgraphs.  Scopes nest; inner scopes override outer keys."""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["AttrScope", "current"]

_STACK = threading.local()


def _stack():
    if not hasattr(_STACK, "v"):
        _STACK.v = []
    return _STACK.v


class AttrScope:
    """Attach attributes to all symbols created within the scope."""

    def __init__(self, **kwargs):
        self._attrs = {k: str(v) for k, v in kwargs.items()}

    def get(self, attr: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        """Merged attrs: every enclosing scope (outer→inner), then the
        explicit ``attr`` dict."""
        out: Dict[str, str] = {}
        for scope in _stack():
            out.update(scope._attrs)
        if attr:
            out.update({k: str(v) for k, v in attr.items()})
        return out

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *args):
        _stack().pop()


_DEFAULT = AttrScope()


def current() -> AttrScope:
    s = _stack()
    return s[-1] if s else _DEFAULT
