"""NumPy-semantics operators (the ``_np_*`` registry namespace).

Reference: ``src/operator/numpy/`` (SURVEY.md §2.1 "Operator library" row,
"numpy/ (mx.np ops)") — the reference implements a parallel op namespace
with NumPy semantics (``_npi_*`` kernels) because classic MXNet ops diverge
from NumPy (reshape shape-codes, axis defaults, comparison dtypes).  Same
split here: classic ops keep MXNet semantics, these keep NumPy's.  Every
impl is a pure JAX function (jnp follows NumPy), so most are one-liners and
autograd/AMP/jit come from the shared registry infrastructure.
"""
from __future__ import annotations

import numpy as _np

from ..ops.registry import register, op_exists as _op_exists


def _j():
    import jax.numpy as jnp
    return jnp


# ------------------------------------------------------------ manipulation --

@register("_np_reshape")
def _np_reshape(a, newshape=None, order="C", **kw):
    return _j().reshape(a, newshape, order=order)


@register("_np_transpose")
def _np_transpose(a, axes=None, **kw):
    return _j().transpose(a, axes)


@register("_np_concatenate", variadic=True)
def _np_concatenate(seq, axis=0, **kw):
    return _j().concatenate(seq, axis=axis)


@register("_np_stack", variadic=True)
def _np_stack(seq, axis=0, **kw):
    return _j().stack(seq, axis=axis)


@register("_np_split", num_outputs=-1)
def _np_split(a, indices_or_sections=None, axis=0, **kw):
    out = _j().split(a, indices_or_sections, axis=axis)
    return tuple(out)


@register("_np_pad")
def _np_pad(a, pad_width=None, mode="constant", constant_values=0, **kw):
    if mode == "constant":
        return _j().pad(a, pad_width, mode=mode,
                        constant_values=constant_values)
    return _j().pad(a, pad_width, mode=mode)


@register("_np_moveaxis")
def _np_moveaxis(a, source=None, destination=None, **kw):
    return _j().moveaxis(a, source, destination)


@register("_np_rollaxis")
def _np_rollaxis(a, axis=0, start=0, **kw):
    return _j().rollaxis(a, axis, start)


@register("_np_roll")
def _np_roll(a, shift=None, axis=None, **kw):
    return _j().roll(a, shift, axis=axis)


@register("_np_rot90")
def _np_rot90(a, k=1, axes=(0, 1), **kw):
    return _j().rot90(a, k=k, axes=tuple(axes))


@register("_np_flip")
def _np_flip(a, axis=None, **kw):
    return _j().flip(a, axis=axis)


@register("_np_trace")
def _np_trace(a, offset=0, axis1=0, axis2=1, **kw):
    return _j().trace(a, offset=offset, axis1=axis1, axis2=axis2)


@register("_np_tril")
def _np_tril(a, k=0, **kw):
    return _j().tril(a, k=k)


@register("_np_triu")
def _np_triu(a, k=0, **kw):
    return _j().triu(a, k=k)


@register("_np_diag")
def _np_diag(a, k=0, **kw):
    return _j().diag(a, k=k)


@register("_np_diagonal")
def _np_diagonal(a, offset=0, axis1=0, axis2=1, **kw):
    return _j().diagonal(a, offset=offset, axis1=axis1, axis2=axis2)


# ------------------------------------------------------------------ linalg --

@register("_np_matmul")
def _np_matmul(a, b, **kw):
    return _j().matmul(a, b)


@register("_np_tensordot")
def _np_tensordot(a, b, axes=2, **kw):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(x) if isinstance(x, (list, tuple)) else x
                     for x in axes)
    return _j().tensordot(a, b, axes=axes)


@register("_np_einsum", variadic=True)
def _np_einsum(operands, subscripts=None, **kw):
    return _j().einsum(subscripts, *operands)


@register("_np_outer")
def _np_outer(a, b, **kw):
    return _j().outer(a, b)


@register("_np_inner")
def _np_inner(a, b, **kw):
    return _j().inner(a, b)


@register("_np_kron")
def _np_kron(a, b, **kw):
    return _j().kron(a, b)


@register("_np_vdot")
def _np_vdot(a, b, **kw):
    return _j().vdot(a, b)


@register("_np_cross")
def _np_cross(a, b, axis=-1, **kw):
    return _j().cross(a, b, axis=axis)


def _linalg(name, fn, num_outputs=1, no_grad=False):
    @register("_np_linalg_" + name, num_outputs=num_outputs, no_grad=no_grad)
    def impl(*arrays, **kw):
        return fn(_j(), *arrays, **{k: v for k, v in kw.items()
                                    if k != "_training"})
    impl.__name__ = "_np_linalg_" + name
    return impl


_linalg("norm", lambda jnp, a, ord=None, axis=None, keepdims=False:
        jnp.linalg.norm(a, ord=ord, axis=axis, keepdims=keepdims))
_linalg("inv", lambda jnp, a: jnp.linalg.inv(a))
_linalg("det", lambda jnp, a: jnp.linalg.det(a))
_linalg("slogdet", lambda jnp, a: tuple(jnp.linalg.slogdet(a)),
        num_outputs=2)
_linalg("cholesky", lambda jnp, a: jnp.linalg.cholesky(a))
_linalg("qr", lambda jnp, a: tuple(jnp.linalg.qr(a)), num_outputs=2)
_linalg("svd", lambda jnp, a: tuple(jnp.linalg.svd(a, full_matrices=False)),
        num_outputs=3)
_linalg("eigh", lambda jnp, a: tuple(jnp.linalg.eigh(a)), num_outputs=2)
_linalg("eigvalsh", lambda jnp, a: jnp.linalg.eigvalsh(a))
_linalg("solve", lambda jnp, a, b: jnp.linalg.solve(a, b))
_linalg("lstsq", lambda jnp, a, b: jnp.linalg.lstsq(a, b)[0])
_linalg("pinv", lambda jnp, a: jnp.linalg.pinv(a))
_linalg("matrix_rank", lambda jnp, a: jnp.linalg.matrix_rank(a),
        no_grad=True)
_linalg("matrix_power", lambda jnp, a, n=1: jnp.linalg.matrix_power(a, n))


# -------------------------------------------------------------- reductions --

def _np_reduce(name, fn, no_grad=False):
    @register("_np_" + name, no_grad=no_grad)
    def impl(a, axis=None, keepdims=False, **kw):
        if isinstance(axis, list):
            axis = tuple(axis)
        return fn(_j(), a, axis, keepdims, kw)
    impl.__name__ = "_np_" + name
    return impl


_np_reduce("sum", lambda jnp, a, ax, kd, kw:
           jnp.sum(a, axis=ax, keepdims=kd, dtype=kw.get("dtype")))
_np_reduce("mean", lambda jnp, a, ax, kd, kw:
           jnp.mean(a, axis=ax, keepdims=kd, dtype=kw.get("dtype")))
_np_reduce("prod", lambda jnp, a, ax, kd, kw:
           jnp.prod(a, axis=ax, keepdims=kd, dtype=kw.get("dtype")))
_np_reduce("max", lambda jnp, a, ax, kd, kw: jnp.max(a, axis=ax, keepdims=kd))
_np_reduce("min", lambda jnp, a, ax, kd, kw: jnp.min(a, axis=ax, keepdims=kd))
_np_reduce("std", lambda jnp, a, ax, kd, kw:
           jnp.std(a, axis=ax, keepdims=kd, ddof=kw.get("ddof", 0)))
_np_reduce("var", lambda jnp, a, ax, kd, kw:
           jnp.var(a, axis=ax, keepdims=kd, ddof=kw.get("ddof", 0)))
_np_reduce("median", lambda jnp, a, ax, kd, kw:
           jnp.median(a, axis=ax, keepdims=kd))
_np_reduce("all", lambda jnp, a, ax, kd, kw:
           jnp.all(a, axis=ax, keepdims=kd), no_grad=True)
_np_reduce("any", lambda jnp, a, ax, kd, kw:
           jnp.any(a, axis=ax, keepdims=kd), no_grad=True)
_np_reduce("nanmean", lambda jnp, a, ax, kd, kw:
           jnp.nanmean(a, axis=ax, keepdims=kd))


@register("_np_average")
def _np_average(a, weights=None, axis=None, **kw):
    jnp = _j()
    if weights is None:
        return jnp.mean(a, axis=axis)
    return jnp.average(a, axis=axis, weights=weights)


@register("_np_cumsum")
def _np_cumsum(a, axis=None, dtype=None, **kw):
    return _j().cumsum(a, axis=axis, dtype=dtype)


@register("_np_cumprod")
def _np_cumprod(a, axis=None, dtype=None, **kw):
    return _j().cumprod(a, axis=axis, dtype=dtype)


@register("_np_ptp", no_grad=True)
def _np_ptp(a, axis=None, keepdims=False, **kw):
    return _j().ptp(a, axis=axis, keepdims=keepdims)


# ---------------------------------------------------------- search / logic --

@register("_np_unique", no_grad=True, num_outputs=1)
def _np_unique(a, **kw):
    # jnp.unique needs static size: fall back to host computation (the
    # reference's np.unique is likewise not a kernel op)
    return _j().asarray(_np.unique(_np.asarray(a)))


@register("_np_nonzero", no_grad=True, num_outputs=-1)
def _np_nonzero(a, **kw):
    return tuple(_j().asarray(ix) for ix in _np.nonzero(_np.asarray(a)))


@register("_np_bincount", no_grad=True)
def _np_bincount(a, minlength=0, **kw):
    return _j().asarray(_np.bincount(_np.asarray(a), minlength=minlength))


@register("_np_searchsorted", no_grad=True)
def _np_searchsorted(a, v, side="left", **kw):
    return _j().searchsorted(a, v, side=side)


@register("_np_where")
def _np_where(cond, x, y, **kw):
    return _j().where(cond, x, y)


@register("_np_meshgrid", variadic=True, num_outputs=-1)
def _np_meshgrid(seq, indexing="xy", **kw):
    return tuple(_j().meshgrid(*seq, indexing=indexing))


@register("_np_isclose", no_grad=True)
def _np_isclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False, **kw):
    return _j().isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


@register("_np_allclose", no_grad=True)
def _np_allclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False, **kw):
    return _j().allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


@register("_np_array_equal", no_grad=True)
def _np_array_equal(a, b, **kw):
    return _j().array_equal(a, b)


# --------------------------------------------------------- missing elemwise --

def _np_binary(name, fn, no_grad=False):
    @register("_np_" + name, no_grad=no_grad)
    def impl(a, b, **kw):
        return fn(_j(), a, b)
    impl.__name__ = "_np_" + name
    return impl


_np_binary("floor_divide", lambda jnp, a, b: jnp.floor_divide(a, b))
_np_binary("fmod", lambda jnp, a, b: jnp.fmod(a, b))
_np_binary("arctan2", lambda jnp, a, b: jnp.arctan2(a, b))
_np_binary("hypot", lambda jnp, a, b: jnp.hypot(a, b))
_np_binary("copysign", lambda jnp, a, b: jnp.copysign(a, b))
_np_binary("logaddexp", lambda jnp, a, b: jnp.logaddexp(a, b))
_np_binary("heaviside", lambda jnp, a, b: jnp.heaviside(a, b))
_np_binary("bitwise_and", lambda jnp, a, b: jnp.bitwise_and(a, b),
           no_grad=True)
_np_binary("bitwise_or", lambda jnp, a, b: jnp.bitwise_or(a, b),
           no_grad=True)
_np_binary("bitwise_xor", lambda jnp, a, b: jnp.bitwise_xor(a, b),
           no_grad=True)
_np_binary("left_shift", lambda jnp, a, b: jnp.left_shift(a, b),
           no_grad=True)
_np_binary("right_shift", lambda jnp, a, b: jnp.right_shift(a, b),
           no_grad=True)


@register("_np_interp", no_grad=True)
def _np_interp(x, xp, fp, **kw):
    return _j().interp(x, xp, fp)


@register("_np_clip")
def _np_clip(a, a_min=None, a_max=None, **kw):
    return _j().clip(a, a_min, a_max)


@register("_np_round")
def _np_round(a, decimals=0, **kw):
    return _j().round(a, decimals=decimals)


@register("_np_nan_to_num")
def _np_nan_to_num(a, nan=0.0, posinf=None, neginf=None, **kw):
    return _j().nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf)


@register("_np_take")
def _np_take(a, indices, axis=None, mode="clip", **kw):
    return _j().take(a, indices, axis=axis, mode=mode)


@register("_np_take_along_axis")
def _np_take_along_axis(a, indices, axis=None, **kw):
    return _j().take_along_axis(a, indices, axis=axis)


@register("_np_repeat")
def _np_repeat(a, repeats=1, axis=None, **kw):
    return _j().repeat(a, repeats, axis=axis)


@register("_np_tile")
def _np_tile(a, reps=None, **kw):
    return _j().tile(a, reps)


@register("_np_broadcast_to")
def _np_broadcast_to(a, shape=None, **kw):
    return _j().broadcast_to(a, tuple(shape))


@register("_np_expand_dims")
def _np_expand_dims(a, axis=0, **kw):
    return _j().expand_dims(a, axis)


@register("_np_squeeze")
def _np_squeeze(a, axis=None, **kw):
    return _j().squeeze(a, axis=axis)


@register("_np_swapaxes")
def _np_swapaxes(a, axis1=0, axis2=1, **kw):
    return _j().swapaxes(a, axis1, axis2)


@register("_np_flatten")
def _np_ravel(a, **kw):
    return _j().ravel(a)


@register("_np_sort")
def _np_sort(a, axis=-1, **kw):
    return _j().sort(a, axis=axis)


@register("_np_argsort", no_grad=True)
def _np_argsort(a, axis=-1, **kw):
    return _j().argsort(a, axis=axis)


@register("_np_gradient", num_outputs=-1)
def _np_gradient(a, axis=None, **kw):
    out = _j().gradient(a, axis=axis)
    return tuple(out) if isinstance(out, (list, tuple)) else out


@register("_np_percentile", no_grad=True)
def _np_percentile(a, q=None, axis=None, keepdims=False, **kw):
    return _j().percentile(a, q, axis=axis, keepdims=keepdims)


@register("_np_quantile", no_grad=True)
def _np_quantile(a, q=None, axis=None, keepdims=False, **kw):
    return _j().quantile(a, q, axis=axis, keepdims=keepdims)


@register("_np_cov")
def _np_cov(m, rowvar=True, bias=False, ddof=None, **kw):
    return _j().cov(m, rowvar=rowvar, bias=bias, ddof=ddof)


@register("_np_histogram", no_grad=True, num_outputs=2)
def _np_histogram(a, bins=10, range=None, **kw):
    return _j().histogram(a, bins=bins, range=range)


@register("_np_column_stack", variadic=True)
def _np_column_stack(seq, **kw):
    return _j().column_stack(seq)


@register("_np_digitize", no_grad=True)
def _np_digitize(x, bins, right=False, **kw):
    return _j().digitize(x, bins, right=right)


@register("_np_diff")
def _np_diff(a, n=1, axis=-1, **kw):
    return _j().diff(a, n=n, axis=axis)


@register("_np_trapz")
def _np_trapz(y, dx=1.0, axis=-1, **kw):
    return _j().trapezoid(y, dx=dx, axis=axis)


@register("_np_ediff1d")
def _np_ediff1d(ary, **kw):
    return _j().ediff1d(ary)


# ---------------------------------------------------------------------------
# Generated long-tail: functions where jnp already implements NumPy
# semantics exactly — registered en masse (reference: the bulk of
# ``src/operator/numpy/*_op.cc`` is the same mechanical fan-out).
# ---------------------------------------------------------------------------

def _reg_jnp(name, jnp_name=None, n_in=1, no_grad=False, num_outputs=1):
    jnp_name = jnp_name or name[len("_np_"):]

    def impl(*args, **kw):
        kw.pop("out", None)
        fn = getattr(_j(), jnp_name)
        return fn(*args, **kw)

    impl.__name__ = name
    impl.__doc__ = ("NumPy-semantics %r (reference: src/operator/numpy/)"
                    % jnp_name)
    register(name, no_grad=no_grad, num_outputs=num_outputs)(impl)


# differentiable unary/binary where jnp == numpy semantics
for _n in ["real", "imag", "conj", "angle", "sinc", "i0", "deg2rad",
           "rad2deg", "positive", "fliplr", "flipud", "fmax", "fmin",
           "float_power", "ldexp", "logaddexp2", "nextafter",
           "nanmax", "nanmin", "nanstd", "nanvar", "ptp",
           "convolve", "correlate", "unwrap", "vander",
           "trace"]:
    if not _op_exists("_np_" + _n):
        _reg_jnp("_np_" + _n)

# integer/boolean-valued (non-differentiable)
for _n in ["signbit", "gcd", "lcm", "nanargmax", "nanargmin",
           "count_nonzero", "isin", "argwhere", "flatnonzero",
           "tri", "indices", "spacing"]:
    if not _op_exists("_np_" + _n):
        _reg_jnp("_np_" + _n, no_grad=True)

# window functions (creation ops: scalar int arg, no array inputs)
for _n in ["bartlett", "blackman", "hamming", "hanning", "kaiser"]:
    if not _op_exists("_np_" + _n):
        _reg_jnp("_np_" + _n, no_grad=True)

# multi-output.  frexp's exponent is int-dtype: recording it would hand
# jax.vjp a non-float cotangent, so it is no_grad.  divmod/modf outputs
# are float for float inputs and stay differentiable (divmod's remainder
# grad matches np.mod; the floor'd quotient contributes zeros).
for _n, _k, _ng in [("frexp", 2, True), ("modf", 2, False),
                    ("divmod", 2, False)]:
    if not _op_exists("_np_" + _n):
        _reg_jnp("_np_" + _n, num_outputs=_k, no_grad=_ng)


@register("_np_polyval")
def _np_polyval(p, x, **kw):
    return _j().polyval(p, x)


@register("_np_in1d", no_grad=True)
def _np_in1d(ar1, ar2, **kw):
    # jnp has no in1d (removed upstream); NumPy defines it as the
    # raveled isin
    return _j().isin(ar1, ar2, **kw).ravel()
