"""``mx.np`` — the NumPy-compatible array API.

Reference: ``python/mxnet/ndarray/numpy/`` + ``python/mxnet/numpy/``
(SURVEY.md §2.2 "NDArray API" row: "``ndarray/numpy/`` (``mx.np``
NumPy-compatible API, ``npx`` extensions)").

TPU-native design: the reference maintains a second kernel namespace
(``_npi_*``) because its classic CPU/GPU kernels bake in MXNet semantics.
Here both APIs share one substrate — ``mx.np.ndarray`` IS an ``NDArray``
subclass (same chunk, same autograd tape, same engine), so classic and
numpy arrays interoperate freely and Gluon blocks accept either.  NumPy
semantics that differ from classic MXNet (reshape codes, axis tuples,
comparison dtypes) live in dedicated ``_np_*`` registry ops
(``_np_ops.py``), which keeps autograd/AMP/hybridize working through the
same single invoke path.
"""
from __future__ import annotations

import numpy as _onp

from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray, _wrap
from ..ops.registry import get_op, invoke
from . import _np_ops  # registers the _np_* ops
from . import random  # noqa: F401
from . import linalg  # noqa: F401

pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
newaxis = None
euler_gamma = _onp.euler_gamma

float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
bool_ = _onp.bool_


class ndarray(NDArray):
    """NumPy-semantics array sharing the NDArray substrate (chunk, tape,
    engine).  Zero-copy converts with classic NDArray via
    ``as_np_ndarray``/``as_nd_ndarray``."""

    __slots__ = ()

    def __repr__(self):
        return repr(self.asnumpy()).replace("array", "array", 1)

    def as_nd_ndarray(self):
        out = NDArray(self._data)
        out._ag = self._ag
        return out

    # numpy-flavored methods -------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return reshape(self, shape)

    def transpose(self, *axes):
        if len(axes) == 0:
            axes = None
        elif len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return transpose(self, axes)

    @property
    def T(self):
        return transpose(self)

    def sum(self, axis=None, keepdims=False, dtype=None):
        return sum(self, axis=axis, keepdims=keepdims, dtype=dtype)

    def mean(self, axis=None, keepdims=False, dtype=None):
        return mean(self, axis=axis, keepdims=keepdims, dtype=dtype)

    def max(self, axis=None, keepdims=False):
        return max(self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return min(self, axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        return prod(self, axis=axis, keepdims=keepdims)

    def std(self, axis=None, keepdims=False, ddof=0):
        return std(self, axis=axis, keepdims=keepdims, ddof=ddof)

    def var(self, axis=None, keepdims=False, ddof=0):
        return var(self, axis=axis, keepdims=keepdims, ddof=ddof)

    def argmax(self, axis=None):
        return argmax(self, axis=axis)

    def argmin(self, axis=None):
        return argmin(self, axis=axis)

    def all(self, axis=None, keepdims=False):
        return all(self, axis=axis, keepdims=keepdims)

    def any(self, axis=None, keepdims=False):
        return any(self, axis=axis, keepdims=keepdims)

    def cumsum(self, axis=None, dtype=None):
        return cumsum(self, axis=axis, dtype=dtype)

    def clip(self, a_min=None, a_max=None):
        return clip(self, a_min, a_max)

    def round(self, decimals=0):
        return round(self, decimals=decimals)

    def squeeze(self, axis=None):
        return squeeze(self, axis=axis)

    def flatten(self):
        return ravel(self)

    def ravel(self):
        return ravel(self)

    def repeat(self, repeats, axis=None):
        return repeat(self, repeats, axis=axis)

    def take(self, indices, axis=None, mode="clip"):
        return take(self, indices, axis=axis, mode=mode)

    def dot(self, other):
        return dot(self, other)

    def item(self, *args):
        return self.asnumpy().item(*args)

    def tolist(self):
        return self.asnumpy().tolist()

    def astype(self, dtype, copy=True):
        out = super().astype(dtype, copy=copy)
        return _as_np(out)

    def copy(self):
        return _as_np(super().copy())


def _as_np(res):
    """Rebrand an invoke result as np ndarray(s) without breaking tape
    identity (same object, class swap — both classes share __slots__)."""
    if isinstance(res, NDArray):
        res.__class__ = ndarray
        return res
    if isinstance(res, (tuple, list)):
        return tuple(_as_np(r) for r in res)
    return res


def _to_input(x):
    if isinstance(x, NDArray):
        return x
    if isinstance(x, (int, float, bool, complex)):
        return x
    return array(x)


def _apply(op_name, *inputs, pos_attrs=(), **attrs):
    ins = [_to_input(i) for i in inputs]
    return _as_np(invoke(get_op(op_name), ins, tuple(pos_attrs), attrs))


def _apply_variadic(op_name, seq, **attrs):
    ins = [_to_input(i) for i in seq]
    return _as_np(invoke(get_op(op_name), ins, (), attrs))


# ------------------------------------------------------------------ creation

def array(object, dtype=None, ctx=None, device=None):
    import jax
    import jax.numpy as jnp
    ctx = ctx or device
    if isinstance(object, NDArray):
        data = object._data
        if dtype is not None:
            data = data.astype(dtype)
        out = ndarray(data)
        return out
    if dtype is None and isinstance(object, (list, tuple, int, float)):
        # numpy default dtype semantics, but float64→float32 (TPU policy,
        # matches the reference's mx.np float32 default)
        arr = _onp.asarray(object)
        if arr.dtype == _onp.float64:
            arr = arr.astype(_onp.float32)
        elif arr.dtype == _onp.int64:
            arr = arr.astype(_onp.int32)
        object = arr
    dev = (ctx or current_context()).jax_device
    with jax.default_device(dev):
        data = jnp.asarray(object, dtype=dtype)
    return ndarray(data)


def _creation(fn):
    def wrapper(*args, ctx=None, device=None, dtype=None, **kw):
        import jax
        import jax.numpy as jnp
        ctx = ctx or device or current_context()
        if dtype is None and fn.__name__ not in ("arange",):
            dtype = "float32"
        with jax.default_device(ctx.jax_device):
            return ndarray(fn(jnp, *args, dtype=dtype, **kw))
    wrapper.__name__ = fn.__name__
    return wrapper


@_creation
def zeros(jnp, shape, dtype=None):
    return jnp.zeros(shape, dtype=dtype)


@_creation
def ones(jnp, shape, dtype=None):
    return jnp.ones(shape, dtype=dtype)


@_creation
def full(jnp, shape, fill_value, dtype=None):
    return jnp.full(shape, fill_value, dtype=dtype)


@_creation
def empty(jnp, shape, dtype=None):
    return jnp.empty(shape, dtype=dtype)


@_creation
def arange(jnp, start, stop=None, step=1, dtype=None):
    return jnp.arange(start, stop, step, dtype=dtype)


@_creation
def linspace(jnp, start, stop, num=50, endpoint=True, dtype=None):
    return jnp.linspace(start, stop, num, endpoint=endpoint, dtype=dtype)


@_creation
def logspace(jnp, start, stop, num=50, endpoint=True, base=10.0, dtype=None):
    return jnp.logspace(start, stop, num, endpoint=endpoint, base=base,
                        dtype=dtype)


@_creation
def eye(jnp, N, M=None, k=0, dtype=None):
    return jnp.eye(N, M, k=k, dtype=dtype)


@_creation
def identity(jnp, n, dtype=None):
    return jnp.identity(n, dtype=dtype)


@_creation
def tri(jnp, N, M=None, k=0, dtype=None):
    return jnp.tri(N, M, k=k, dtype=dtype)


def zeros_like(a, dtype=None):
    return zeros(a.shape, dtype=dtype or a.dtype)


def ones_like(a, dtype=None):
    return ones(a.shape, dtype=dtype or a.dtype)


def full_like(a, fill_value, dtype=None):
    return full(a.shape, fill_value, dtype=dtype or a.dtype)


def empty_like(a, dtype=None):
    return empty(a.shape, dtype=dtype or a.dtype)


def copy(a):
    return array(a).copy() if not isinstance(a, NDArray) else _as_np(a.copy())


def asarray(a, dtype=None):
    if isinstance(a, ndarray) and dtype is None:
        return a
    return array(a, dtype=dtype)


def ascontiguousarray(a, dtype=None):
    return asarray(a, dtype)


def meshgrid(*xi, indexing="xy"):
    return _apply_variadic("_np_meshgrid", xi, indexing=indexing)


# --------------------------------------------------------- elementwise unary

def _unary_fn(np_name, op_name):
    def fn(x, out=None, **kw):
        r = _apply(op_name, x)
        if out is not None:
            if isinstance(r, tuple):
                for o, v in zip(out, r):
                    o[...] = v
            else:
                out[...] = r
            return out
        return r
    fn.__name__ = np_name
    return fn


_UNARY = {
    "negative": "negative", "absolute": "abs", "abs": "abs", "sign": "sign",
    "square": "square", "sqrt": "sqrt", "cbrt": "cbrt", "exp": "exp",
    "expm1": "expm1", "log": "log", "log2": "log2", "log10": "log10",
    "log1p": "log1p", "reciprocal": "reciprocal", "sin": "sin", "cos": "cos",
    "tan": "tan", "arcsin": "arcsin", "arccos": "arccos", "arctan": "arctan",
    "sinh": "sinh", "cosh": "cosh", "tanh": "tanh", "arcsinh": "arcsinh",
    "arccosh": "arccosh", "arctanh": "arctanh", "floor": "floor",
    "ceil": "ceil", "trunc": "trunc", "rint": "rint", "fix": "fix",
    "isnan": "isnan", "isinf": "isinf", "isfinite": "isfinite",
    "logical_not": "logical_not", "relu": "relu", "sigmoid": "sigmoid",
}

for _nm, _op in _UNARY.items():
    globals()[_nm] = _unary_fn(_nm, _op)


# -------------------------------------------------------- elementwise binary

def _binary_fn(np_name, op_name):
    def fn(a, b, out=None, **kw):
        r = _apply(op_name, a, b)
        if out is not None:
            if isinstance(r, tuple):
                for o, v in zip(out, r):
                    o[...] = v
            else:
                out[...] = r
            return out
        return r
    fn.__name__ = np_name
    return fn


_BINARY = {
    "add": "broadcast_add", "subtract": "broadcast_sub",
    "multiply": "broadcast_mul", "divide": "broadcast_div",
    "true_divide": "broadcast_div", "power": "broadcast_power",
    "mod": "broadcast_mod", "remainder": "broadcast_mod",
    "maximum": "broadcast_maximum", "minimum": "broadcast_minimum",
    "equal": "broadcast_equal", "not_equal": "broadcast_not_equal",
    "greater": "broadcast_greater", "less": "broadcast_lesser",
    "greater_equal": "broadcast_greater_equal",
    "less_equal": "broadcast_lesser_equal",
    "logical_and": "broadcast_logical_and",
    "logical_or": "broadcast_logical_or",
    "logical_xor": "broadcast_logical_xor",
    "floor_divide": "_np_floor_divide", "fmod": "_np_fmod",
    "arctan2": "_np_arctan2", "hypot": "_np_hypot",
    "copysign": "_np_copysign", "logaddexp": "_np_logaddexp",
    "heaviside": "_np_heaviside", "bitwise_and": "_np_bitwise_and",
    "bitwise_or": "_np_bitwise_or", "bitwise_xor": "_np_bitwise_xor",
    "left_shift": "_np_left_shift", "right_shift": "_np_right_shift",
}

for _nm, _op in _BINARY.items():
    globals()[_nm] = _binary_fn(_nm, _op)


# --------------------------------------------------------------- reductions

def sum(a, axis=None, keepdims=False, dtype=None, out=None):
    return _apply("_np_sum", a, axis=axis, keepdims=keepdims, dtype=dtype)


def mean(a, axis=None, keepdims=False, dtype=None, out=None):
    return _apply("_np_mean", a, axis=axis, keepdims=keepdims, dtype=dtype)


def prod(a, axis=None, keepdims=False, dtype=None):
    return _apply("_np_prod", a, axis=axis, keepdims=keepdims, dtype=dtype)


def max(a, axis=None, keepdims=False):
    return _apply("_np_max", a, axis=axis, keepdims=keepdims)


def min(a, axis=None, keepdims=False):
    return _apply("_np_min", a, axis=axis, keepdims=keepdims)


amax = max
amin = min


def std(a, axis=None, keepdims=False, ddof=0):
    return _apply("_np_std", a, axis=axis, keepdims=keepdims, ddof=ddof)


def var(a, axis=None, keepdims=False, ddof=0):
    return _apply("_np_var", a, axis=axis, keepdims=keepdims, ddof=ddof)


def median(a, axis=None, keepdims=False):
    return _apply("_np_median", a, axis=axis, keepdims=keepdims)


def average(a, axis=None, weights=None):
    if weights is None:
        return _apply("_np_average", a, axis=axis)
    return _apply("_np_average", a, weights, axis=axis)


def nanmean(a, axis=None, keepdims=False):
    return _apply("_np_nanmean", a, axis=axis, keepdims=keepdims)


def all(a, axis=None, keepdims=False):
    return _apply("_np_all", a, axis=axis, keepdims=keepdims)


def any(a, axis=None, keepdims=False):
    return _apply("_np_any", a, axis=axis, keepdims=keepdims)


def cumsum(a, axis=None, dtype=None):
    return _apply("_np_cumsum", a, axis=axis, dtype=dtype)


def cumprod(a, axis=None, dtype=None):
    return _apply("_np_cumprod", a, axis=axis, dtype=dtype)


def ptp(a, axis=None, keepdims=False):
    return _apply("_np_ptp", a, axis=axis, keepdims=keepdims)


def argmax(a, axis=None):
    return _apply("argmax", a, axis=axis)


def argmin(a, axis=None):
    return _apply("argmin", a, axis=axis)


# ------------------------------------------------------------- manipulation

def reshape(a, newshape, order="C"):
    return _apply("_np_reshape", a, newshape=tuple(newshape)
                  if isinstance(newshape, (tuple, list)) else newshape,
                  order=order)


def transpose(a, axes=None):
    return _apply("_np_transpose", a,
                  axes=tuple(axes) if axes is not None else None)


def concatenate(seq, axis=0):
    return _apply_variadic("_np_concatenate", seq, axis=axis)


def stack(seq, axis=0):
    return _apply_variadic("_np_stack", seq, axis=axis)


def vstack(seq):
    seq = [atleast_2d(s) for s in seq]
    return concatenate(seq, axis=0)


def hstack(seq):
    seq = [asarray(s) for s in seq]
    if seq and seq[0].ndim == 1:
        return concatenate(seq, axis=0)
    return concatenate(seq, axis=1)


def dstack(seq):
    seq = [atleast_3d(s) for s in seq]
    return concatenate(seq, axis=2)


def atleast_1d(a):
    a = asarray(a)
    return a if a.ndim >= 1 else reshape(a, (1,))


def atleast_2d(a):
    a = asarray(a)
    if a.ndim >= 2:
        return a
    if a.ndim == 1:
        return reshape(a, (1,) + a.shape)
    return reshape(a, (1, 1))


def atleast_3d(a):
    a = asarray(a)
    if a.ndim >= 3:
        return a
    if a.ndim == 2:
        return reshape(a, a.shape + (1,))
    if a.ndim == 1:
        return reshape(a, (1,) + a.shape + (1,))
    return reshape(a, (1, 1, 1))


def split(a, indices_or_sections, axis=0):
    res = _apply("_np_split", a, indices_or_sections=indices_or_sections,
                 axis=axis)
    return list(res) if isinstance(res, tuple) else [res]


def array_split(a, n, axis=0):
    sizes = a.shape[axis]
    base, extra = divmod(sizes, n)
    points, acc = [], 0
    for i in range(n - 1):
        acc += base + (1 if i < extra else 0)
        points.append(acc)
    return split(a, points, axis=axis)


def hsplit(a, n):
    return split(a, n, axis=1 if asarray(a).ndim > 1 else 0)


def vsplit(a, n):
    return split(a, n, axis=0)


def expand_dims(a, axis):
    return _apply("_np_expand_dims", a, axis=axis)


def squeeze(a, axis=None):
    return _apply("_np_squeeze", a, axis=axis)


def swapaxes(a, axis1, axis2):
    return _apply("_np_swapaxes", a, axis1=axis1, axis2=axis2)


def moveaxis(a, source, destination):
    return _apply("_np_moveaxis", a, source=source, destination=destination)


def rollaxis(a, axis, start=0):
    return _apply("_np_rollaxis", a, axis=axis, start=start)


def roll(a, shift, axis=None):
    return _apply("_np_roll", a, shift=shift, axis=axis)


def rot90(a, k=1, axes=(0, 1)):
    return _apply("_np_rot90", a, k=k, axes=axes)


def flip(a, axis=None):
    return _apply("_np_flip", a, axis=axis)


def fliplr(a):
    return flip(a, 1)


def flipud(a):
    return flip(a, 0)


def ravel(a):
    return _apply("_np_flatten", a)


def tile(a, reps):
    return _apply("_np_tile", a, reps=reps)


def repeat(a, repeats, axis=None):
    return _apply("_np_repeat", a, repeats=repeats, axis=axis)


def broadcast_to(a, shape):
    return _apply("_np_broadcast_to", a, shape=tuple(shape))


def pad(a, pad_width, mode="constant", constant_values=0):
    return _apply("_np_pad", a, pad_width=pad_width, mode=mode,
                  constant_values=constant_values)


def tril(a, k=0):
    return _apply("_np_tril", a, k=k)


def triu(a, k=0):
    return _apply("_np_triu", a, k=k)


def diag(a, k=0):
    return _apply("_np_diag", a, k=k)


def diagonal(a, offset=0, axis1=0, axis2=1):
    return _apply("_np_diagonal", a, offset=offset, axis1=axis1, axis2=axis2)


def trace(a, offset=0, axis1=0, axis2=1):
    return _apply("_np_trace", a, offset=offset, axis1=axis1, axis2=axis2)


# ------------------------------------------------------------- linear algebra

def dot(a, b):
    return _apply("dot", a, b)


def matmul(a, b):
    return _apply("_np_matmul", a, b)


def tensordot(a, b, axes=2):
    return _apply("_np_tensordot", a, b, axes=axes)


def einsum(subscripts, *operands):
    return _apply_variadic("_np_einsum", operands, subscripts=subscripts)


def outer(a, b):
    return _apply("_np_outer", a, b)


def inner(a, b):
    return _apply("_np_inner", a, b)


def kron(a, b):
    return _apply("_np_kron", a, b)


def vdot(a, b):
    return _apply("_np_vdot", a, b)


def cross(a, b, axis=-1):
    return _apply("_np_cross", a, b, axis=axis)


# ------------------------------------------------------------ search / logic

def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition)
    return _apply("_np_where", condition, x, y)


def nonzero(a):
    return _apply("_np_nonzero", a)


def unique(a):
    return _apply("_np_unique", a)


def bincount(a, minlength=0):
    return _apply("_np_bincount", a, minlength=minlength)


def searchsorted(a, v, side="left"):
    return _apply("_np_searchsorted", a, v, side=side)


def clip(a, a_min=None, a_max=None):
    return _apply("_np_clip", a, a_min=a_min, a_max=a_max)


def round(a, decimals=0):
    return _apply("_np_round", a, decimals=decimals)


around = round


def nan_to_num(a, nan=0.0, posinf=None, neginf=None):
    return _apply("_np_nan_to_num", a, nan=nan, posinf=posinf, neginf=neginf)


def take(a, indices, axis=None, mode="clip"):
    return _apply("_np_take", a, indices, axis=axis, mode=mode)


def take_along_axis(a, indices, axis):
    return _apply("_np_take_along_axis", a, indices, axis=axis)


def sort(a, axis=-1):
    return _apply("_np_sort", a, axis=axis)


def argsort(a, axis=-1):
    return _apply("_np_argsort", a, axis=axis)


def isclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    return _apply("_np_isclose", a, b, rtol=rtol, atol=atol,
                  equal_nan=equal_nan)


def allclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    return bool(_apply("_np_allclose", a, b, rtol=rtol, atol=atol,
                       equal_nan=equal_nan).asnumpy())


def array_equal(a, b):
    return bool(_apply("_np_array_equal", a, b).asnumpy())


def interp(x, xp, fp):
    return _apply("_np_interp", x, xp, fp)


def gradient(f, axis=None):
    return _apply("_np_gradient", f, axis=axis)


def maximum_(a, b):
    return maximum(a, b)  # noqa: F821


def abs_(a):
    return absolute(a)  # noqa: F821


def may_share_memory(a, b):
    return False


def shape(a):
    return asarray(a).shape


def ndim(a):
    return asarray(a).ndim


def size(a):
    return asarray(a).size


def _scalarize_q(q):
    # q may be scalar, list, or (nd)array (numpy semantics); attrs skip
    # the NDArray→jax unwrap, so convert array-likes here
    if hasattr(q, "asnumpy"):
        return q.asnumpy()
    return q


def percentile(a, q, axis=None, keepdims=False):
    return _apply("_np_percentile", a, q=_scalarize_q(q), axis=axis,
                  keepdims=keepdims)


def quantile(a, q, axis=None, keepdims=False):
    return _apply("_np_quantile", a, q=_scalarize_q(q), axis=axis,
                  keepdims=keepdims)


def cov(m, rowvar=True, bias=False, ddof=None):
    return _apply("_np_cov", m, rowvar=rowvar, bias=bias, ddof=ddof)


def histogram(a, bins=10, range=None):
    return _apply("_np_histogram", a, bins=bins, range=range)


def broadcast_arrays(*args):
    import jax.numpy as jnp

    def unwrap(a):
        a = _to_input(a)
        return a._data if isinstance(a, NDArray) else a

    outs = jnp.broadcast_arrays(*[unwrap(a) for a in args])
    return [ndarray(o) for o in outs]


def column_stack(tup):
    return _apply("_np_column_stack", *tup)


def digitize(x, bins, right=False):
    return _apply("_np_digitize", x, bins, right=right)


def diff(a, n=1, axis=-1):
    return _apply("_np_diff", a, n=n, axis=axis)


def trapz(y, dx=1.0, axis=-1):
    return _apply("_np_trapz", y, dx=dx, axis=axis)


def ediff1d(ary):
    return _apply("_np_ediff1d", ary)


# ------------------------------------------------------- generated long-tail

def _gen_np_fn(np_name, n_array_args=1):
    op_name = "_np_" + np_name

    def fn(*args, **kwargs):
        out = kwargs.pop("out", None)
        arrays = args[:n_array_args]
        rest = args[n_array_args:]
        if rest:
            r = _apply(op_name, *arrays, pos_attrs=tuple(rest),
                       **kwargs)
        else:
            r = _apply(op_name, *arrays, **kwargs)
        if out is not None:
            if isinstance(r, tuple):
                for o, v in zip(out, r):
                    o[...] = v
            else:
                out[...] = r
            return out
        return r
    fn.__name__ = np_name
    return fn


for _nm in ["real", "imag", "conj", "angle", "sinc", "i0", "deg2rad",
            "rad2deg", "positive", "fliplr", "flipud", "unwrap",
            "nanmax", "nanmin", "nanstd", "nanvar", "ptp", "signbit",
            "nanargmax", "nanargmin", "count_nonzero", "argwhere",
            "flatnonzero", "vander", "frexp", "modf", "spacing"]:
    if _nm not in globals():
        globals()[_nm] = _gen_np_fn(_nm, 1)

for _nm in ["fmax", "fmin", "float_power", "ldexp", "logaddexp2",
            "nextafter", "gcd", "lcm", "isin", "in1d", "convolve",
            "correlate", "polyval", "divmod"]:
    if _nm not in globals():
        globals()[_nm] = _gen_np_fn(_nm, 2)


def _gen_creation_fn(np_name):
    op_name = "_np_" + np_name

    def fn(*args, **kwargs):
        return _apply(op_name, pos_attrs=tuple(args), **kwargs)
    fn.__name__ = np_name
    return fn


for _nm in ["bartlett", "blackman", "hamming", "hanning", "kaiser",
            "tri", "indices"]:
    if _nm not in globals():
        globals()[_nm] = _gen_creation_fn(_nm)
