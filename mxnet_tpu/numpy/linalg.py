"""``mx.np.linalg`` — NumPy-style linear algebra.

Reference: ``python/mxnet/numpy/linalg.py`` over the ``_npi_*`` linalg
kernels (here: ``_np_linalg_*`` registry ops lowering to
``jax.numpy.linalg``, which XLA maps onto MXU matmuls / host LAPACK).
"""
from __future__ import annotations


def _apply(op, *inputs, **attrs):
    from . import _apply as apply_
    return apply_(op, *inputs, **attrs)


def norm(a, ord=None, axis=None, keepdims=False):
    return _apply("_np_linalg_norm", a, ord=ord, axis=axis,
                  keepdims=keepdims)


def inv(a):
    return _apply("_np_linalg_inv", a)


def det(a):
    return _apply("_np_linalg_det", a)


def slogdet(a):
    return _apply("_np_linalg_slogdet", a)


def cholesky(a):
    return _apply("_np_linalg_cholesky", a)


def qr(a):
    return _apply("_np_linalg_qr", a)


def svd(a):
    return _apply("_np_linalg_svd", a)


def eigh(a):
    return _apply("_np_linalg_eigh", a)


def eigvalsh(a):
    return _apply("_np_linalg_eigvalsh", a)


def solve(a, b):
    return _apply("_np_linalg_solve", a, b)


def lstsq(a, b):
    return _apply("_np_linalg_lstsq", a, b)


def pinv(a):
    return _apply("_np_linalg_pinv", a)


def matrix_rank(a):
    return _apply("_np_linalg_matrix_rank", a)


def matrix_power(a, n):
    return _apply("_np_linalg_matrix_power", a, n=n)
