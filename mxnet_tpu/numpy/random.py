"""``mx.np.random`` — NumPy-style random sampling.

Reference: ``python/mxnet/ndarray/numpy/random.py``.  Delegates to the
registered random ops (which thread explicit PRNG keys through the tape —
see ``mxnet_tpu/random.py``) and rebrands results as np ndarrays.
"""
from __future__ import annotations

import numpy as _onp


def _nd_random():
    from .. import ndarray as _nd

    class _R:
        uniform = staticmethod(_nd.random_uniform)
        normal = staticmethod(_nd.random_normal)
        randint = staticmethod(_nd.random_randint)
        gamma = staticmethod(_nd.random_gamma)
        exponential = staticmethod(_nd.random_exponential)
        poisson = staticmethod(_nd.random_poisson)

        @staticmethod
        def seed(s):
            from .. import random as _r
            _r.seed(s)
    return _R


def _np():
    from . import _as_np, array
    return _as_np, array


def seed(s):
    _nd_random().seed(s)


def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None):
    _as_np, _ = _np()
    return _as_np(_nd_random().uniform(low, high, shape=_shape(size),
                                       dtype=dtype or "float32", ctx=ctx))


def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
    _as_np, _ = _np()
    return _as_np(_nd_random().normal(loc, scale, shape=_shape(size),
                                      dtype=dtype or "float32", ctx=ctx))


def randn(*size):
    return normal(0.0, 1.0, size=size or None)


def rand(*size):
    return uniform(0.0, 1.0, size=size or None)


def randint(low, high=None, size=None, dtype=None, ctx=None):
    _as_np, _ = _np()
    if high is None:
        low, high = 0, low
    return _as_np(_nd_random().randint(low, high, shape=_shape(size),
                                       dtype=dtype or "int32", ctx=ctx))


def choice(a, size=None, replace=True, p=None, ctx=None):
    _as_np, array = _np()
    if isinstance(a, int):
        n = a
    else:
        n = len(a)
    idx = _onp.random.choice(n, size=size, replace=replace,
                             p=None if p is None else _onp.asarray(p))
    if isinstance(a, int):
        return array(idx)
    return array(_onp.asarray(a)[idx])


def shuffle(x):
    """In-place permutation along the first axis (reference:
    ``mx.np.random.shuffle``)."""
    perm = _onp.random.permutation(x.shape[0])
    x[...] = x[perm]


def permutation(n):
    _as_np, array = _np()
    return array(_onp.random.permutation(n))


def gamma(shape_param, scale=1.0, size=None):
    _as_np, _ = _np()
    return _as_np(_nd_random().gamma(alpha=shape_param, beta=scale,
                                     shape=_shape(size)))


def exponential(scale=1.0, size=None):
    _as_np, _ = _np()
    return _as_np(_nd_random().exponential(lam=1.0 / scale,
                                           shape=_shape(size)))


def beta(a, b, size=None):
    """Beta(a, b) via two gammas (XLA has no native beta sampler)."""
    ga = gamma(a, 1.0, size=size)
    gb = gamma(b, 1.0, size=size)
    return ga / (ga + gb)


def poisson(lam=1.0, size=None):
    _as_np, _ = _np()
    return _as_np(_nd_random().poisson(lam, shape=_shape(size)))


def multinomial(n, pvals, size=None):
    _as_np, array = _np()
    return array(_onp.random.multinomial(n, _onp.asarray(pvals), size=size))


def bernoulli(prob, size=None):
    return (uniform(0.0, 1.0, size=size) < prob).astype("float32")


def _shape(size):
    if size is None:
        return ()
    if isinstance(size, int):
        return (size,)
    return tuple(size)
