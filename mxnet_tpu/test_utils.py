"""Testing toolkit — the TPU-native analog of ``python/mxnet/test_utils.py``
(SURVEY.md §2.2 "test_utils" row, §4 "the mechanisms to replicate").

Provides the four correctness oracles the reference's test suite is built on:

* ``assert_almost_equal`` — dtype-aware tolerance compare.
* ``check_numeric_gradient`` — finite-difference gradient vs autograd
  (reference: finite difference vs per-op ``FGradient``).
* ``check_consistency`` — run the same computation on a list of contexts /
  dtypes and cross-compare forward and backward.  In the reference this is
  THE oracle for a second backend (cpu vs gpu); here it is cpu vs tpu.
* ``check_symbolic_forward`` / ``check_symbolic_backward`` — compare a bound
  Symbol executor against NumPy expectations.
"""
from __future__ import annotations

import numpy as np

from .context import Context, cpu, current_context
from . import ndarray as nd
from .ndarray import NDArray
from . import autograd


# Per-dtype default tolerances (reference: test_utils.py's dtype maps).
_DTYPE_RTOL = {
    np.dtype(np.float16): 1e-2,
    np.dtype(np.float32): 1e-4,
    np.dtype(np.float64): 1e-6,
    "bfloat16": 3e-2,
}
_DTYPE_ATOL = {
    np.dtype(np.float16): 1e-2,
    np.dtype(np.float32): 1e-5,
    np.dtype(np.float64): 1e-8,
    "bfloat16": 3e-2,
}


def default_context() -> Context:
    """Context that tests run on (reference: test_utils.default_context)."""
    return current_context()


def _as_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


def _tol_for(a, b, rtol, atol):
    if rtol is not None and atol is not None:
        return rtol, atol
    dts = []
    for x in (a, b):
        name = str(x.dtype)
        dts.append("bfloat16" if name == "bfloat16" else np.dtype(x.dtype))
    r = max(_DTYPE_RTOL.get(d, 1e-5) for d in dts)
    t = max(_DTYPE_ATOL.get(d, 1e-8) for d in dts)
    return (rtol if rtol is not None else r,
            atol if atol is not None else t)


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False) -> bool:
    a, b = _as_numpy(a), _as_numpy(b)
    rtol, atol = _tol_for(a, b, rtol, atol)
    return np.allclose(a.astype(np.float64) if a.dtype != object else a,
                       b.astype(np.float64) if b.dtype != object else b,
                       rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    an, bn = _as_numpy(a), _as_numpy(b)
    rtol, atol = _tol_for(an, bn, rtol, atol)
    if an.shape != bn.shape:
        raise AssertionError("shape mismatch %s=%s vs %s=%s"
                             % (names[0], an.shape, names[1], bn.shape))
    af = an.astype(np.float64)
    bf = bn.astype(np.float64)
    if np.allclose(af, bf, rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    err = np.abs(af - bf)
    denom = np.abs(bf) + atol / max(rtol, 1e-300)
    rel = err / np.maximum(denom, 1e-300)
    idx = np.unravel_index(np.argmax(rel), rel.shape)
    raise AssertionError(
        "Arrays not almost equal (rtol=%g atol=%g): max |%s-%s|=%g, "
        "max rel err %g at %s (%r vs %r)"
        % (rtol, atol, names[0], names[1], err.max(), rel.max(), idx,
           af[idx], bf[idx]))


def same(a, b) -> bool:
    return np.array_equal(_as_numpy(a), _as_numpy(b))


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, dtype=np.float32, ctx=None, scale=1.0):
    data = np.random.uniform(-scale, scale, size=shape).astype(dtype)
    return nd.array(data, ctx=ctx)


def random_arrays(*shapes, dtype=np.float32):
    arrays = [np.random.randn(*s).astype(dtype) if s else
              np.array(np.random.randn(), dtype=dtype) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


# ---------------------------------------------------------------------------
# Numeric-gradient oracle
# ---------------------------------------------------------------------------

def numeric_grad(f, inputs, eps=1e-4):
    """Central-difference gradients of scalar-valued ``f(*numpy_arrays)``."""
    grads = []
    for i, x in enumerate(inputs):
        g = np.zeros_like(x, dtype=np.float64)
        flat = x.reshape(-1)
        gflat = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = float(f(*inputs))
            flat[j] = orig - eps
            fm = float(f(*inputs))
            flat[j] = orig
            gflat[j] = (fp - fm) / (2 * eps)
        grads.append(g)
    return grads


def check_numeric_gradient(fn, inputs, eps=1e-4, rtol=1e-2, atol=1e-4,
                           dtype=np.float64):
    """Compare autograd gradients of ``fn`` against central finite
    differences (reference: ``check_numeric_gradient`` — finite difference
    vs ``FGradient``; SURVEY.md §4.1).

    ``fn`` maps NDArrays → a single NDArray; its sum is used as the scalar
    objective.  ``inputs`` are numpy arrays (float64 recommended).

    Runs under ``jax.experimental.enable_x64`` so the finite differences are
    true float64 — without it XLA silently downcasts and the central
    difference loses half its digits.
    """
    from jax.experimental import enable_x64
    with enable_x64(True):
        return _check_numeric_gradient_x64(fn, inputs, eps, rtol, atol,
                                           dtype)


def _check_numeric_gradient_x64(fn, inputs, eps, rtol, atol, dtype):
    np_inputs = [np.asarray(x, dtype=dtype) for x in inputs]

    nd_inputs = [nd.array(x, dtype=dtype) for x in np_inputs]
    for a in nd_inputs:
        a.attach_grad()
    with autograd.record():
        out = fn(*nd_inputs)
        loss = out.sum() if hasattr(out, "sum") else sum(o.sum() for o in out)
    loss.backward()
    ad_grads = [a.grad.asnumpy() for a in nd_inputs]

    def scalar_f(*xs):
        outs = fn(*[nd.array(x, dtype=dtype) for x in xs])
        if isinstance(outs, (tuple, list)):
            return sum(float(o.sum().asnumpy()) for o in outs)
        return float(outs.sum().asnumpy())

    num_grads = numeric_grad(scalar_f, [x.copy() for x in np_inputs], eps=eps)

    for i, (ag, ng) in enumerate(zip(ad_grads, num_grads)):
        assert_almost_equal(ag, ng, rtol=rtol, atol=atol,
                            names=("autograd[%d]" % i, "numeric[%d]" % i))
    return ad_grads, num_grads


# ---------------------------------------------------------------------------
# Cross-context consistency oracle (cpu vs tpu)
# ---------------------------------------------------------------------------

def check_consistency(fn, inputs, ctx_list=None, dtypes=None, grad=True,
                      rtol=None, atol=None):
    """Run ``fn`` on every context (and dtype) and cross-compare forward
    outputs and input gradients (reference: ``check_consistency`` in
    test_utils.py — THE second-backend oracle, SURVEY.md §4.2).

    Parameters
    ----------
    fn : callable(NDArray...) -> NDArray.
    inputs : list of numpy arrays.
    ctx_list : contexts to compare (default: [cpu()] + tpu if available).
    dtypes : dtype per run (default float32 for each ctx).
    """
    if ctx_list is None:
        ctx_list = [cpu()]
        try:
            from .context import tpu, num_tpus
            if num_tpus() > 0:
                ctx_list.append(tpu())
        except Exception:
            pass
    if dtypes is None:
        dtypes = [np.float32] * len(ctx_list)

    runs = []
    for ctx, dt in zip(ctx_list, dtypes):
        # integer/bool inputs (indices, masks) keep their own dtype —
        # casting them to the comparison float dtype would feed ops
        # garbage indices; only float inputs follow the dtype matrix
        nd_in = []
        is_float = []
        for x in inputs:
            xa = x.asnumpy() if hasattr(x, "asnumpy") else np.asarray(x)
            f = np.issubdtype(xa.dtype, np.floating)
            is_float.append(f)
            nd_in.append(nd.array(xa, dtype=dt if f else xa.dtype,
                                  ctx=ctx))
        if grad:
            for a, f in zip(nd_in, is_float):
                if f:                      # grads only flow to floats
                    a.attach_grad()
            with autograd.record():
                out = fn(*nd_in)
            out.backward(nd.ones_like(out))
            runs.append((dt, out.asnumpy(),
                         [a.grad.asnumpy() if f else None
                          for a, f in zip(nd_in, is_float)]))
        else:
            out = fn(*nd_in)
            runs.append((dt, out.asnumpy(), None))

    ref_dt, ref_out, ref_grads = runs[0]
    for (dt, out, grads), ctx in list(zip(runs, ctx_list))[1:]:
        r, t = _tol_for(np.asarray(out, dtype=None), ref_out, rtol, atol)
        assert_almost_equal(out, ref_out, rtol=r, atol=t,
                            names=("fwd@%s" % ctx, "fwd@%s" % ctx_list[0]))
        if grad:
            for i, (g, rg) in enumerate(zip(grads, ref_grads)):
                if g is None or rg is None:
                    continue
                assert_almost_equal(
                    g, rg, rtol=r, atol=t,
                    names=("grad%d@%s" % (i, ctx),
                           "grad%d@%s" % (i, ctx_list[0])))
    return runs


# ---------------------------------------------------------------------------
# Symbolic oracles (Symbol/Module API)
# ---------------------------------------------------------------------------

def check_symbolic_forward(sym, inputs, expected, rtol=1e-4, atol=1e-5,
                           ctx=None, aux_states=None):
    """Bind ``sym`` with ``inputs`` (list of numpy arrays, in argument
    order) and compare outputs against ``expected`` numpy arrays."""
    from . import symbol as _sym  # local: symbol layers on test_utils-free core
    args = {k: nd.array(np.asarray(v))
            for k, v in zip(sym.list_arguments(), inputs)}
    exe = sym._bind(ctx or default_context(), args,
                    aux_states=aux_states)
    outs = exe.forward(is_train=False)
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    for i, (o, e) in enumerate(zip(outs, expected)):
        assert_almost_equal(o, e, rtol=rtol, atol=atol,
                            names=("out%d" % i, "expected%d" % i))
    return outs


def check_symbolic_backward(sym, inputs, out_grads, expected_grads,
                            rtol=1e-4, atol=1e-5, ctx=None):
    """Bind ``sym``, run forward+backward with ``out_grads`` and compare the
    argument gradients against ``expected_grads``."""
    ctx = ctx or default_context()
    arg_names = sym.list_arguments()
    args = {k: nd.array(np.asarray(v))
            for k, v in zip(arg_names, inputs)}
    grad_arrays = {k: nd.zeros_like(v) for k, v in args.items()}
    exe = sym._bind(ctx, args, args_grad=grad_arrays, grad_req="write")
    exe.forward(is_train=True)
    exe.backward([nd.array(np.asarray(g)) for g in (
        out_grads if isinstance(out_grads, (list, tuple)) else [out_grads])])
    if isinstance(expected_grads, dict):
        items = expected_grads.items()
    else:
        items = zip(arg_names, expected_grads)
    for k, e in items:
        assert_almost_equal(grad_arrays[k], e, rtol=rtol, atol=atol,
                            names=("grad[%s]" % k, "expected[%s]" % k))
    return grad_arrays
