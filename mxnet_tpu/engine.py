"""Execution engine — async scheduling semantics on top of XLA/PjRt.

Reference: ``src/engine/`` (``ThreadedEngine``, ``NaiveEngine``,
``Engine::PushAsync/WaitForVar/WaitForAll`` — SURVEY.md §2.1 "Engine",
§3.1 call stack, and the ``note_engine.md`` design doc).

TPU-native design: the reference needed a user-space dataflow scheduler
because CUDA exposes raw streams.  PjRt already gives us an asynchronous,
dependency-ordered execution stream per device: every op dispatched through
JAX returns immediately with a future-like ``jax.Array``; data dependencies
are tracked by XLA/PjRt itself and transfers/computation overlap
automatically.  So the *mechanism* (versioned vars, worker threads) dissolves
— but the *semantics* users rely on are preserved here:

* ``NaiveEngine`` debug mode (``MXNET_ENGINE_TYPE=NaiveEngine``): fully
  synchronous execution — every op blocks until complete.  The reference's
  main async-bug-bisection tool (SURVEY.md §5.2).
* ``wait_for_var`` / ``wait_for_all`` sync points with deferred-exception
  rethrow (reference: exceptions stored on vars, rethrown at sync —
  ``tests/python/unittest/test_exc_handling.py``).
* ``bulk`` scope: hint that a sequence of imperative ops may be batched
  (reference: ``MXNET_EXEC_BULK_EXEC_*`` op bulking; here it is a no-op hint
  because XLA fuses inside ``jit`` — kept for API parity).
* op-start/op-end hooks used by the profiler (the engine is the single
  choke point for tracing in the reference; we keep that property).
"""
from __future__ import annotations

import contextlib
import os
import threading
import weakref
from typing import Any, Callable, List, Optional

__all__ = ["Engine", "engine", "bulk", "set_bulk_size"]


class _PendingException:
    """Deferred exception captured from an async op, rethrown at sync."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class Engine:
    """Process-global engine facade.

    ``push`` runs ``fn`` (a closure that issues JAX ops) and returns its
    result.  In the default (threaded/async) mode the JAX dispatch itself is
    the async boundary.  In NaiveEngine mode we block on every output.
    """

    _instance: Optional["Engine"] = None
    _lock = threading.Lock()

    def __init__(self):
        import collections
        etype = os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
        self.engine_type = etype
        self.synchronous = etype == "NaiveEngine"
        self._op_hooks: List[Callable[[str, str], None]] = []  # (event, name)
        self._bulk_size = 15
        # ring of weakrefs to recent op outputs; wait_for_all blocks on
        # them so it is a true sync point (benchmarks, deferred errors)
        self._recent = collections.deque(maxlen=512)

    @classmethod
    def get(cls) -> "Engine":
        with cls._lock:
            if cls._instance is None:
                cls._instance = Engine()
            return cls._instance

    # -- hooks (profiler attaches here; single choke point) ----------------
    def add_op_hook(self, hook: Callable[[str, str], None]):
        self._op_hooks.append(hook)

    def remove_op_hook(self, hook):
        if hook in self._op_hooks:
            self._op_hooks.remove(hook)

    def notify(self, event: str, name: str):
        for h in self._op_hooks:
            h(event, name)

    # -- execution ---------------------------------------------------------
    def push(self, fn: Callable[[], Any], name: str = "op") -> Any:
        """Run an op closure; sync immediately under NaiveEngine."""
        if self._op_hooks:
            self.notify("start", name)
        try:
            result = fn()
        finally:
            if self._op_hooks:
                self.notify("stop", name)
        if self.synchronous:
            _block(result)
        else:
            self.note(result)
        return result

    def note(self, result):
        """Record op outputs in the recent ring without the push() hook
        machinery — the invoke fast lane calls this so ``wait_for_all``
        stays a true sync point.  Walks the full pytree so nested
        structures (a tuple holding a list of arrays) don't escape."""
        from jax.tree_util import tree_leaves
        for leaf in tree_leaves(result):
            if hasattr(leaf, "block_until_ready"):
                try:
                    self._recent.append(weakref.ref(leaf))
                except TypeError:
                    pass

    def wait_for_all(self):
        """Block until all outstanding device work completes; deferred
        device errors surface here.

        Reference: ``Engine::WaitForAll`` / ``mx.nd.waitall()``.  PjRt has
        no global barrier from Python, so we block on every recently
        dispatched output (weakref ring) + the effects barrier.
        """
        import jax
        try:
            jax.effects_barrier()
        except Exception:
            pass
        while self._recent:
            ref = self._recent.popleft()
            arr = ref()
            if arr is not None:
                arr.block_until_ready()

    @staticmethod
    def wait_for_var(data):
        """Block until ``data`` (a jax.Array / pytree) is ready; rethrows any
        deferred device exception (reference: ``Engine::WaitForVar``)."""
        _block(data)

    def set_bulk_size(self, size: int) -> int:
        old = self._bulk_size
        self._bulk_size = size
        return old


def _block(result):
    import jax
    for leaf in jax.tree_util.tree_leaves(result):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()


def engine() -> Engine:
    return Engine.get()


@contextlib.contextmanager
def bulk(size: int = 15):
    """Bulk-execution scope (reference: ``mx.engine.bulk``).

    Under XLA the fusion happens in the compiler, so this is a semantic
    no-op kept for API parity; it still toggles the engine bulk-size knob so
    user code reading it back behaves identically.
    """
    eng = Engine.get()
    old = eng.set_bulk_size(size)
    try:
        yield
    finally:
        eng.set_bulk_size(old)


def set_bulk_size(size: int) -> int:
    return Engine.get().set_bulk_size(size)
