"""mxnet_tpu — a TPU-native deep-learning framework with MXNet's API.

An imperative, asynchronously-scheduled mutable NDArray API, Gluon
(``Block``/``HybridBlock`` with ``hybridize()`` compiling to a single XLA
computation), autograd, the Symbol/Module API with a bucketing executor, a
RecordIO data pipeline and a KVStore data-parallel interface — with XLA/PjRt
as the execution substrate instead of mshadow/CUDA.  See SURVEY.md for the
reference blueprint.

Usage mirrors the reference::

    import mxnet_tpu as mx
    x = mx.nd.zeros((2, 3), ctx=mx.tpu())
"""
__version__ = "0.1.0"

from .base import MXNetError
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, \
    num_gpus, num_tpus
from . import engine
from . import random
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import autograd

# Submodules that layer on the core.  This list grows as subsystems land;
# the package stays importable at every commit.
from . import initializer      # noqa: E402
from . import optimizer        # noqa: E402
from . import lr_scheduler     # noqa: E402
from . import metric           # noqa: E402
from . import kvstore          # noqa: E402
from . import kvstore as kv    # noqa: E402
from . import recordio         # noqa: E402
from . import io               # noqa: E402
from . import image            # noqa: E402
from . import gluon            # noqa: E402
from . import parallel         # noqa: E402
from . import models           # noqa: E402
from . import symbol           # noqa: E402
from . import symbol as sym    # noqa: E402
from . import callback         # noqa: E402
from . import model            # noqa: E402
from . import module           # noqa: E402
from . import module as mod    # noqa: E402
from . import contrib          # noqa: E402
from . import operator         # noqa: E402
from . import name             # noqa: E402
from . import attribute       # noqa: E402
from .attribute import AttrScope  # noqa: E402
from . import visualization    # noqa: E402
from . import visualization as viz  # noqa: E402
from . import util             # noqa: E402
from . import numpy as np      # noqa: E402
from . import numpy_extension as npx  # noqa: E402
from . import profiler         # noqa: E402
from . import obs              # noqa: E402
from . import runtime          # noqa: E402
from . import library          # noqa: E402
from . import rtc              # noqa: E402
from . import monitor          # noqa: E402
from .monitor import Monitor   # noqa: E402
from . import test_utils       # noqa: E402
