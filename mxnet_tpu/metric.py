"""Evaluation metrics.

Reference: ``python/mxnet/metric.py`` (SURVEY.md §2.2 / §5.5 — the
Accuracy/TopK/F1/Perplexity parity metrics named in BASELINE.json).
Computation happens on host numpy after an explicit sync, matching the
reference (metric.update forces a wait on outputs).
"""
from __future__ import annotations

import math
import numpy as _np

from .base import Registry, MXNetError

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MCC", "MAE",
           "MSE", "RMSE", "CrossEntropy", "NegativeLogLikelihood",
           "Perplexity", "PearsonCorrelation", "Loss",
           "CompositeEvalMetric", "CustomMetric", "create", "np"]

_REG = Registry("metric")
register = _REG.register


def _as_numpy(x):
    from .ndarray.ndarray import NDArray
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    if isinstance(labels, (list, tuple)) and isinstance(preds, (list, tuple)):
        if len(labels) != len(preds):
            raise MXNetError("labels and predictions have different lengths")
    if wrap:
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
    return labels, preds


class EvalMetric:
    """Base metric (reference: ``mxnet.metric.EvalMetric``)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0

    def reset_local(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_global(self):
        if self.global_num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.global_sum_metric / self.global_num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def _add(self, metric, inst):
        self.sum_metric += metric
        self.num_inst += inst
        self.global_sum_metric += metric
        self.global_num_inst += inst

    def __str__(self):
        return "EvalMetric: %s" % dict(self.get_name_value())


@register("acc", aliases=["accuracy"])
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype("int32").reshape(-1)
            label = label.astype("int32").reshape(-1)
            n = min(len(label), len(pred))
            self._add(float((pred[:n] == label[:n]).sum()), n)


@register("top_k_accuracy", aliases=["top_k_acc"])
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.top_k = top_k
        assert self.top_k > 1, "Use Accuracy if top_k is 1"
        self.name += "_%d" % top_k

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype("int32")
            assert pred.ndim == 2, "Predictions should be 2 dims"
            idx = _np.argsort(pred, axis=1)[:, -self.top_k:]
            n = pred.shape[0]
            correct = (idx == label.reshape(-1, 1)).any(axis=1).sum()
            self._add(float(correct), n)


class _BinaryClassificationHelper:
    def __init__(self):
        self.reset_stats()

    def reset_stats(self):
        self.tp = self.fp = self.tn = self.fn = 0

    def update(self, label, pred):
        pred = _as_numpy(pred)
        label = _as_numpy(label).astype("int32").reshape(-1)
        if pred.ndim > 1 and pred.shape[-1] > 1:
            pred_label = pred.argmax(axis=-1).reshape(-1)
        else:
            pred_label = (pred.reshape(-1) > 0.5).astype("int32")
        if label.max() > 1:
            raise MXNetError("F1/MCC currently only supports binary "
                             "classification.")
        self.tp += int(((pred_label == 1) & (label == 1)).sum())
        self.fp += int(((pred_label == 1) & (label == 0)).sum())
        self.tn += int(((pred_label == 0) & (label == 0)).sum())
        self.fn += int(((pred_label == 0) & (label == 1)).sum())

    @property
    def precision(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0

    @property
    def recall(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0

    @property
    def fscore(self):
        d = self.precision + self.recall
        return 2 * self.precision * self.recall / d if d else 0.0

    @property
    def matthewscc(self):
        terms = [(self.tp + self.fp), (self.tp + self.fn),
                 (self.tn + self.fp), (self.tn + self.fn)]
        denom = 1.0
        for t in terms:
            denom *= t if t else 1.0
        return ((self.tp * self.tn) - (self.fp * self.fn)) / \
            math.sqrt(denom)

    @property
    def total_examples(self):
        return self.tp + self.fp + self.tn + self.fn


@register("f1")
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)
        self.average = average
        self._helper = _BinaryClassificationHelper()

    def reset(self):
        super().reset()
        if hasattr(self, "_helper"):
            self._helper.reset_stats()

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            self._helper.update(label, pred)
        if self.average == "micro":
            self.sum_metric = self._helper.fscore * \
                self._helper.total_examples
            self.num_inst = self._helper.total_examples
        else:
            self.sum_metric = self._helper.fscore
            self.num_inst = 1
        self.global_sum_metric = self.sum_metric
        self.global_num_inst = self.num_inst


@register("mcc")
class MCC(EvalMetric):
    def __init__(self, name="mcc", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self._helper = _BinaryClassificationHelper()

    def reset(self):
        super().reset()
        if hasattr(self, "_helper"):
            self._helper.reset_stats()

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            self._helper.update(label, pred)
        self.sum_metric = self._helper.matthewscc
        self.num_inst = 1
        self.global_sum_metric = self.sum_metric
        self.global_num_inst = 1


@register("mae")
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self._add(float(_np.abs(label - pred).mean()), 1)


@register("mse")
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self._add(float(((label - pred) ** 2.0).mean()), 1)


@register("rmse")
class RMSE(EvalMetric):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self._add(float(_np.sqrt(((label - pred) ** 2.0).mean())), 1)


@register("ce", aliases=["cross-entropy"])
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel().astype("int64")
            pred = _as_numpy(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[_np.arange(label.shape[0]), label]
            self._add(float((-_np.log(prob + self.eps)).sum()),
                      label.shape[0])


@register("nll_loss")
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super(CrossEntropy, self).__init__(name, output_names, label_names)
        self.eps = eps


@register("perplexity")
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).astype("int64")
            pred = _as_numpy(pred)
            label = label.reshape(-1)
            pred = pred.reshape(-1, pred.shape[-1])
            prob = pred[_np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                prob = _np.where(ignore, 1.0, prob)
                num -= int(ignore.sum())
            loss -= float(_np.log(_np.maximum(1e-10, prob)).sum())
            num += label.shape[0]
        self._add(loss, num)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register("bleu")
class BLEU(EvalMetric):
    """Corpus-level BLEU over token-id sequences (reference:
    Sockeye/GluonNLP evaluation — BASELINE.md "BLEU/F1 parity" row;
    Papineni et al. 2002: modified n-gram precision, geometric mean,
    brevity penalty).

    ``update(labels, preds)``: ``labels`` = reference sequences
    (batch, len) of token ids; ``preds`` = hypothesis token ids
    (batch, len), or per-token scores (batch, len, vocab) which are
    argmax-decoded first.  ``pad_token`` (and anything after
    ``eos_token`` when given) is stripped before matching.

    Counts accumulate corpus-wide (NOT per-sentence averages), so
    ``get()`` is true corpus BLEU; ``smooth`` adds +1 smoothing to the
    higher-order precisions (Lin & Och 2004) for short corpora."""

    def __init__(self, max_n=4, pad_token=None, eos_token=None,
                 smooth=False, name="bleu", output_names=None,
                 label_names=None):
        self.max_n = int(max_n)
        self.pad_token = pad_token
        self.eos_token = eos_token
        self.smooth = smooth
        super().__init__(name, output_names, label_names)

    def reset(self):
        super().reset()
        self._match = [0] * getattr(self, "max_n", 4)
        self._total = [0] * getattr(self, "max_n", 4)
        self._hyp_len = 0
        self._ref_len = 0

    def _clean(self, seq):
        toks = [int(t) for t in seq]
        if self.eos_token is not None and self.eos_token in toks:
            toks = toks[:toks.index(self.eos_token)]
        if self.pad_token is not None:
            toks = [t for t in toks if t != self.pad_token]
        return toks

    def update(self, labels, preds):
        from collections import Counter
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if pred.ndim == label.ndim + 1:
                pred = pred.argmax(axis=-1)
            label = label.reshape(-1, label.shape[-1])
            pred = pred.reshape(-1, pred.shape[-1])
            for ref_row, hyp_row in zip(label, pred):
                ref = self._clean(ref_row)
                hyp = self._clean(hyp_row)
                self._hyp_len += len(hyp)
                self._ref_len += len(ref)
                for n in range(1, self.max_n + 1):
                    hg = Counter(tuple(hyp[i:i + n])
                                 for i in range(len(hyp) - n + 1))
                    rg = Counter(tuple(ref[i:i + n])
                                 for i in range(len(ref) - n + 1))
                    self._match[n - 1] += sum(
                        min(c, rg[g]) for g, c in hg.items())
                    self._total[n - 1] += max(len(hyp) - n + 1, 0)
                self.num_inst += 1

    def get(self):
        if self.num_inst == 0 or self._hyp_len == 0:
            return (self.name, float("nan"))
        logp = 0.0
        for n in range(self.max_n):
            m, t = self._match[n], self._total[n]
            if self.smooth and n > 0:
                m, t = m + 1, t + 1
            if m == 0 or t == 0:
                return (self.name, 0.0)
            logp += math.log(m / t)
        logp /= self.max_n
        bp = 0.0 if self._hyp_len >= self._ref_len else \
            1.0 - self._ref_len / self._hyp_len
        return (self.name, float(math.exp(bp + logp)))


@register("pearsonr")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred).ravel()
            self._add(float(_np.corrcoef(pred, label)[0, 1]), 1)


@register("loss")
class Loss(EvalMetric):
    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
        for pred in preds:
            loss = float(_as_numpy(pred).sum())
            self._add(loss, _as_numpy(pred).size)


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        if metrics is None:
            metrics = []
        self.metrics = [create(i) if isinstance(i, str) else i
                        for i in metrics]

    def add(self, metric):
        self.metrics.append(create(metric) if isinstance(metric, str)
                            else metric)

    def get_metric(self, index):
        return self.metrics[index]

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False,
                 output_names=None, label_names=None):
        super().__init__("custom(%s)" % name, output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, wrap=True)
        else:
            labels, preds = check_label_shapes(labels, preds, wrap=True)
        for pred, label in zip(preds, labels):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self._add(sum_metric, num_inst)
            else:
                self._add(reval, 1)


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval into a CustomMetric (reference: ``metric.np``)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name or numpy_feval.__name__,
                        allow_extra_outputs)


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    return _REG.create(metric, *args, **kwargs)


@register("torch")
class Torch(Loss):
    """Legacy alias of :class:`Loss` kept for reference parity
    (``metric.Torch`` — mean of raw outputs)."""

    def __init__(self, name="torch", **kwargs):
        super().__init__(name=name, **kwargs)


@register("caffe")
class Caffe(Torch):
    """Legacy alias of :class:`Loss` (``metric.Caffe``)."""

    def __init__(self, name="caffe", **kwargs):
        super().__init__(name=name, **kwargs)
