"""Elementwise / broadcast operator kernels.

Reference: ``src/operator/tensor/elemwise_*`` + ``broadcast_reduce_op*`` +
``mshadow_op.h`` functors (SURVEY.md §2.1 "Operator library").  Every impl
is a pure JAX function lowering to XLA HLO; XLA's fusion pass subsumes the
reference's mshadow expression templates and NVRTC pointwise fusion
(``src/operator/fusion/fused_op.cu``) — fused elementwise chains come from
the compiler, not hand-written kernels.
"""
from __future__ import annotations

import numpy as _np

from .registry import register


def _j():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# unary
# ---------------------------------------------------------------------------

def _unary(name, fn, aliases=(), no_grad=False):
    @register(name, aliases=aliases, no_grad=no_grad)
    def impl(data, **kw):
        return fn(_j(), data)
    impl.__name__ = name
    return impl


_unary("negative", lambda jnp, x: -x)
_unary("abs", lambda jnp, x: jnp.abs(x))
_unary("sign", lambda jnp, x: jnp.sign(x))
_unary("square", lambda jnp, x: jnp.square(x))
_unary("sqrt", lambda jnp, x: jnp.sqrt(x))
_unary("rsqrt", lambda jnp, x: 1.0 / jnp.sqrt(x))
_unary("cbrt", lambda jnp, x: jnp.cbrt(x))
_unary("rcbrt", lambda jnp, x: 1.0 / jnp.cbrt(x))
_unary("exp", lambda jnp, x: jnp.exp(x))
_unary("expm1", lambda jnp, x: jnp.expm1(x))
_unary("log", lambda jnp, x: jnp.log(x))
_unary("log2", lambda jnp, x: jnp.log2(x))
_unary("log10", lambda jnp, x: jnp.log10(x))
_unary("log1p", lambda jnp, x: jnp.log1p(x))
_unary("reciprocal", lambda jnp, x: 1.0 / x)
_unary("sin", lambda jnp, x: jnp.sin(x))
_unary("cos", lambda jnp, x: jnp.cos(x))
_unary("tan", lambda jnp, x: jnp.tan(x))
_unary("arcsin", lambda jnp, x: jnp.arcsin(x))
_unary("arccos", lambda jnp, x: jnp.arccos(x))
_unary("arctan", lambda jnp, x: jnp.arctan(x))
_unary("sinh", lambda jnp, x: jnp.sinh(x))
_unary("cosh", lambda jnp, x: jnp.cosh(x))
_unary("tanh", lambda jnp, x: jnp.tanh(x))
_unary("arcsinh", lambda jnp, x: jnp.arcsinh(x))
_unary("arccosh", lambda jnp, x: jnp.arccosh(x))
_unary("arctanh", lambda jnp, x: jnp.arctanh(x))
_unary("degrees", lambda jnp, x: jnp.degrees(x))
_unary("radians", lambda jnp, x: jnp.radians(x))
_unary("floor", lambda jnp, x: jnp.floor(x))
_unary("ceil", lambda jnp, x: jnp.ceil(x))
_unary("trunc", lambda jnp, x: jnp.trunc(x))
_unary("rint", lambda jnp, x: jnp.rint(x))
_unary("round", lambda jnp, x: jnp.round(x))
_unary("fix", lambda jnp, x: jnp.fix(x))
_unary("erf", lambda jnp, x: __import__("jax").scipy.special.erf(x))
_unary("erfinv", lambda jnp, x: __import__("jax").scipy.special.erfinv(x))
_unary("gamma", lambda jnp, x: jnp.exp(__import__("jax").scipy.special.gammaln(x)))
_unary("gammaln", lambda jnp, x: __import__("jax").scipy.special.gammaln(x))
_unary("relu", lambda jnp, x: jnp.maximum(x, 0))
_unary("sigmoid", lambda jnp, x: __import__("jax").nn.sigmoid(x))
_unary("softsign", lambda jnp, x: x / (1 + jnp.abs(x)))
_unary("hard_sigmoid", lambda jnp, x: jnp.clip(0.2 * x + 0.5, 0, 1))
_unary("logical_not", lambda jnp, x: (~(x.astype(bool))).astype(x.dtype))
_unary("identity", lambda jnp, x: x, aliases=("_copy",))
_unary("erfc", lambda jnp, x: __import__("jax").scipy.special.erfc(x))
_unary("digamma", lambda jnp, x: __import__("jax").scipy.special.digamma(x))


@register("BlockGrad", aliases=("stop_gradient",))
def block_grad(data, **kw):
    import jax
    return jax.lax.stop_gradient(data)


@register("make_loss", aliases=("MakeLoss",))
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null",
              **kw):
    return data * grad_scale if grad_scale != 1.0 else data


@register("clip")
def clip(data, a_min=None, a_max=None, **kw):
    return _j().clip(data, a_min, a_max)


@register("smooth_l1")
def smooth_l1(data, scalar=1.0, **kw):
    jnp = _j()
    s2 = scalar * scalar
    return jnp.where(jnp.abs(data) < 1.0 / s2,
                     0.5 * s2 * jnp.square(data),
                     jnp.abs(data) - 0.5 / s2)


@register("Cast", aliases=("cast",), no_grad=False)
def cast(data, dtype="float32", **kw):
    return data.astype(_np.dtype(dtype).name)


@register("amp_cast")
def amp_cast(data, dtype="float32", **kw):
    return data.astype(_np.dtype(dtype).name)


@register("amp_multicast", variadic=True, num_outputs=-1)
def amp_multicast(data, num_outputs=None, cast_narrow=False, **kw):
    jnp = _j()
    dtypes = [d.dtype for d in data]
    widths = [_np.dtype(str(d)).itemsize for d in dtypes]
    target = dtypes[_np.argmin(widths)] if cast_narrow else \
        dtypes[_np.argmax(widths)]
    return tuple(d.astype(target) for d in data)


# ---------------------------------------------------------------------------
# binary (same-shape elemwise + broadcast variants; on XLA both lower to the
# same HLO so the broadcast impls serve both op families)
# ---------------------------------------------------------------------------

def _binary(name, fn, aliases=(), no_grad=False):
    @register(name, aliases=aliases, no_grad=no_grad)
    def impl(lhs, rhs, **kw):
        return fn(_j(), lhs, rhs)
    impl.__name__ = name
    return impl


_binary("broadcast_add", lambda jnp, a, b: a + b,
        aliases=("elemwise_add", "_plus", "_add", "broadcast_plus"))
_binary("broadcast_sub", lambda jnp, a, b: a - b,
        aliases=("elemwise_sub", "_sub", "_minus", "broadcast_minus"))
_binary("broadcast_mul", lambda jnp, a, b: a * b,
        aliases=("elemwise_mul", "_mul"))
_binary("broadcast_div", lambda jnp, a, b: a / b,
        aliases=("elemwise_div", "_div"))
_binary("broadcast_mod", lambda jnp, a, b: jnp.mod(a, b), aliases=("_mod",))
_binary("broadcast_power", lambda jnp, a, b: jnp.power(a, b),
        aliases=("_power", "pow"))
_binary("_broadcast_floordiv", lambda jnp, a, b: jnp.floor_divide(a, b))
_binary("broadcast_maximum", lambda jnp, a, b: jnp.maximum(a, b),
        aliases=("_maximum", "maximum"))
_binary("broadcast_minimum", lambda jnp, a, b: jnp.minimum(a, b),
        aliases=("_minimum", "minimum"))
_binary("broadcast_hypot", lambda jnp, a, b: jnp.hypot(a, b))
_binary("arctan2", lambda jnp, a, b: jnp.arctan2(a, b))

_binary("broadcast_equal", lambda jnp, a, b: (a == b).astype(a.dtype),
        aliases=("_equal",), no_grad=True)
_binary("broadcast_not_equal", lambda jnp, a, b: (a != b).astype(a.dtype),
        aliases=("_not_equal",), no_grad=True)
_binary("broadcast_greater", lambda jnp, a, b: (a > b).astype(a.dtype),
        aliases=("_greater",), no_grad=True)
_binary("broadcast_greater_equal",
        lambda jnp, a, b: (a >= b).astype(a.dtype),
        aliases=("_greater_equal",), no_grad=True)
_binary("broadcast_lesser", lambda jnp, a, b: (a < b).astype(a.dtype),
        aliases=("_lesser",), no_grad=True)
_binary("broadcast_lesser_equal",
        lambda jnp, a, b: (a <= b).astype(a.dtype),
        aliases=("_lesser_equal",), no_grad=True)
_binary("broadcast_logical_and",
        lambda jnp, a, b: (a.astype(bool) & b.astype(bool)).astype(a.dtype),
        no_grad=True)
_binary("broadcast_logical_or",
        lambda jnp, a, b: (a.astype(bool) | b.astype(bool)).astype(a.dtype),
        no_grad=True)
_binary("broadcast_logical_xor",
        lambda jnp, a, b: (a.astype(bool) ^ b.astype(bool)).astype(a.dtype),
        no_grad=True)
_binary("_npi_matmul", lambda jnp, a, b: jnp.matmul(a, b),
        aliases=("matmul",))


@register("add_n", aliases=("ElementWiseSum", "_sum"), variadic=True)
def add_n(args, **kw):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


# ---------------------------------------------------------------------------
# scalar ops (reference: _plus_scalar etc. backing the Python operators)
# ---------------------------------------------------------------------------

def _scalar(name, fn, no_grad=False):
    @register(name, no_grad=no_grad)
    def impl(data, scalar=0.0, **kw):
        return fn(_j(), data, scalar)
    impl.__name__ = name
    return impl


_scalar("_plus_scalar", lambda jnp, x, s: x + _tc(jnp, x, s))
_scalar("_minus_scalar", lambda jnp, x, s: x - _tc(jnp, x, s))
_scalar("_rminus_scalar", lambda jnp, x, s: _tc(jnp, x, s) - x)
_scalar("_mul_scalar", lambda jnp, x, s: x * _tc(jnp, x, s))
_scalar("_div_scalar", lambda jnp, x, s: x / _tc(jnp, x, s))
_scalar("_rdiv_scalar", lambda jnp, x, s: _tc(jnp, x, s) / x)
_scalar("_mod_scalar", lambda jnp, x, s: jnp.mod(x, _tc(jnp, x, s)))
_scalar("_rmod_scalar", lambda jnp, x, s: jnp.mod(_tc(jnp, x, s), x))
_scalar("_power_scalar", lambda jnp, x, s: jnp.power(x, _tc(jnp, x, s)))
_scalar("_rpower_scalar", lambda jnp, x, s: jnp.power(_tc(jnp, x, s), x))
_scalar("_floordiv_scalar",
        lambda jnp, x, s: jnp.floor_divide(x, _tc(jnp, x, s)))
_scalar("_maximum_scalar", lambda jnp, x, s: jnp.maximum(x, _tc(jnp, x, s)))
_scalar("_minimum_scalar", lambda jnp, x, s: jnp.minimum(x, _tc(jnp, x, s)))
_scalar("_equal_scalar", lambda jnp, x, s: (x == s).astype(x.dtype),
        no_grad=True)
_scalar("_not_equal_scalar", lambda jnp, x, s: (x != s).astype(x.dtype),
        no_grad=True)
_scalar("_greater_scalar", lambda jnp, x, s: (x > s).astype(x.dtype),
        no_grad=True)
_scalar("_greater_equal_scalar", lambda jnp, x, s: (x >= s).astype(x.dtype),
        no_grad=True)
_scalar("_lesser_scalar", lambda jnp, x, s: (x < s).astype(x.dtype),
        no_grad=True)
_scalar("_lesser_equal_scalar", lambda jnp, x, s: (x <= s).astype(x.dtype),
        no_grad=True)


def _tc(jnp, x, s):
    """Type-consistent scalar: keep the array dtype (MXNet semantics — a
    Python float does not promote float16/bfloat16 arrays)."""
    if _np.issubdtype(_np.dtype(str(x.dtype)), _np.integer) and \
            float(s) == int(s):
        return int(s)
    return jnp.asarray(s, dtype=x.dtype)


@register("where")
def where(condition, x, y, **kw):
    return _j().where(condition.astype(bool), x, y)


@register("all_finite")
def all_finite(data, init_output=True, **kw):
    jnp = _j()
    return jnp.all(jnp.isfinite(data)).reshape((1,)).astype("float32")


@register("multi_all_finite", variadic=True)
def multi_all_finite(data, num_arrays=None, init_output=True, **kw):
    jnp = _j()
    ok = jnp.asarray(True)
    for d in data:
        ok = ok & jnp.all(jnp.isfinite(d))
    return ok.reshape((1,)).astype("float32")


@register("isnan", no_grad=True)
def isnan(data, **kw):
    return _j().isnan(data).astype("float32")


@register("isinf", no_grad=True)
def isinf(data, **kw):
    return _j().isinf(data).astype("float32")


@register("isfinite", no_grad=True)
def isfinite(data, **kw):
    return _j().isfinite(data).astype("float32")
