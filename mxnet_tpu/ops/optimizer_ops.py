"""Fused optimizer-update kernels.

Reference: ``src/operator/optimizer_op.cc`` — ``sgd_update``,
``sgd_mom_update``, ``adam_update``, ``lamb_*``, ``multi_*`` grouped and
``mp_*`` multi-precision variants (SURVEY.md §2.1).  Semantics: the caller
passes ``out=weight`` (buffer-swap mutation); optimizer *state* inputs are
declared via ``mutate=`` and written back by the invoke layer.  XLA fuses
each update into a single elementwise kernel; the grouped ``multi_*`` ops
exist so one dispatch covers many small parameters (same motivation as the
reference's grouped kernels).
"""
from __future__ import annotations

from .registry import register


def _j():
    import jax.numpy as jnp
    return jnp


def _prep_grad(grad, rescale_grad, clip_gradient, wd, weight):
    jnp = _j()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    if wd:
        g = g + wd * weight
    return g


@register("sgd_update")
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True, **kw):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    return weight - lr * g


@register("sgd_mom_update", mutate=(2,))
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True,
                   **kw):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("mp_sgd_update", mutate=(2,))
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True, **kw):
    g32 = grad.astype("float32")
    g = _prep_grad(g32, rescale_grad, clip_gradient, wd, weight32)
    new_w32 = weight32 - lr * g
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", mutate=(2, 3))
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True, **kw):
    g = _prep_grad(grad.astype("float32"), rescale_grad, clip_gradient, wd,
                   weight32)
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("nag_mom_update", mutate=(2,))
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, **kw):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("mp_nag_mom_update", mutate=(2, 3))
def mp_nag_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, **kw):
    g = _prep_grad(grad.astype("float32"), rescale_grad, clip_gradient, wd,
                   weight32)
    new_mom = momentum * mom + g
    new_w32 = weight32 - lr * (g + momentum * new_mom)
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("adam_update", mutate=(2, 3))
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True, **kw):
    jnp = _j()
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new_w, new_mean, new_var


@register("mp_adam_update", mutate=(2, 3, 4))
def mp_adam_update(weight, grad, mean, var, weight32, lr=0.001, beta1=0.9,
                   beta2=0.999, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, **kw):
    jnp = _j()
    g = _prep_grad(grad.astype("float32"), rescale_grad, clip_gradient, wd,
                   weight32)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w32 = weight32 - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new_w32.astype(weight.dtype), new_mean, new_var, new_w32


@register("adamw_update", mutate=(2, 3))
def adamw_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0,
                 clip_gradient=-1.0, **kw):
    jnp = _j()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w = weight - eta * (lr * new_mean / (jnp.sqrt(new_var) + epsilon)
                            + wd * weight)
    return new_w, new_mean, new_var


@register("ftrl_update", mutate=(2, 3))
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0, **kw):
    jnp = _j()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1) /
        ((beta + jnp.sqrt(new_n)) / lr + wd))
    return new_w, new_z, new_n


@register("rmsprop_update", mutate=(2,))
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0, **kw):
    jnp = _j()
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n


@register("rmspropalex_update", mutate=(2, 3, 4))
def rmspropalex_update(weight, grad, n, g, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0, **kw):
    jnp = _j()
    gr = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(gr)
    new_g = gamma1 * g + (1 - gamma1) * gr
    new_delta = gamma2 * delta - lr * gr / jnp.sqrt(
        new_n - jnp.square(new_g) + epsilon)
    new_w = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n, new_g, new_delta


@register("signsgd_update")
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, **kw):
    jnp = _j()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", mutate=(2,))
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0, **kw):
    jnp = _j()
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - (1 - momentum) * g
    new_w = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return new_w, new_mom


@register("lamb_update_phase1", mutate=(2, 3))
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0, **kw):
    """LAMB phase 1; mean/var moments are mutated in place (reference
    FMutateInputs contract, ``optimizer_op.cc``)."""
    jnp = _j()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    m, v = new_mean, new_var
    if bias_correction:
        m = m / (1 - beta1 ** t)
        v = v / (1 - beta2 ** t)
    out = m / (jnp.sqrt(v) + epsilon) + wd * weight
    return out, new_mean, new_var


@register("lamb_update_phase2")
def lamb_update_phase2(weight, g_update, r1, r2, lr=0.01,
                       lower_bound=-1.0, upper_bound=-1.0, **kw):
    jnp = _j()
    r1_ = r1
    r2_ = r2
    if lower_bound is not None and lower_bound >= 0:
        r1_ = jnp.maximum(r1_, lower_bound)
    if upper_bound is not None and upper_bound >= 0:
        r1_ = jnp.minimum(r1_, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1_ > 0, r2_ > 0), r1_ / r2_,
                      jnp.ones_like(r1_))
    return weight - lr * ratio * g_update


# ---------------------------------------------------------------------------
# grouped multi-tensor updates (one dispatch, many params)
# ---------------------------------------------------------------------------

def _concrete_rates(lrs, wds):
    """True when per-tensor rates are host numbers.  Array-valued rates
    (the preloaded_* ops — LARS recomputes them on device every step)
    must stay on the traced per-tensor path: the fused kernel bakes
    rates in as floats, which would force a host sync per step eagerly
    and break under jit."""
    import numbers
    return all(isinstance(v, numbers.Number)
               for seq in (lrs, wds) for v in list(seq))


def _use_fused_group(tensors):
    # fused path computes in f32 end-to-end; restrict it to f32 groups
    # so numerics stay bit-identical with the per-tensor loop
    import os
    if os.environ.get("MXNET_FUSED_OPTIMIZER", "1") != "1":
        return False
    import jax.numpy as jnp
    return all(getattr(t, "dtype", None) == jnp.float32
               for t in tensors)


@register("multi_sgd_update", variadic=True, num_outputs=-1)
def multi_sgd_update(data, lrs=None, wds=None, rescale_grad=1.0,
                     clip_gradient=-1.0, num_weights=1, **kw):
    ws = [data[2 * i] for i in range(num_weights)]
    if num_weights > 1 and _use_fused_group(data) \
            and _concrete_rates(lrs, wds):
        from ..kernels.fused_optimizer import fused_multi_sgd
        gs = [data[2 * i + 1] for i in range(num_weights)]
        outs, _ = fused_multi_sgd(ws, gs, lrs=lrs, wds=wds,
                                  rescale_grad=rescale_grad,
                                  clip_gradient=clip_gradient)
        return tuple(outs)
    outs = []
    for i in range(num_weights):
        w, g = data[2 * i], data[2 * i + 1]
        outs.append(sgd_update(w, g, lr=lrs[i], wd=wds[i],
                               rescale_grad=rescale_grad,
                               clip_gradient=clip_gradient))
    return tuple(outs)


@register("multi_sgd_mom_update", variadic=True, num_outputs=-1,
          mutate=lambda attrs: tuple(
              3 * i + 2 for i in range(attrs.get("num_weights", 1))))
def multi_sgd_mom_update(data, lrs=None, wds=None, momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0,
                         num_weights=1, **kw):
    ws = [data[3 * i] for i in range(num_weights)]
    if num_weights > 1 and _use_fused_group(data) \
            and _concrete_rates(lrs, wds):
        from ..kernels.fused_optimizer import fused_multi_sgd
        gs = [data[3 * i + 1] for i in range(num_weights)]
        ms = [data[3 * i + 2] for i in range(num_weights)]
        outs, moms = fused_multi_sgd(ws, gs, ms, lrs=lrs, wds=wds,
                                     momentum=momentum,
                                     rescale_grad=rescale_grad,
                                     clip_gradient=clip_gradient)
        return tuple(outs) + tuple(moms)
    outs = []
    moms = []
    for i in range(num_weights):
        w, g, m = data[3 * i], data[3 * i + 1], data[3 * i + 2]
        nw, nm = sgd_mom_update(w, g, m, lr=lrs[i], momentum=momentum,
                                wd=wds[i], rescale_grad=rescale_grad,
                                clip_gradient=clip_gradient)
        outs.append(nw)
        moms.append(nm)
    # momenta appended after outputs; written back via the mutate contract
    return tuple(outs) + tuple(moms)


@register("mp_lamb_update_phase1", mutate=(2, 3))
def mp_lamb_update_phase1(weight, grad, mean, var, weight32, beta1=0.9,
                          beta2=0.999, epsilon=1e-6, t=1,
                          bias_correction=True, wd=0.0, rescale_grad=1.0,
                          clip_gradient=-1.0, **kw):
    """Mixed-precision LAMB phase 1: the phase-1 math on the f32 master
    weight (reference: mp_lamb_update_phase1)."""
    return lamb_update_phase1(weight32, grad.astype("float32"), mean, var,
                              beta1=beta1, beta2=beta2, epsilon=epsilon,
                              t=t, bias_correction=bias_correction, wd=wd,
                              rescale_grad=rescale_grad,
                              clip_gradient=clip_gradient)


@register("mp_lamb_update_phase2", mutate=(4,))
def mp_lamb_update_phase2(weight, g_update, r1, r2, weight32, lr=0.01,
                          lower_bound=-1.0, upper_bound=-1.0, **kw):
    """Mixed-precision LAMB phase 2: updates the f32 master, emits the
    low-precision weight (reference: mp_lamb_update_phase2)."""
    new32 = lamb_update_phase2(weight32, g_update, r1, r2, lr=lr,
                               lower_bound=lower_bound,
                               upper_bound=upper_bound)
    return new32.astype(weight.dtype), new32


@register("multi_lars")
def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
               eps=1e-8, rescale_grad=1.0, **kw):
    """LARS layerwise-rate computation over stacked per-layer norms
    (reference: ``optimizer_op.cc`` multi_lars): out lr_i = lr_i *
    eta * ||w_i|| / (||g_i|| * rescale + wd_i * ||w_i|| + eps)."""
    jnp = _j()
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
    ratio = eta * w_norm / (g_norm + wds * w_norm + eps)
    # the lars ratio applies only when BOTH norms are positive
    # (reference: a zero-grad layer passes its lr through unchanged,
    # not lr*eta*||w||/eps)
    return jnp.where((w_norm > 0) & (g_norm > 0), lrs * ratio, lrs)


@register("preloaded_multi_sgd_update", variadic=True, num_outputs=-1)
def preloaded_multi_sgd_update(data, rescale_grad=1.0, clip_gradient=-1.0,
                               num_weights=1, **kw):
    """multi_sgd_update with per-layer lrs/wds passed as ARRAYS (the
    last two inputs) instead of attrs — avoids re-jitting when LARS
    recomputes rates every step (reference: preloaded_multi_sgd)."""
    # delegate: array lrs/wds index identically to attr lists, and the
    # fused-group fast path applies unchanged
    return multi_sgd_update(data[:-2], lrs=data[-2], wds=data[-1],
                            rescale_grad=rescale_grad,
                            clip_gradient=clip_gradient,
                            num_weights=num_weights)


@register("preloaded_multi_sgd_mom_update", variadic=True, num_outputs=-1,
          mutate=lambda attrs: tuple(
              3 * i + 2 for i in range(attrs.get("num_weights", 1))))
def preloaded_multi_sgd_mom_update(data, momentum=0.0, rescale_grad=1.0,
                                   clip_gradient=-1.0, num_weights=1,
                                   **kw):
    return multi_sgd_mom_update(data[:-2], lrs=data[-2], wds=data[-1],
                                momentum=momentum,
                                rescale_grad=rescale_grad,
                                clip_gradient=clip_gradient,
                                num_weights=num_weights)


@register("multi_sum_sq", variadic=True, num_outputs=1, no_grad=True)
def multi_sum_sq(data, num_arrays=1, **kw):
    """Per-array sum of squares over a group, one fused launch
    (reference: ``contrib/multi_sum_sq.cc`` — feeds ``multi_lars``)."""
    jnp = _j()
    return jnp.stack([jnp.sum(jnp.square(a.astype("float32")))
                      for a in data[:num_arrays]])


@register("reset_arrays", variadic=True, num_outputs=-1,
          mutate=lambda attrs: tuple(range(attrs.get("num_arrays", 1))),
          no_grad=True)
def reset_arrays(data, num_arrays=1, **kw):
    """Zero a group of arrays in one call (reference:
    ``contrib/reset_arrays.cc`` — gradient clearing between
    accumulation windows)."""
    jnp = _j()
    return tuple(jnp.zeros_like(a) for a in data[:num_arrays])


@register("multi_mp_sgd_update", variadic=True, num_outputs=-1,
          mutate=lambda attrs: tuple(
              3 * i + 2 for i in range(attrs.get("num_weights", 1))))
def multi_mp_sgd_update(data, lrs=None, wds=None, rescale_grad=1.0,
                        clip_gradient=-1.0, num_weights=1, **kw):
    """Grouped multi-precision SGD: per weight the triple is
    (weight16, grad16, weight32 master) — reference:
    ``optimizer_op.cc multi_mp_sgd_update``."""
    outs, masters = [], []
    for i in range(num_weights):
        w, g, w32 = data[3 * i], data[3 * i + 1], data[3 * i + 2]
        nw, nw32 = mp_sgd_update(w, g, w32, lr=lrs[i], wd=wds[i],
                                 rescale_grad=rescale_grad,
                                 clip_gradient=clip_gradient)
        outs.append(nw)
        masters.append(nw32)
    return tuple(outs) + tuple(masters)


@register("multi_mp_sgd_mom_update", variadic=True, num_outputs=-1,
          mutate=lambda attrs: tuple(
              v for i in range(attrs.get("num_weights", 1))
              for v in (4 * i + 2, 4 * i + 3)))
def multi_mp_sgd_mom_update(data, lrs=None, wds=None, momentum=0.0,
                            rescale_grad=1.0, clip_gradient=-1.0,
                            num_weights=1, **kw):
    """Grouped multi-precision momentum SGD: quadruples of
    (weight16, grad16, momentum32, weight32)."""
    outs, moms, masters = [], [], []
    for i in range(num_weights):
        w, g, m, w32 = (data[4 * i], data[4 * i + 1], data[4 * i + 2],
                        data[4 * i + 3])
        nw, nm, nw32 = mp_sgd_mom_update(
            w, g, m, w32, lr=lrs[i], momentum=momentum, wd=wds[i],
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
        outs.append(nw)
        moms.append(nm)
        masters.append(nw32)
    out = list(outs)
    for nm, nw32 in zip(moms, masters):
        out += [nm, nw32]
    return tuple(out)


@register("_contrib_group_adagrad_update",
          aliases=("group_adagrad_update",), mutate=(2,))
def group_adagrad_update(weight, grad, history, lr=0.01,
                         rescale_grad=1.0, clip_gradient=-1.0,
                         epsilon=1e-5, **kw):
    """Group AdaGrad (reference: ``contrib/optimizer_op.cc``): history
    is per-ROW — mean of squared grads over trailing dims — so the
    state is a vector, not a full weight copy."""
    jnp = _j()
    g = grad * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    if g.ndim > 1:
        h_new = history + jnp.mean(jnp.square(g),
                                   axis=tuple(range(1, g.ndim)),
                                   keepdims=True)
    else:
        h_new = history + jnp.square(g)
    w_new = weight - lr * g / (jnp.sqrt(h_new) + epsilon)
    return w_new, h_new
