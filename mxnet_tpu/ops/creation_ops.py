"""Creation ops already live in shape_ops (``_zeros``/``_ones``/…); this
module holds the few remaining init ops (reference:
``src/operator/tensor/init_op.cc``)."""
from __future__ import annotations

from .registry import register


def _j():
    import jax.numpy as jnp
    return jnp


@register("_full", no_grad=True)
def _full(shape=None, value=0.0, dtype="float32", **kw):
    import numpy as _np
    return _j().full(shape, value, dtype=_np.dtype(dtype or "float32").name)
