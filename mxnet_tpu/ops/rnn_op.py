"""Fused RNN operator (LSTM/GRU/vanilla, multi-layer, bidirectional).

Reference: ``src/operator/rnn-inl.h`` — the fused ``RNN`` op that the
reference dispatches to cuDNN (SURVEY.md §2.1; gluon/rnn uses it).
TPU-native design: the time loop is a ``lax.scan`` (static-shape, XLA
compiles it to a single fused while loop on device); the layer loop is
unrolled in the trace (num_layers is static).  Weight layout follows the
reference's cuDNN-canonical packing: all gate weights (per layer, per
direction: W then R), then all biases — gate order LSTM ``[i, f, c, o]``,
GRU ``[r, z, n]`` — so checkpoints round-trip.
"""
from __future__ import annotations

from .registry import register
from ..base import MXNetError

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "gru": 3, "lstm": 4}


def _unpack_params(params, mode, num_layers, input_size, H, D):
    """Split the flat parameter vector into per-layer (W, R, bW, bR)."""
    import jax.numpy as jnp
    G = _GATES[mode]
    weights = []
    offset = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else H * D
        layer_w = []
        for d in range(D):
            W = params[offset:offset + G * H * in_sz].reshape(G * H, in_sz)
            offset += G * H * in_sz
            R = params[offset:offset + G * H * H].reshape(G * H, H)
            offset += G * H * H
            layer_w.append((W, R))
        weights.append(layer_w)
    biases = []
    for layer in range(num_layers):
        layer_b = []
        for d in range(D):
            bW = params[offset:offset + G * H]
            offset += G * H
            bR = params[offset:offset + G * H]
            offset += G * H
            layer_b.append((bW, bR))
        biases.append(layer_b)
    return weights, biases


def rnn_param_size(mode, num_layers, input_size, H, bidirectional=False):
    """Total packed parameter count (used by gluon.rnn for allocation)."""
    G = _GATES[mode]
    D = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else H * D
        size += D * (G * H * in_sz + G * H * H + 2 * G * H)
    return size


def _cell_step(mode, x_proj, h, c, R, bR):
    """One timestep given precomputed input projection x_proj."""
    import jax
    import jax.numpy as jnp
    H = h.shape[-1]
    if mode == "lstm":
        gates = x_proj + jnp.matmul(h, R.T) + bR
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new
    if mode == "gru":
        rproj = jnp.matmul(h, R.T) + bR
        xr, xz, xn = jnp.split(x_proj, 3, axis=-1)
        hr, hz, hn = jnp.split(rproj, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h_new = (1 - z) * n + z * h
        return h_new, c
    act = jnp.tanh if mode == "rnn_tanh" else (lambda v: jnp.maximum(v, 0))
    h_new = act(x_proj + jnp.matmul(h, R.T) + bR)
    return h_new, c


def _run_direction(mode, data, h0, c0, W, R, bW, bR, reverse):
    """Scan one direction of one layer.  data: (T, N, I)."""
    import jax
    import jax.numpy as jnp
    x = jnp.flip(data, axis=0) if reverse else data
    # hoist the input projection out of the scan: one big MXU matmul
    x_proj = jnp.einsum("tni,gi->tng", x, W) + bW

    def step(carry, xp):
        h, c = carry
        h_new, c_new = _cell_step(mode, xp, h, c, R, bR)
        return (h_new, c_new), h_new

    (hT, cT), out = jax.lax.scan(step, (h0, c0), x_proj)
    if reverse:
        out = jnp.flip(out, axis=0)
    return out, hT, cT


@register("RNN", num_outputs=-1, needs_rng=True, training_aware=True)
def rnn(key, data, parameters, state, state_cell=None, state_size=None,
        num_layers=1, mode="lstm", bidirectional=False, p=0.0,
        state_outputs=False, projection_size=None, sequence_length=None,
        use_sequence_length=False, lstm_state_clip_min=None,
        lstm_state_clip_max=None, _training=False, **kw):
    import jax
    import jax.numpy as jnp
    if mode not in _GATES:
        raise MXNetError("RNN mode %r not supported" % mode)
    if projection_size:
        raise MXNetError("RNN projection_size is not implemented")
    T, N, I = data.shape
    H = state_size
    D = 2 if bidirectional else 1
    weights, biases = _unpack_params(parameters, mode, num_layers, I, H, D)

    h_states = state  # (L*D, N, H)
    c_states = state_cell if mode == "lstm" else jnp.zeros_like(state)

    x = data
    hs_out, cs_out = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(D):
            sidx = layer * D + d
            W, R = weights[layer][d]
            bW, bR = biases[layer][d]
            out, hT, cT = _run_direction(
                mode, x, h_states[sidx], c_states[sidx], W, R, bW, bR,
                reverse=(d == 1))
            outs.append(out)
            hs_out.append(hT)
            cs_out.append(cT)
        x = outs[0] if D == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0 and _training and layer < num_layers - 1:
            key, sub = jax.random.split(key)
            mask = jax.random.bernoulli(sub, 1 - p, shape=x.shape)
            x = jnp.where(mask, x / (1 - p), 0.0).astype(x.dtype)

    hN = jnp.stack(hs_out, axis=0)
    if mode == "lstm":
        cN = jnp.stack(cs_out, axis=0)
        return x, hN, cN
    return x, hN


@register("_rnn_nostate", num_outputs=-1, needs_rng=True,
          training_aware=True)
def rnn_nostate(key, data, parameters, state_size=None, num_layers=1,
                mode="lstm", bidirectional=False, _training=False, **kw):
    """RNN with implicit all-zero initial states — the ONNX importer's
    target for LSTM/GRU/RNN nodes whose optional ``initial_h``/
    ``initial_c`` inputs are omitted (zero states per the ONNX spec)."""
    import jax.numpy as jnp
    D = 2 if bidirectional else 1
    T, N, I = data.shape
    z = jnp.zeros((num_layers * D, N, state_size), dtype=data.dtype)
    kw.pop("state_outputs", None)
    return rnn(key, data, parameters, z,
               z if mode == "lstm" else None, state_size=state_size,
               num_layers=num_layers, mode=mode,
               bidirectional=bidirectional, _training=_training, **kw)
