"""INT8 quantization operators.

Reference: ``src/operator/quantization/`` — ``quantize.cc``,
``quantize_v2.cc``, ``dequantize.cc``, ``requantize.cc``,
``quantized_fully_connected.cc``, ``quantized_conv.cc``,
``quantized_pooling.cc``, ``quantized_flatten.cc`` (SURVEY.md §2.1
"Operator library" quantization/ and §2.2 "Quantization").

TPU-native design:

* Quantized matmul/conv lower to ``lax.dot_general`` /
  ``lax.conv_general_dilated`` with int8 operands and
  ``preferred_element_type=int32`` — the MXU executes s8×s8→s32 natively,
  so there is no cuDNN-int8/oneDNN bridge to replicate: the same XLA op
  that serves the fp32 path serves the int8 path at double the MAC rate.
* Quantization is **symmetric** for int8 (zero-point 0, scale
  ``127 / max|x|``), matching the reference's GPU int8 path.  uint8
  activations (zero-point-0 affine at ``min==0`` — the reference
  quantized-conv default for post-ReLU data) are a supported COMPUTE
  path in quantized conv/FC: the u8×s8 product widens to s32 (HLO has
  no mixed-sign int8 dot); s8×s8 remains the MXU-native fast path.
* Every quantized op follows the reference calling convention: inputs are
  ``(qdata..., min..., max...)`` triples and outputs are
  ``(qout, out_min, out_max)`` so graphs thread value ranges alongside
  the int tensors.  ``dequantize(qout, out_min, out_max)`` always recovers
  the float value — int32 accumulator outputs report the range
  ``±INT32_MAX / (scale_lhs * scale_rhs)`` exactly like the reference's
  ``quantization_range_for_multiplication``.
"""
from __future__ import annotations

import numpy as _np

from .registry import register
from ..base import MXNetError

_INT32_MAX = float(2 ** 31 - 1)


def _j():
    import jax.numpy as jnp
    return jnp


def _lax():
    import jax
    return jax.lax


def _real_range(min_range, max_range):
    jnp = _j()
    return jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))


def _q_range(out_type):
    if out_type == "int8":
        return 127.0
    if out_type == "uint8":
        return 255.0
    if out_type == "int32":
        return _INT32_MAX
    raise MXNetError("unsupported quantized dtype %r" % (out_type,))


@register("_contrib_quantize", num_outputs=3, no_grad=True,
          aliases=("quantize",))
def quantize(data, min_range, max_range, out_type="uint8", **kw):
    """Quantize float → int8/uint8 given an explicit range
    (reference: ``quantize.cc``)."""
    jnp = _j()
    if out_type == "int8":
        r = _real_range(min_range, max_range)
        scale = 127.0 / jnp.maximum(r, 1e-30)
        q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
        return q, -r, r
    if out_type == "uint8":
        scale = 255.0 / jnp.maximum(max_range - min_range, 1e-30)
        q = jnp.clip(jnp.round((data - min_range) * scale), 0, 255)
        return q.astype(jnp.uint8), min_range, max_range
    raise MXNetError("quantize: out_type must be int8/uint8")


@register("_contrib_quantize_v2", num_outputs=3, no_grad=True,
          aliases=("quantize_v2",))
def quantize_v2(data, out_type="int8", min_calib_range=None,
                max_calib_range=None, **kw):
    """Quantize with a calibrated or data-derived range
    (reference: ``quantize_v2.cc``)."""
    jnp = _j()
    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(data)
        mx = jnp.max(data)
    else:
        mn = jnp.asarray(float(min_calib_range))
        mx = jnp.asarray(float(max_calib_range))
    if out_type == "auto":
        # reference quantize_v2 "auto": uint8 when the calibrated range
        # is non-negative (post-ReLU activations), else int8.  Runtime
        # (traced) ranges cannot branch -> int8.
        if min_calib_range is not None and float(min_calib_range) >= 0:
            out_type = "uint8"
        else:
            out_type = "int8"
    if out_type == "uint8":
        # the u8 COMPUTE path (quantized conv/FC) is zero-point-0 —
        # q = x * 255/max — so the calibrated quantization must use the
        # range [0, max], not an affine [min, max]: an affine u8 with
        # min > 0 would silently shift every product (reference's u8
        # convs are likewise zero-point-0 for non-negative data)
        if min_calib_range is not None and float(min_calib_range) < 0:
            raise MXNetError(
                "quantize_v2: out_type='uint8' needs a non-negative "
                "calibrated range (got min=%r); use int8 or 'auto'"
                % (min_calib_range,))
        mn = jnp.zeros_like(mn)
    return quantize(data, mn, mx, out_type=out_type)


@register("_contrib_dequantize", no_grad=True, aliases=("dequantize",))
def dequantize(qdata, min_range, max_range, out_type="float32", **kw):
    """Int → float (reference: ``dequantize.cc``)."""
    jnp = _j()
    if qdata.dtype == jnp.uint8:
        scale = (max_range - min_range) / 255.0
        return qdata.astype(jnp.float32) * scale + min_range
    qrange = 127.0 if qdata.dtype == jnp.int8 else _INT32_MAX
    r = _real_range(min_range, max_range)
    return qdata.astype(jnp.float32) * (r / qrange)


@register("_contrib_requantize", num_outputs=3, no_grad=True,
          aliases=("requantize",))
def requantize(qdata, min_range, max_range, min_calib_range=None,
               max_calib_range=None, **kw):
    """Int32 accumulator → int8, with calibrated or runtime-computed range
    (reference: ``requantize.cc``)."""
    jnp = _j()
    r_in = _real_range(min_range, max_range)
    fdata = qdata.astype(jnp.float32) * (r_in / _INT32_MAX)
    if min_calib_range is not None and max_calib_range is not None:
        r_out = max(abs(float(min_calib_range)), abs(float(max_calib_range)))
        r_out = jnp.asarray(r_out)
    else:
        r_out = jnp.maximum(jnp.max(jnp.abs(fdata)), 1e-30)
    q = jnp.clip(jnp.round(fdata * (127.0 / r_out)), -127, 127)
    return q.astype(jnp.int8), -r_out, r_out


def _mul_out_range(min_a, max_a, min_b, max_b, qa=127.0):
    """Output range of a q8×s8→s32 product chain: the int32 value equals
    ``float * scale_a * scale_b``, so reporting ``±INT32_MAX/(sa*sb)``
    makes ``dequantize`` exact (reference:
    ``quantization_range_for_multiplication``).  ``qa`` is the data
    quantum count: 127 for s8, 255 for u8 (zero-point-0 affine)."""
    jnp = _j()
    ra = _real_range(min_a, max_a)
    rb = _real_range(min_b, max_b)
    sa = qa / jnp.maximum(ra, 1e-30)
    sb = 127.0 / jnp.maximum(rb, 1e-30)
    r_out = _INT32_MAX / (sa * sb)
    return -r_out, r_out, sa * sb


def _check_int8(name, *arrs):
    jnp = _j()
    for a in arrs:
        if a is not None and a.dtype != jnp.int8:
            raise MXNetError("%s requires int8 inputs (got %s); quantize "
                             "with out_type='int8'" % (name, a.dtype))


def _check_q8(name, data, weight):
    """Activations may be int8 or uint8 (the reference's quantized conv
    defaults to uint8 activations post-ReLU, zero-point 0); weights are
    always symmetric int8."""
    jnp = _j()
    if data.dtype not in (jnp.int8, jnp.uint8):
        raise MXNetError("%s requires int8/uint8 data (got %s)"
                         % (name, data.dtype))
    if weight.dtype != jnp.int8:
        raise MXNetError("%s requires int8 weight (got %s)"
                         % (name, weight.dtype))


def _data_qmax(data):
    jnp = _j()
    return 255.0 if data.dtype == jnp.uint8 else 127.0


@register("_contrib_quantized_fully_connected", num_outputs=3, no_grad=True,
          aliases=("quantized_fully_connected",))
def quantized_fully_connected(data, weight, bias=None, min_data=None,
                              max_data=None, min_weight=None,
                              max_weight=None, min_bias=None,
                              max_bias=None, num_hidden=None, no_bias=False,
                              flatten=True, **kw):
    """Int8 FullyConnected with int32 accumulation on the MXU
    (reference: ``quantized_fully_connected.cc``)."""
    jnp = _j()
    lax = _lax()
    if no_bias and min_bias is None and bias is not None:
        # arity without bias: (data, weight, min_d, max_d, min_w, max_w)
        data, weight, min_data, max_data, min_weight, max_weight = (
            data, weight, bias, min_data, max_data, min_weight)
        bias = None
    _check_q8("quantized_fully_connected", data, weight)
    qa = _data_qmax(data)
    x = data
    if flatten and x.ndim > 2:
        x = x.reshape((x.shape[0], -1))
    if x.dtype == jnp.uint8:
        # mixed u8×s8 dots are not HLO-expressible; widen to s32 (the
        # s8×s8 path below stays the MXU-native fast path)
        out = lax.dot_general(x.astype(jnp.int32),
                              weight.astype(jnp.int32),
                              (((x.ndim - 1,), (1,)), ((), ())),
                              preferred_element_type=jnp.int32)
    else:
        out = lax.dot_general(x, weight,
                              (((x.ndim - 1,), (1,)), ((), ())),
                              preferred_element_type=jnp.int32)
    mn, mx, scale_out = _mul_out_range(min_data, max_data,
                                       min_weight, max_weight, qa=qa)
    if bias is not None and not no_bias:
        # re-scale int8 bias into the int32 accumulator's scale
        rb = _real_range(min_bias, max_bias)
        bias_f = bias.astype(jnp.float32) * (rb / 127.0)
        out = out + jnp.round(bias_f * scale_out).astype(jnp.int32)
    return out, mn, mx


@register("_contrib_quantized_conv", num_outputs=3, no_grad=True,
          aliases=("quantized_conv",))
def quantized_conv(data, weight, bias=None, min_data=None, max_data=None,
                   min_weight=None, max_weight=None,
                   min_bias=None, max_bias=None, kernel=None,
                   stride=(1, 1), pad=(0, 0), dilate=(1, 1), num_filter=None,
                   num_group=1, no_bias=False, layout="NCHW", **kw):
    """Int8 convolution with int32 accumulation (reference:
    ``quantized_conv.cc``).  NCHW in/out; XLA re-tiles for the MXU."""
    jnp = _j()
    lax = _lax()
    if no_bias and min_bias is None and bias is not None:
        data, weight, min_data, max_data, min_weight, max_weight = (
            data, weight, bias, min_data, max_data, min_weight)
        bias = None
    _check_q8("quantized_conv", data, weight)
    qa = _data_qmax(data)
    if data.dtype == jnp.uint8:
        data = data.astype(jnp.int32)
        weight = weight.astype(jnp.int32)
    nd_spatial = data.ndim - 2
    stride = tuple(stride)[:nd_spatial] or (1,) * nd_spatial
    pad = tuple(pad)[:nd_spatial] or (0,) * nd_spatial
    dilate = tuple(dilate)[:nd_spatial] or (1,) * nd_spatial
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if nd_spatial == 2
        else ("NCW", "OIW", "NCW"))
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    mn, mx, scale_out = _mul_out_range(min_data, max_data,
                                       min_weight, max_weight, qa=qa)
    if bias is not None and not no_bias:
        rb = _real_range(min_bias, max_bias)
        bias_f = bias.astype(jnp.float32) * (rb / 127.0)
        bias32 = jnp.round(bias_f * scale_out).astype(jnp.int32)
        out = out + bias32.reshape((1, -1) + (1,) * nd_spatial)
    return out, mn, mx


@register("_contrib_quantized_pooling", num_outputs=3, no_grad=True,
          aliases=("quantized_pooling",))
def quantized_pooling(data, min_data, max_data, kernel=None, pool_type="max",
                      stride=None, pad=None, global_pool=False, **kw):
    """Pooling straight on int8 (max) or via int32 mean (avg); range is
    unchanged (reference: ``quantized_pooling.cc``)."""
    jnp = _j()
    lax = _lax()
    nd_spatial = data.ndim - 2
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * nd_spatial
        pad = (0,) * nd_spatial
    kernel = tuple(kernel)
    stride = tuple(stride) if stride else kernel
    pad = tuple(pad) if pad else (0,) * nd_spatial
    dims = (1, 1) + kernel
    strides = (1, 1) + stride
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if pool_type == "max":
        # init value must carry the operand dtype (a bare Python int
        # traces as int32 and reduce_window rejects the mix)
        out = lax.reduce_window(
            data, jnp.asarray(jnp.iinfo(data.dtype).min, data.dtype),
            lax.max, dims, strides, padding)
    elif pool_type == "avg":
        s = lax.reduce_window(data.astype(jnp.int32), 0, lax.add,
                              dims, strides, padding)
        out = jnp.round(s / float(_np.prod(kernel))).astype(jnp.int8)
    else:
        raise MXNetError("quantized_pooling: pool_type must be max/avg")
    return out, min_data, max_data


@register("_contrib_quantized_flatten", num_outputs=3, no_grad=True,
          aliases=("quantized_flatten",))
def quantized_flatten(data, min_data, max_data, **kw):
    return data.reshape((data.shape[0], -1)), min_data, max_data


@register("_contrib_quantized_act", num_outputs=3, no_grad=True,
          aliases=("quantized_act",))
def quantized_act(data, min_data, max_data, act_type="relu", **kw):
    """Int8 relu: clamp at zero, range unchanged (reference:
    ``quantized_activation.cc``)."""
    jnp = _j()
    if act_type != "relu":
        raise MXNetError("quantized_act supports relu only")
    return jnp.maximum(data, 0), min_data, max_data
