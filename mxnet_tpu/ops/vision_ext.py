"""Extended detection/vision operators: the deformable family, RPN
proposals, position-sensitive ROI pooling, rotated ROIAlign, box
codecs and matching.

Reference: ``src/operator/contrib/deformable_convolution.cc``,
``deformable_psroi_pooling.cc``, ``psroi_pooling.cc``, ``proposal.cc``,
``multi_proposal.cc``, ``bounding_box.cc`` (box_encode/box_decode,
bipartite_matching) — SURVEY.md §2.1 operator library (contrib rows).

TPU-native design: every sampler is expressed as dense bilinear gathers
(vectorized ``jnp.take``-based interpolation, vmapped over batch/ROI)
followed by MXU-friendly contractions — no per-pixel scalar loops, all
shapes static so XLA tiles them.  NMS/matching reuse the masked
fori-loop kernels from ``vision.py`` (compiler-friendly control flow,
``lax``-only)."""
from __future__ import annotations

import numpy as _np

from .registry import register
from .vision import _bilinear_gather, _nms_keep


def _j():
    import jax.numpy as jnp
    return jnp


def _pair(v):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


# ---------------------------------------------------------------------------
# Deformable convolution family
# ---------------------------------------------------------------------------

def _deform_im2col(img, offset, mask, kernel, stride, pad, dilate,
                   num_deformable_group, out_hw):
    """Sampled im2col for ONE image.

    img (C, H, W); offset (2*G*Kh*Kw, Ho, Wo); mask (G*Kh*Kw, Ho, Wo) or
    None → columns (C, Kh*Kw, Ho, Wo) sampled at p0 + pn + Δpn.
    """
    import jax
    jnp = _j()
    C, H, W = img.shape
    Kh, Kw = kernel
    Ho, Wo = out_hw
    G = num_deformable_group
    cg = C // G
    # base sampling grid per output position
    ys = jnp.arange(Ho) * stride[0] - pad[0]
    xs = jnp.arange(Wo) * stride[1] - pad[1]
    base_y = ys[:, None]          # (Ho, 1)
    base_x = xs[None, :]          # (1, Wo)
    off = offset.reshape(G, Kh * Kw, 2, Ho, Wo)
    msk = (None if mask is None
           else mask.reshape(G, Kh * Kw, Ho, Wo))
    cols = []
    for tap in range(Kh * Kw):
        kh, kw = tap // Kw, tap % Kw
        per_g = []
        for g in range(G):
            y = base_y + kh * dilate[0] + off[g, tap, 0]
            x = base_x + kw * dilate[1] + off[g, tap, 1]
            v = _bilinear_gather(img[g * cg:(g + 1) * cg], y, x,
                                 border="zero")        # (cg, Ho, Wo)
            if msk is not None:
                v = v * msk[g, tap]
            per_g.append(v)
        cols.append(jnp.concatenate(per_g, axis=0))    # (C, Ho, Wo)
    return jnp.stack(cols, axis=1)                     # (C, K*K, Ho, Wo)


def _deformable_conv(data, offset, weight, bias, mask, kernel, stride,
                     pad, dilate, num_filter, num_group,
                     num_deformable_group, no_bias):
    import jax
    jnp = _j()
    kernel = _pair(kernel)
    stride = _pair(stride) if stride else (1, 1)
    pad = _pair(pad) if pad else (0, 0)
    dilate = _pair(dilate) if dilate else (1, 1)
    N, C, H, W = data.shape
    Kh, Kw = kernel
    Ho = (H + 2 * pad[0] - dilate[0] * (Kh - 1) - 1) // stride[0] + 1
    Wo = (W + 2 * pad[1] - dilate[1] * (Kw - 1) - 1) // stride[1] + 1

    def one(img, off, m):
        cols = _deform_im2col(img, off, m, kernel, stride, pad, dilate,
                              num_deformable_group, (Ho, Wo))
        # grouped contraction: split C and num_filter into num_group
        cg = C // num_group
        fg = num_filter // num_group
        outs = []
        for g in range(num_group):
            w = weight[g * fg:(g + 1) * fg].reshape(fg, cg * Kh * Kw)
            c = cols[g * cg:(g + 1) * cg].reshape(cg * Kh * Kw, Ho * Wo)
            outs.append((w @ c).reshape(fg, Ho, Wo))
        return jnp.concatenate(outs, axis=0)

    if mask is None:
        out = jax.vmap(lambda i, o: one(i, o, None))(data, offset)
    else:
        out = jax.vmap(one)(data, offset, mask)
    if not no_bias and bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


@register("_contrib_DeformableConvolution",
          aliases=("DeformableConvolution",))
def deformable_convolution(data, offset, weight, bias=None, kernel=(1, 1),
                           stride=(), pad=(), dilate=(), num_filter=1,
                           num_group=1, num_deformable_group=1,
                           no_bias=False, layout="NCHW", **kw):
    """DCNv1: convolution sampling at offset-shifted tap positions
    (reference: ``_contrib_DeformableConvolution``)."""
    import jax
    if bias is not None and getattr(bias, "ndim", 1) == 0:
        bias = None
    return _deformable_conv(data, offset, weight, bias, None, kernel,
                            stride, pad, dilate, int(num_filter),
                            int(num_group), int(num_deformable_group),
                            no_bias)


@register("_contrib_ModulatedDeformableConvolution",
          aliases=("ModulatedDeformableConvolution",))
def modulated_deformable_convolution(data, offset, mask, weight, bias=None,
                                     kernel=(1, 1), stride=(), pad=(),
                                     dilate=(), num_filter=1, num_group=1,
                                     num_deformable_group=1, no_bias=False,
                                     layout="NCHW", **kw):
    """DCNv2: adds a learned per-tap modulation mask."""
    if bias is not None and getattr(bias, "ndim", 1) == 0:
        bias = None
    return _deformable_conv(data, offset, weight, bias, mask, kernel,
                            stride, pad, dilate, int(num_filter),
                            int(num_group), int(num_deformable_group),
                            no_bias)


# ---------------------------------------------------------------------------
# Position-sensitive ROI pooling
# ---------------------------------------------------------------------------

def _psroi_one(img, roi, spatial_scale, output_dim, pooled, group,
               trans=None, part_size=0, sample_per_part=1, trans_std=0.0):
    """img (C,H,W) C=output_dim*group^2, roi (5,) → (output_dim, P, P)."""
    import jax
    jnp = _j()
    C, H, W = img.shape
    P = pooled
    x1 = roi[1] * spatial_scale - 0.5
    y1 = roi[2] * spatial_scale - 0.5
    x2 = (roi[3] + 1.0) * spatial_scale - 0.5
    y2 = (roi[4] + 1.0) * spatial_scale - 0.5
    rw = jnp.maximum(x2 - x1, 0.1)
    rh = jnp.maximum(y2 - y1, 0.1)
    bin_w = rw / P
    bin_h = rh / P
    n_s = max(1, int(sample_per_part))
    # sample grid: for bin (i,j), n_s x n_s uniform samples
    ii = jnp.arange(P)
    sub = (jnp.arange(n_s) + 0.5) / n_s
    # (P, n_s) absolute y coords per bin row
    ys = y1 + (ii[:, None] + sub[None, :]) * bin_h       # (P, n_s)
    xs = x1 + (ii[:, None] + sub[None, :]) * bin_w       # (P, n_s)
    if trans is not None:
        # trans (2, part, part): per-part offsets scaled by roi size;
        # part bin of pooled bin i is floor(i * part / P)
        part = part_size if part_size > 0 else P
        pi = jnp.clip((ii * part) // P, 0, part - 1)
        dyg = trans[1][pi][:, pi] * trans_std * rh        # (P, P)
        dxg = trans[0][pi][:, pi] * trans_std * rw        # (P, P)
    else:
        dyg = jnp.zeros((P, P))
        dxg = jnp.zeros((P, P))
    # full sample coordinate grids (P, P, n_s, n_s)
    Y = ys[:, None, :, None] + dyg[:, :, None, None]
    X = xs[None, :, None, :] + dxg[:, :, None, None]
    Yc = jnp.clip(Y, 0.0, H - 1.0)
    Xc = jnp.clip(X, 0.0, W - 1.0)
    vals = _bilinear_gather(img, Yc, Xc)   # (C, P, P, n_s, n_s)
    vals = vals.mean(axis=(-1, -2))        # (C, P, P)
    # position-sensitive channel selection: bin (i,j) reads channel
    # group (gi*group + gj)
    gi = jnp.clip((ii * group) // P, 0, group - 1)
    cs = vals.reshape(output_dim, group * group, P, P)
    sel = (gi[:, None] * group + gi[None, :])            # (P, P)
    return cs[:, sel, jnp.arange(P)[:, None], jnp.arange(P)[None, :]]


@register("_contrib_PSROIPooling", aliases=("PSROIPooling",))
def psroi_pooling(data, rois, spatial_scale=1.0, output_dim=1,
                  pooled_size=1, group_size=0, **kw):
    """Position-sensitive ROI pooling (R-FCN head)."""
    import jax
    group = int(group_size) if group_size else int(pooled_size)
    f = lambda r: _psroi_one(data[r[0].astype("int32")], r,
                             spatial_scale, int(output_dim),
                             int(pooled_size), group)
    return jax.vmap(f)(rois)


@register("_contrib_DeformablePSROIPooling",
          aliases=("DeformablePSROIPooling",))
def deformable_psroi_pooling(data, rois, trans=None, spatial_scale=1.0,
                             output_dim=1, group_size=1, pooled_size=1,
                             part_size=0, sample_per_part=1,
                             trans_std=0.0, no_trans=False, **kw):
    """Deformable position-sensitive ROI pooling (reference:
    ``deformable_psroi_pooling.cc``): per-part offsets shift the bins."""
    import jax
    P = int(pooled_size)
    use_trans = (not no_trans) and trans is not None

    def f(r, idx):
        t = None
        if use_trans:
            # trans (R, 2*cls, part, part); class-agnostic → first 2
            t = trans[idx, :2]
        return _psroi_one(data[r[0].astype("int32")], r, spatial_scale,
                          int(output_dim), P, int(group_size), t,
                          int(part_size), int(sample_per_part),
                          float(trans_std))

    jnp = _j()
    idxs = jnp.arange(rois.shape[0])
    return jax.vmap(f)(rois, idxs)


# ---------------------------------------------------------------------------
# RPN proposals
# ---------------------------------------------------------------------------

def _gen_anchors(feat_h, feat_w, stride, scales, ratios):
    jnp = _j()
    base = float(stride)
    scales = _np.array(scales, dtype=_np.float32)
    ratios = _np.array(ratios, dtype=_np.float32)
    # base anchor centered at (stride-1)/2
    ctr = (base - 1) / 2.0
    ws, hs = [], []
    size = base * base
    for r in ratios:
        size_r = size / r
        w0 = _np.round(_np.sqrt(size_r))
        h0 = _np.round(w0 * r)
        for s in scales:
            ws.append(w0 * s)
            hs.append(h0 * s)
    ws = _np.array(ws, _np.float32)
    hs = _np.array(hs, _np.float32)
    A = len(ws)
    anchors = _np.stack([ctr - 0.5 * (ws - 1), ctr - 0.5 * (hs - 1),
                         ctr + 0.5 * (ws - 1), ctr + 0.5 * (hs - 1)],
                        axis=1)                      # (A, 4)
    sx = _np.arange(feat_w) * stride
    sy = _np.arange(feat_h) * stride
    shift = _np.stack(_np.meshgrid(sx, sy), axis=-1)  # (H, W, 2) x,y
    shift4 = _np.concatenate([shift, shift], axis=-1).reshape(-1, 1, 4)
    all_anchors = (anchors[None] + shift4).reshape(-1, 4)  # (H*W*A, 4)
    return jnp.asarray(all_anchors)


def _decode_bbox(anchors, deltas):
    jnp = _j()
    w = anchors[:, 2] - anchors[:, 0] + 1.0
    h = anchors[:, 3] - anchors[:, 1] + 1.0
    cx = anchors[:, 0] + 0.5 * (w - 1)
    cy = anchors[:, 1] + 0.5 * (h - 1)
    dx, dy, dw, dh = (deltas[:, 0], deltas[:, 1], deltas[:, 2],
                      deltas[:, 3])
    ncx = dx * w + cx
    ncy = dy * h + cy
    nw = jnp.exp(dw) * w
    nh = jnp.exp(dh) * h
    return jnp.stack([ncx - 0.5 * (nw - 1), ncy - 0.5 * (nh - 1),
                      ncx + 0.5 * (nw - 1), ncy + 0.5 * (nh - 1)],
                     axis=1)


def _proposal_one(scores, deltas, im_info, anchors, pre_n, post_n,
                  nms_thresh, min_size):
    import jax
    jnp = _j()
    H, W = im_info[0], im_info[1]
    boxes = _decode_bbox(anchors, deltas)
    boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, W - 1),
                       jnp.clip(boxes[:, 1], 0, H - 1),
                       jnp.clip(boxes[:, 2], 0, W - 1),
                       jnp.clip(boxes[:, 3], 0, H - 1)], axis=1)
    ws = boxes[:, 2] - boxes[:, 0] + 1
    hs = boxes[:, 3] - boxes[:, 1] + 1
    ms = min_size * im_info[2]
    valid = (ws >= ms) & (hs >= ms)
    scores = jnp.where(valid, scores, -1.0)
    pre_n = min(pre_n, scores.shape[0])
    top_s, top_i = jax.lax.top_k(scores, pre_n)
    top_b = boxes[top_i]
    keep = _nms_keep(top_b, top_s, top_s > -1.0, nms_thresh, True,
                     jnp.zeros_like(top_s))
    # order: kept boxes by score, padded with the top-1 box (reference
    # pads with repeats)
    rank = jnp.where(keep, top_s, -jnp.inf)
    post = min(post_n, pre_n)
    sel_s, sel_i = jax.lax.top_k(rank, post)
    out_b = top_b[sel_i]
    out_s = top_s[sel_i]
    good = jnp.isfinite(sel_s)
    out_b = jnp.where(good[:, None], out_b, out_b[0:1])
    out_s = jnp.where(good, out_s, out_s[0])
    return out_b, out_s


def _proposal_impl(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n,
                   rpn_post_nms_top_n, threshold, rpn_min_size, scales,
                   ratios, feature_stride, output_score):
    import jax
    jnp = _j()
    N, A2, H, W = cls_prob.shape
    A = A2 // 2
    anchors = _gen_anchors(H, W, feature_stride, scales, ratios)

    def one(cp, bp, info):
        # fg scores are the second half of the A2 channels
        sc = cp[A:].transpose(1, 2, 0).reshape(-1)        # (H*W*A,)
        dl = bp.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        return _proposal_one(sc, dl, info, anchors,
                             int(rpn_pre_nms_top_n),
                             int(rpn_post_nms_top_n), float(threshold),
                             float(rpn_min_size))

    boxes, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    batch_ids = jnp.broadcast_to(
        jnp.arange(N, dtype=boxes.dtype)[:, None, None],
        (N, boxes.shape[1], 1))
    rois = jnp.concatenate([batch_ids, boxes], axis=2).reshape(-1, 5)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


@register("_contrib_Proposal", aliases=("Proposal",), num_outputs=-1,
          no_grad=True)
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             feature_stride=16, output_score=False, iou_loss=False, **kw):
    """RPN proposal generation (reference: ``proposal.cc``)."""
    return _proposal_impl(cls_prob, bbox_pred, im_info,
                          rpn_pre_nms_top_n, rpn_post_nms_top_n,
                          threshold, rpn_min_size, scales, ratios,
                          feature_stride, output_score)


@register("_contrib_MultiProposal", aliases=("MultiProposal",),
          num_outputs=-1, no_grad=True)
def multi_proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
                   rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                   scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                   feature_stride=16, output_score=False, iou_loss=False,
                   **kw):
    """Batched RPN proposals (reference: ``multi_proposal.cc``)."""
    return _proposal_impl(cls_prob, bbox_pred, im_info,
                          rpn_pre_nms_top_n, rpn_post_nms_top_n,
                          threshold, rpn_min_size, scales, ratios,
                          feature_stride, output_score)


# ---------------------------------------------------------------------------
# Rotated ROIAlign
# ---------------------------------------------------------------------------

@register("_contrib_RROIAlign", aliases=("RROIAlign",), no_grad=True)
def rroi_align(data, rois, pooled_size=None, spatial_scale=1.0,
               sampling_ratio=2, **kw):
    """Rotated ROIAlign: rois (R, 6) = [batch, cx, cy, w, h, angle_deg];
    samples a rotated grid bilinearly and average-pools."""
    import jax
    jnp = _j()
    ph, pw = _pair(pooled_size)
    ns = max(1, int(sampling_ratio))

    def one(roi):
        img = data[roi[0].astype("int32")]
        cx = roi[1] * spatial_scale
        cy = roi[2] * spatial_scale
        w = roi[3] * spatial_scale
        h = roi[4] * spatial_scale
        theta = roi[5] * _np.pi / 180.0
        cos, sin = jnp.cos(theta), jnp.sin(theta)
        # local grid in roi frame, centered
        gy = (jnp.arange(ph * ns) + 0.5) / (ph * ns) - 0.5
        gx = (jnp.arange(pw * ns) + 0.5) / (pw * ns) - 0.5
        ly = gy[:, None] * h
        lx = gx[None, :] * w
        X = cx + lx * cos - ly * sin
        Y = cy + lx * sin + ly * cos
        v = _bilinear_gather(img, Y, X)                  # (C, phns, pwns)
        C = v.shape[0]
        return v.reshape(C, ph, ns, pw, ns).mean(axis=(2, 4))

    return jax.vmap(one)(rois)


# ---------------------------------------------------------------------------
# Box codecs + matching
# ---------------------------------------------------------------------------

@register("_contrib_box_encode", no_grad=True, num_outputs=-1)
def box_encode(samples, matches, anchors, refs, means=None, stds=None,
               **kw):
    """SSD target encoding (reference: bounding_box.cc BoxEncode):
    samples (B,N) 1=pos, matches (B,N) ref idx, anchors (B,N,4) corner,
    refs (B,M,4) → (targets (B,N,4), masks (B,N,4))."""
    jnp = _j()
    if means is None:
        means = (0.0, 0.0, 0.0, 0.0)
    if stds is None:
        stds = (0.1, 0.1, 0.2, 0.2)
    means = jnp.asarray(means)
    stds = jnp.asarray(stds)
    m = matches.astype("int32")
    ref = jnp.take_along_axis(refs, m[..., None], axis=1)  # (B,N,4)
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    ax = (anchors[..., 0] + anchors[..., 2]) / 2
    ay = (anchors[..., 1] + anchors[..., 3]) / 2
    rw = ref[..., 2] - ref[..., 0]
    rh = ref[..., 3] - ref[..., 1]
    rx = (ref[..., 0] + ref[..., 2]) / 2
    ry = (ref[..., 1] + ref[..., 3]) / 2
    t = jnp.stack([(rx - ax) / aw, (ry - ay) / ah,
                   jnp.log(jnp.maximum(rw / aw, 1e-12)),
                   jnp.log(jnp.maximum(rh / ah, 1e-12))], axis=-1)
    t = (t - means) / stds
    mask = jnp.broadcast_to((samples > 0.5)[..., None], t.shape) \
        .astype(t.dtype)
    return t * mask, mask


@register("_contrib_box_decode", no_grad=True)
def box_decode(data, anchors, std0=1.0, std1=1.0, std2=1.0, std3=1.0,
               clip=-1.0, format="corner", **kw):
    """Decode (B,N,4) deltas against (1,N,4) anchors (reference:
    bounding_box.cc BoxDecode)."""
    jnp = _j()
    from .vision import _to_corner
    a = _to_corner(anchors, format)
    aw = a[..., 2] - a[..., 0]
    ah = a[..., 3] - a[..., 1]
    ax = (a[..., 0] + a[..., 2]) / 2
    ay = (a[..., 1] + a[..., 3]) / 2
    dx = data[..., 0] * std0
    dy = data[..., 1] * std1
    dw = data[..., 2] * std2
    dh = data[..., 3] * std3
    cx = dx * aw + ax
    cy = dy * ah + ay
    if clip > 0:
        dw = jnp.minimum(dw, clip)
        dh = jnp.minimum(dh, clip)
    w = jnp.exp(dw) * aw
    h = jnp.exp(dh) * ah
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


@register("_contrib_bipartite_matching", aliases=("bipartite_matching",),
          num_outputs=2, no_grad=True)
def bipartite_matching(data, is_ascend=False, threshold=0.5, topk=-1,
                       **kw):
    """Greedy bipartite matching on a (..., N, M) score matrix
    (reference: bounding_box.cc BipartiteMatching): repeatedly take the
    globally best (row, col), mark both used.  Returns (row→col matches,
    col→row matches), -1 for unmatched."""
    import jax
    jnp = _j()
    sign = 1.0 if not is_ascend else -1.0

    def one(mat):
        N, M = mat.shape
        s = mat * sign
        thr = threshold * sign

        def body(state, _):
            s_cur, rmatch, cmatch = state
            flat = jnp.argmax(s_cur)
            i, j = flat // M, flat % M
            ok = s_cur[i, j] >= thr
            rmatch = jnp.where(ok, rmatch.at[i].set(j), rmatch)
            cmatch = jnp.where(ok, cmatch.at[j].set(i), cmatch)
            s_cur = jnp.where(ok, s_cur.at[i, :].set(-jnp.inf), s_cur)
            s_cur = jnp.where(ok, s_cur.at[:, j].set(-jnp.inf), s_cur)
            return (s_cur, rmatch, cmatch), None

        k = min(N, M) if topk < 0 else min(topk, min(N, M))
        init = (s, jnp.full((N,), -1.0, mat.dtype),
                jnp.full((M,), -1.0, mat.dtype))
        (s_f, rmatch, cmatch), _ = jax.lax.scan(body, init, None,
                                                length=k)
        return rmatch, cmatch

    batch_shape = data.shape[:-2]
    flat = data.reshape((-1,) + data.shape[-2:])
    r, c = jax.vmap(one)(flat)
    return (r.reshape(batch_shape + r.shape[1:]),
            c.reshape(batch_shape + c.shape[1:]))
