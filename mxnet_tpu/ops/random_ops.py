"""Random sampling kernels.

Reference: ``src/operator/random/`` (SURVEY.md §2.1).  Every sampler takes
its PRNG key as the first (auto-injected) input — see
``mxnet_tpu/random.py`` for how this preserves MXNet's stateful-RNG API on
JAX's functional keys.
"""
from __future__ import annotations

import numpy as _np

from .registry import register


def _j():
    import jax.numpy as jnp
    return jnp


def _dt(dtype):
    if dtype is None or dtype == "None":
        return "float32"
    return _np.dtype(dtype).name


@register("_random_uniform", aliases=("uniform", "random_uniform"),
          needs_rng=True, no_grad=True)
def random_uniform(key, low=0.0, high=1.0, shape=(1,), dtype=None, **kw):
    import jax
    if isinstance(shape, int):
        shape = (shape,)
    return jax.random.uniform(key, tuple(shape), dtype=_dt(dtype),
                              minval=low, maxval=high)


@register("_random_normal", aliases=("normal", "random_normal"),
          needs_rng=True, no_grad=True)
def random_normal(key, loc=0.0, scale=1.0, shape=(1,), dtype=None, **kw):
    import jax
    if isinstance(shape, int):
        shape = (shape,)
    return jax.random.normal(key, tuple(shape), dtype=_dt(dtype)) * scale \
        + loc


@register("_random_gamma", aliases=("random_gamma",), needs_rng=True,
          no_grad=True)
def random_gamma(key, alpha=1.0, beta=1.0, shape=(1,), dtype=None, **kw):
    import jax
    if isinstance(shape, int):
        shape = (shape,)
    return jax.random.gamma(key, alpha, tuple(shape),
                            dtype=_dt(dtype)) * beta


@register("_random_exponential", aliases=("random_exponential",),
          needs_rng=True, no_grad=True)
def random_exponential(key, lam=1.0, shape=(1,), dtype=None, **kw):
    import jax
    if isinstance(shape, int):
        shape = (shape,)
    return jax.random.exponential(key, tuple(shape), dtype=_dt(dtype)) / lam


@register("_random_poisson", aliases=("random_poisson",), needs_rng=True,
          no_grad=True)
def random_poisson(key, lam=1.0, shape=(1,), dtype=None, **kw):
    import jax
    if isinstance(shape, int):
        shape = (shape,)
    return jax.random.poisson(key, lam, tuple(shape)).astype(_dt(dtype))


@register("_random_negative_binomial",
          aliases=("random_negative_binomial",), needs_rng=True,
          no_grad=True)
def random_negative_binomial(key, k=1, p=1.0, shape=(1,), dtype=None, **kw):
    import jax
    if isinstance(shape, int):
        shape = (shape,)
    k1, k2 = jax.random.split(key)
    # NB(k, p) = Poisson(Gamma(k, (1-p)/p))
    lam = jax.random.gamma(k1, k, tuple(shape)) * ((1 - p) / p)
    return jax.random.poisson(k2, lam).astype(_dt(dtype))


@register("_random_randint", aliases=("randint", "random_randint"),
          needs_rng=True, no_grad=True)
def random_randint(key, low=0, high=1, shape=(1,), dtype="int32", **kw):
    import jax
    if isinstance(shape, int):
        shape = (shape,)
    return jax.random.randint(key, tuple(shape), int(low), int(high),
                              dtype=_np.dtype(dtype or "int32").name)


@register("_sample_multinomial", aliases=("sample_multinomial",),
          needs_rng=True, no_grad=True)
def sample_multinomial(key, data, shape=1, get_prob=False, dtype="int32",
                       **kw):
    import jax
    jnp = _j()
    n = shape if isinstance(shape, int) else int(_np.prod(shape))
    logits = jnp.log(jnp.maximum(data, 1e-38))
    if data.ndim == 1:
        samples = jax.random.categorical(key, logits, shape=(n,))
        out = samples if n > 1 else samples.reshape(())
    else:
        samples = jax.random.categorical(key, logits[:, None, :], axis=-1,
                                         shape=(data.shape[0], n))
        out = samples if n > 1 else samples.reshape((data.shape[0],))
    out = out.astype(_np.dtype(dtype).name)
    if get_prob:
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1),
            out.astype("int32").reshape(data.shape[:-1] + (-1,)), axis=-1)
        return (out, lp.reshape(out.shape))
    return out


@register("_shuffle", aliases=("shuffle",), needs_rng=True, no_grad=True)
def shuffle(key, data, **kw):
    import jax
    return jax.random.permutation(key, data, axis=0)


@register("_sample_unique_zipfian", needs_rng=True, no_grad=True)
def sample_unique_zipfian(key, range_max=None, shape=(1,), **kw):
    import jax
    jnp = _j()
    if isinstance(shape, int):
        shape = (shape,)
    u = jax.random.uniform(key, tuple(shape))
    out = (jnp.exp(u * jnp.log(range_max + 1.0)) - 1.0).astype("int64")
    return jnp.clip(out, 0, range_max - 1)


def _param_sample(name, sampler):
    """sample_* family: per-element distribution parameters as arrays."""
    @register(name, needs_rng=True, no_grad=True)
    def impl(key, *params, shape=None, dtype=None, **kw):
        import jax
        if shape in (None, ()):
            extra = ()
        elif isinstance(shape, int):
            extra = (shape,)
        else:
            extra = tuple(shape)
        out_shape = params[0].shape + extra
        return sampler(jax, key, params, out_shape).astype(_dt(dtype))
    impl.__name__ = name
    return impl


def _expand(p, out_shape):
    jnp = _j()
    return jnp.broadcast_to(
        p.reshape(p.shape + (1,) * (len(out_shape) - p.ndim)), out_shape)


_param_sample(
    "_sample_uniform",
    lambda jax, key, ps, s: jax.random.uniform(key, s) *
    (_expand(ps[1], s) - _expand(ps[0], s)) + _expand(ps[0], s))
_param_sample(
    "_sample_normal",
    lambda jax, key, ps, s: jax.random.normal(key, s) * _expand(ps[1], s) +
    _expand(ps[0], s))
_param_sample(
    "_sample_gamma",
    lambda jax, key, ps, s: jax.random.gamma(key, _expand(ps[0], s), s) *
    _expand(ps[1], s))
_param_sample(
    "_sample_exponential",
    lambda jax, key, ps, s: jax.random.exponential(key, s) /
    _expand(ps[0], s))
_param_sample(
    "_sample_poisson",
    lambda jax, key, ps, s: jax.random.poisson(
        key, _expand(ps[0], s), s).astype("float32"))


_param_sample(
    "_sample_negative_binomial",
    lambda jax, key, ps, s: jax.random.poisson(
        jax.random.split(key)[1],
        jax.random.gamma(jax.random.split(key)[0], _expand(ps[0], s), s)
        * (1 - _expand(ps[1], s)) / _expand(ps[1], s)).astype("float32"))


def _gnb_sampler(jax, key, ps, s):
    # GNB(mu, alpha) = Poisson(Gamma(1/alpha, alpha*mu)); alpha->0 is
    # plain Poisson(mu) (reference: sample_op.cc GeneralizedNegativeBinomial)
    jnp = _j()
    k1, k2 = jax.random.split(key)
    m, a = _expand(ps[0], s), _expand(ps[1], s)
    safe_a = jnp.maximum(a, 1e-8)
    lam = jax.random.gamma(k1, 1.0 / safe_a, s) * safe_a * m
    return jax.random.poisson(k2, jnp.where(a < 1e-8, m, lam)) \
        .astype("float32")


_param_sample("_sample_generalized_negative_binomial", _gnb_sampler)


@register("_random_generalized_negative_binomial",
          aliases=("random_generalized_negative_binomial",),
          needs_rng=True, no_grad=True)
def random_generalized_negative_binomial(key, mu=1.0, alpha=1.0,
                                         shape=(1,), dtype=None, **kw):
    import jax
    jnp = _j()
    if isinstance(shape, int):
        shape = (shape,)
    k1, k2 = jax.random.split(key)
    if alpha < 1e-8:
        lam = jnp.full(tuple(shape), mu)
    else:
        lam = jax.random.gamma(k1, 1.0 / alpha, tuple(shape)) * alpha * mu
    return jax.random.poisson(k2, lam).astype(_dt(dtype))
