"""Shape-manipulation / indexing / layout kernels.

Reference: ``src/operator/tensor/matrix_op.cc`` (reshape, transpose, slice,
concat, …), ``indexing_op.cc`` (take, pick, gather_nd, scatter_nd, one_hot,
Embedding-adjacent ops), ``init_op.cc`` (SURVEY.md §2.1).  MXNet special
reshape codes (0, -1, -2, -3, -4) are implemented to spec.
"""
from __future__ import annotations

import numpy as _np

from .registry import register
from ..base import MXNetError


def _j():
    import jax.numpy as jnp
    return jnp


def _mx_reshape_shape(src_shape, target):
    """Implements MXNet reshape's special codes:
    0 copy dim, -1 infer, -2 copy rest, -3 merge two, -4 split (with -1
    allowed inside the split pair)."""
    src = list(src_shape)
    out = []
    i = 0  # cursor into src
    t = list(target)
    k = 0
    while k < len(t):
        d = t[k]
        if d == 0:
            out.append(src[i]); i += 1
        elif d == -1:
            out.append(-1); i += 1
        elif d == -2:
            out.extend(src[i:]); i = len(src)
        elif d == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif d == -4:
            a, b = t[k + 1], t[k + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; k += 2
        else:
            out.append(d)
            i += 1
        k += 1
    # fix up -1 inference
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in src_shape:
            total *= d
        out[out.index(-1)] = total // known
    return tuple(out)


@register("reshape", aliases=("Reshape",))
def reshape(data, shape=None, reverse=False, **kw):
    if shape is None:
        raise MXNetError("reshape requires shape")
    if isinstance(shape, int):
        shape = (shape,)
    tgt = _mx_reshape_shape(data.shape, tuple(shape))
    return data.reshape(tgt)


@register("reshape_like")
def reshape_like(lhs, rhs, **kw):
    return lhs.reshape(rhs.shape)


@register("shape_array", no_grad=True)
def shape_array(data, **kw):
    return _j().asarray(data.shape, dtype="int64")


@register("size_array", no_grad=True)
def size_array(data, **kw):
    return _j().asarray([data.size], dtype="int64")


@register("transpose")
def transpose(data, axes=None, **kw):
    jnp = _j()
    if axes is None or axes == ():
        return jnp.transpose(data)
    return jnp.transpose(data, axes)


@register("swapaxes", aliases=("SwapAxis",))
def swapaxes(data, dim1=0, dim2=0, **kw):
    return _j().swapaxes(data, dim1, dim2)


@register("expand_dims")
def expand_dims(data, axis=0, **kw):
    return _j().expand_dims(data, axis)


@register("squeeze")
def squeeze(data, axis=None, **kw):
    return _j().squeeze(data, axis=axis)


@register("Flatten", aliases=("flatten",))
def flatten(data, **kw):
    return data.reshape((data.shape[0], -1))


@register("flip", aliases=("reverse",))
def flip(data, axis=None, **kw):
    return _j().flip(data, axis=axis)


@register("tile")
def tile(data, reps=None, **kw):
    return _j().tile(data, reps)


@register("repeat")
def repeat(data, repeats=1, axis=None, **kw):
    return _j().repeat(data, repeats, axis=axis)


@register("broadcast_to")
def broadcast_to(data, shape=None, **kw):
    jnp = _j()
    # MXNet allows 0 meaning "keep this dim".  Rank growth (numpy/ONNX
    # Expand style) right-aligns the input dims: the old same-rank zip
    # silently misaligned the 0-rule for longer targets.
    tgt = list(shape)
    lead = len(tgt) - data.ndim
    if lead < 0:
        raise MXNetError(
            "broadcast_to: target rank %d < data rank %d"
            % (len(tgt), data.ndim))
    for i, d in enumerate(data.shape):
        if tgt[lead + i] == 0:
            tgt[lead + i] = d
    return jnp.broadcast_to(data, tuple(tgt))


@register("_onnx_expand")
def _onnx_expand(data, shape=None, **kw):
    """ONNX Expand semantics (importer-internal): BIDIRECTIONAL
    numpy-style broadcast of data against the target shape — a target
    dim of 1 keeps the larger input dim, and either side may have the
    smaller rank (unlike MXNet broadcast_to, whose target must
    dominate)."""
    jnp = _j()
    tgt = jnp.broadcast_shapes(tuple(data.shape), tuple(shape))
    return jnp.broadcast_to(data, tgt)


@register("broadcast_like")
def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None, **kw):
    return _j().broadcast_to(lhs, rhs.shape)


@register("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(data, axis=None, size=None, **kw):
    jnp = _j()
    if axis is None:
        return data
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


@register("Concat", aliases=("concat",), variadic=True)
def concat_op(data, dim=1, num_args=None, **kw):
    return _j().concatenate(data, axis=dim)


@register("stack", variadic=True)
def stack_op(data, axis=0, num_args=None, **kw):
    return _j().stack(data, axis=axis)


@register("split", aliases=("SliceChannel",), num_outputs=-1)
def split(data, num_outputs=None, axis=1, squeeze_axis=False, **kw):
    jnp = _j()
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("split_v2", num_outputs=-1)
def split_v2(data, indices_or_sections=None, axis=0, squeeze_axis=False,
             sections=0, **kw):
    jnp = _j()
    if sections and not indices_or_sections:
        indices_or_sections = sections
    parts = jnp.split(data, indices_or_sections, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("slice", aliases=("crop",))
def slice_op(data, begin=None, end=None, step=None, **kw):
    idx = []
    step = step or [None] * len(begin)
    for b, e, s in zip(begin, end, step):
        idx.append(builtins_slice(b, e, s))
    return data[tuple(idx)]


def builtins_slice(b, e, s):
    return slice(b, e, s)


@register("slice_axis")
def slice_axis(data, axis=0, begin=0, end=None, **kw):
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]


@register("slice_like")
def slice_like(data, shape_like, axes=(), **kw):
    idx = [slice(None)] * data.ndim
    if not axes:
        axes = range(min(data.ndim, shape_like.ndim))
    for a in axes:
        idx[a] = slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@register("take")
def take(a, indices, axis=0, mode="clip", **kw):
    jnp = _j()
    idx = indices.astype("int32")
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    else:
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    return jnp.take(a, idx, axis=axis)


@register("pick")
def pick(data, index, axis=-1, keepdims=False, mode="clip", **kw):
    jnp = _j()
    idx = jnp.clip(index.astype("int32"), 0, data.shape[axis] - 1)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("gather_nd")
def gather_nd(data, indices, **kw):
    jnp = _j()
    idx = indices.astype("int32")
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register("scatter_nd", no_grad=False)
def scatter_nd(data, indices, shape=None, **kw):
    jnp = _j()
    idx = indices.astype("int32")
    m = idx.shape[0]
    out = jnp.zeros(shape, dtype=data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].add(data)


@register("one_hot", no_grad=True)
def one_hot(indices, depth=None, on_value=1.0, off_value=0.0,
            dtype="float32", **kw):
    import jax
    oh = jax.nn.one_hot(indices.astype("int32"), depth,
                        dtype=_np.dtype(dtype).name)
    return oh * (on_value - off_value) + off_value


@register("SequenceMask")
def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0, **kw):
    jnp = _j()
    if not use_sequence_length or sequence_length is None:
        return data
    T = data.shape[axis]
    steps = jnp.arange(T)
    bshape = [1] * data.ndim
    bshape[axis] = T
    steps = steps.reshape(bshape)
    batch_axis = 1 if axis == 0 else 0
    lshape = [1] * data.ndim
    lshape[batch_axis] = data.shape[batch_axis]
    lens = sequence_length.reshape(lshape)
    mask = steps < lens
    return jnp.where(mask, data, jnp.asarray(value, dtype=data.dtype))


@register("SequenceLast")
def sequence_last(data, sequence_length=None, use_sequence_length=False,
                  axis=0, **kw):
    jnp = _j()
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype("int32") - 1)
    moved = jnp.moveaxis(data, axis, 0)
    batch = moved.shape[1]
    return moved[last, jnp.arange(batch)]


@register("SequenceReverse")
def sequence_reverse(data, sequence_length=None, use_sequence_length=False,
                     axis=0, **kw):
    jnp = _j()
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    moved = jnp.moveaxis(data, axis, 0)
    T = moved.shape[0]
    lens = sequence_length.astype("int32")
    t = jnp.arange(T)[:, None]
    src = jnp.where(t < lens[None, :], lens[None, :] - 1 - t, t)
    rev = jnp.take_along_axis(
        moved, src.reshape(src.shape + (1,) * (moved.ndim - 2)), axis=0)
    return jnp.moveaxis(rev, 0, axis)


@register("pad", aliases=("Pad",))
def pad(data, mode="constant", pad_width=None, constant_value=0.0, **kw):
    jnp = _j()
    pw = list(zip(pad_width[::2], pad_width[1::2]))
    if mode == "constant":
        return jnp.pad(data, pw, constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(data, pw, mode="edge")
    if mode == "reflect":
        return jnp.pad(data, pw, mode="reflect")
    raise MXNetError("unknown pad mode %r" % mode)


@register("diag")
def diag(data, k=0, axis1=0, axis2=1, **kw):
    jnp = _j()
    if data.ndim == 1:
        return jnp.diag(data, k)
    return jnp.diagonal(data, offset=k, axis1=axis1, axis2=axis2)


@register("zeros_like")
def zeros_like(data, **kw):
    return _j().zeros_like(data)


@register("ones_like")
def ones_like(data, **kw):
    return _j().ones_like(data)


@register("_full_like")
def full_like(data, fill_value=0.0, **kw):
    return _j().full_like(data, fill_value)


@register("_zeros", no_grad=True)
def _zeros(shape=None, dtype="float32", **kw):
    return _j().zeros(shape, dtype=_np.dtype(dtype or "float32").name)


@register("_ones", no_grad=True)
def _ones(shape=None, dtype="float32", **kw):
    return _j().ones(shape, dtype=_np.dtype(dtype or "float32").name)


@register("_arange", no_grad=True)
def _arange(start=0, stop=None, step=1.0, repeat=1, dtype="float32", **kw):
    jnp = _j()
    out = jnp.arange(start, stop, step, dtype=_np.dtype(dtype).name)
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_eye", no_grad=True)
def _eye(N=0, M=0, k=0, dtype="float32", **kw):
    return _j().eye(int(N), int(M) if M else None, k=int(k),
                    dtype=_np.dtype(dtype).name)


@register("_linspace", no_grad=True)
def _linspace(start=0, stop=1, num=50, endpoint=True, dtype="float32", **kw):
    return _j().linspace(start, stop, int(num), endpoint=endpoint,
                         dtype=_np.dtype(dtype).name)


@register("space_to_depth")
def space_to_depth(data, block_size=1, **kw):
    jnp = _j()
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return x.reshape(n, c * b * b, h // b, w // b)


@register("depth_to_space")
def depth_to_space(data, block_size=1, **kw):
    jnp = _j()
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = jnp.transpose(x, (0, 3, 4, 1, 5, 2))
    return x.reshape(n, c // (b * b), h * b, w * b)


@register("batch_take", aliases=("choose_element_0index",
                                 "_choose_element_0index"))
def batch_take(a, indices, **kw):
    """Per-row element pick: out[i] = a[i, indices[i]] (reference:
    ``indexing_op.cc`` batch_take; legacy alias
    ``choose_element_0index``)."""
    jnp = _j()
    idx = indices.astype("int32")
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@register("unravel_index", aliases=("_unravel_index",), no_grad=True)
def unravel_index(data, shape=None, **kw):
    """Flat indices → coordinate matrix (D, N) (reference:
    ``ravel.cc``)."""
    jnp = _j()
    coords = jnp.unravel_index(data.astype("int32").reshape(-1),
                               tuple(shape))
    out = jnp.stack(coords, axis=0)
    return out.reshape((len(shape),) + data.shape)


@register("ravel_multi_index", aliases=("_ravel_multi_index",),
          no_grad=True)
def ravel_multi_index(data, shape=None, **kw):
    """Coordinate matrix (D, N) → flat indices (reference:
    ``ravel.cc``)."""
    jnp = _j()
    strides = _np.concatenate(
        [_np.cumprod(list(shape)[::-1])[::-1][1:], [1]]).astype("int32")
    return jnp.sum(data.astype("int32") *
                   jnp.asarray(strides)[:, None], axis=0)


@register("_contrib_arange_like", aliases=("arange_like",), no_grad=True)
def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None, **kw):
    """arange shaped like (an axis of) the input — shape-polymorphic
    graphs without host round-trips (reference:
    ``contrib/arange_like``)."""
    jnp = _j()
    repeat = int(repeat)

    def ramp(n):
        # each value repeated `repeat` times within the n elements
        vals = start + step * jnp.arange(-(-n // repeat), dtype="float32")
        return jnp.repeat(vals, repeat)[:n] if repeat != 1 else vals

    if axis is None:
        n = 1
        for s in data.shape:
            n *= s
        return ramp(n).reshape(data.shape)
    return ramp(data.shape[axis])


@register("_contrib_index_copy", aliases=("index_copy",))
def index_copy(old, index, new, **kw):
    """out = old with rows at ``index`` replaced by ``new`` (reference:
    ``contrib/index_copy.cc``)."""
    return old.at[index.astype("int32")].set(new.astype(old.dtype))


@register("_contrib_index_array", aliases=("index_array",), no_grad=True)
def index_array(data, axes=None, **kw):
    """Index-coordinate tensor of the input's shape (reference:
    ``contrib/index_array.cc``): out[..., k] = coordinate along axes[k]."""
    jnp = _j()
    nd_ = data.ndim
    sel = tuple(axes) if axes is not None else tuple(range(nd_))
    grids = jnp.meshgrid(*[jnp.arange(s) for s in data.shape],
                         indexing="ij")
    return jnp.stack([grids[a] for a in sel], axis=-1).astype("int32")


@register("_contrib_boolean_mask", aliases=("boolean_mask",))
def boolean_mask(data, index, axis=0, **kw):
    """Rows of ``data`` where ``index`` is nonzero (reference:
    ``contrib/boolean_mask.cc``).

    TPU note: the output length is data-dependent — a dynamic shape XLA
    cannot compile.  Eager mode materializes the compacted result on
    host (matching the reference's output exactly); under jit/hybridize
    use masking (``where``) or ``np.nonzero``-free formulations instead
    (SURVEY.md §7 hard-part #5: dynamic shapes are the documented
    TPU-hostile corner)."""
    import jax
    jnp = _j()
    try:
        idx = _np.asarray(jax.device_get(index)).astype(bool)
    except jax.errors.TracerArrayConversionError:
        raise MXNetError(
            "boolean_mask has a data-dependent output shape and cannot "
            "run under jit/hybridize on TPU; restructure with nd.where "
            "masking (see op docstring)")
    keep = _np.nonzero(idx)[0]
    return jnp.take(data, jnp.asarray(keep), axis=axis)


@register("fill_element_0index", aliases=("_fill_element_0index",))
def fill_element_0index(lhs, mhs, rhs, **kw):
    """out = lhs with out[i, rhs[i]] = mhs[i] (reference legacy op)."""
    jnp = _j()
    idx = rhs.astype("int32")
    rows = jnp.arange(lhs.shape[0])
    return lhs.at[rows, idx].set(mhs.astype(lhs.dtype))
