"""Operator library — importing this package registers every op.

Reference: the nnvm registry populated by static initializers in
``src/operator/*`` (SURVEY.md §2.1).  Python stubs for the ``nd``/``sym``
namespaces are generated from this registry at import time
(reference: ``python/mxnet/ndarray/register.py``).
"""
from . import registry
from .registry import get_op, list_ops, invoke, register, OpDef

from . import elemwise      # noqa: F401
from . import creation_ops  # noqa: F401
from . import reduce        # noqa: F401
from . import shape_ops     # noqa: F401
from . import nn            # noqa: F401
from . import random_ops    # noqa: F401
from . import linalg        # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import rnn_op        # noqa: F401
from . import quantization  # noqa: F401
from . import vision        # noqa: F401
from . import vision_ext    # noqa: F401
from . import contrib_misc  # noqa: F401
from .. import operator     # noqa: F401  (registers the "Custom" op)
