"""Neural-network operator kernels.

Reference: ``src/operator/nn/`` — convolution, fully_connected, pooling,
activation, batch/layer/instance/group norm, dropout, softmax family,
embedding (SURVEY.md §2.1 "Operator library").  The cuDNN/oneDNN bridges of
the reference dissolve: XLA's convolution/matmul emitters target the MXU
directly, and elementwise epilogues (bias, relu, BN scale) are fused by XLA
rather than by hand-written vendor-library glue.

Layout note: the API preserves MXNet's NCHW/NCW/NCDHW default layouts;
XLA's layout assignment re-tiles internally for the MXU, so no NHWC
conversion is forced on the user.
"""
from __future__ import annotations

import numpy as _np

from .registry import register
from ..base import MXNetError


def _j():
    import jax.numpy as jnp
    return jnp


def _jax():
    import jax
    return jax


# ---------------------------------------------------------------------------
# FullyConnected / dot / batch_dot — the MXU ops
# ---------------------------------------------------------------------------

@register("FullyConnected")
def fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True, **kw):
    jnp = _j()
    x = data
    if flatten and x.ndim > 2:
        x = x.reshape((x.shape[0], -1))
    out = jnp.matmul(x, weight.T)
    if bias is not None and not no_bias:
        out = out + bias
    return out


@register("dot")
def dot(lhs, rhs, transpose_a=False, transpose_b=False, **kw):
    jnp = _j()
    a = lhs.T if transpose_a and lhs.ndim == 2 else lhs
    b = rhs.T if transpose_b and rhs.ndim == 2 else rhs
    if transpose_a and lhs.ndim > 2:
        a = jnp.moveaxis(lhs, list(range(lhs.ndim)),
                         list(range(lhs.ndim))[::-1])
    if transpose_b and rhs.ndim > 2:
        b = jnp.moveaxis(rhs, list(range(rhs.ndim)),
                         list(range(rhs.ndim))[::-1])
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    # MXNet dot: contract last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot")
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False, **kw):
    jnp = _j()
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@register("khatri_rao", variadic=True)
def khatri_rao(args, **kw):
    jnp = _j()
    out = args[0]
    for m in args[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(
            (-1,) + out.shape[1:])
    return out


# ---------------------------------------------------------------------------
# Convolution family
# ---------------------------------------------------------------------------

def _tup(v, n):
    if v is None:
        return (0,) * n if n else ()
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def _conv_dims(kernel):
    return len(kernel) if not isinstance(kernel, int) else 1


@register("Convolution")
def convolution(data, weight, bias=None, kernel=None, stride=None,
                dilate=None, pad=None, num_filter=None, num_group=1,
                no_bias=False, layout=None, cudnn_tune=None,
                cudnn_off=False, workspace=1024, **kw):
    """ND convolution, NC(D)HW layout (reference:
    ``src/operator/nn/convolution.cc``).  Lowers to
    ``lax.conv_general_dilated`` → XLA conv emitter → MXU."""
    jax = _jax()
    nd = _conv_dims(kernel)
    stride = _tup(stride or 1, nd)
    dilate = _tup(dilate or 1, nd)
    pad = _tup(pad or 0, nd)
    spatial = "DHW"[-nd:] if nd <= 3 else None
    if spatial is None:
        raise MXNetError("Convolution supports 1/2/3 spatial dims")
    lhs_spec = "NC" + spatial
    rhs_spec = "OI" + spatial
    out = jax.lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=(lhs_spec, rhs_spec, lhs_spec),
        feature_group_count=num_group,
        preferred_element_type=None)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@register("Deconvolution")
def deconvolution(data, weight, bias=None, kernel=None, stride=None,
                  dilate=None, pad=None, adj=None, target_shape=None,
                  num_filter=None, num_group=1, no_bias=True, layout=None,
                  **kw):
    """Transposed convolution (reference: ``deconvolution.cc``)."""
    jax = _jax()
    jnp = _j()
    nd = _conv_dims(kernel)
    stride = _tup(stride or 1, nd)
    dilate = _tup(dilate or 1, nd)
    pad = _tup(pad or 0, nd)
    adj = _tup(adj or 0, nd)
    spatial = "DHW"[-nd:]
    lhs_spec = "NC" + spatial
    rhs_spec = "IO" + spatial  # deconv weight is (in, out/g, *k) in MXNet
    kdims = weight.shape[2:]
    # transposed conv = gradient of conv: spatially flip the kernel (conv
    # vs correlation) and use grad-of-conv padding e-1-p with lhs dilation
    weight = jnp.flip(weight, axis=tuple(range(2, weight.ndim)))
    pads = []
    for k_, d_, p_, s_, a_ in zip(kdims, dilate, pad, stride, adj):
        e = (k_ - 1) * d_ + 1
        lo = e - 1 - p_
        hi = e - 1 - p_ + a_
        pads.append((lo, hi))
    out = jax.lax.conv_general_dilated(
        data, weight,
        window_strides=(1,) * nd,
        padding=pads,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=(lhs_spec, rhs_spec, lhs_spec),
        feature_group_count=num_group)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@register("Pooling")
def pooling(data, kernel=None, pool_type="max", global_pool=False,
            stride=None, pad=None, pooling_convention="valid",
            count_include_pad=True, layout=None, cudnn_off=False, p_value=2,
            **kw):
    """Max/avg/sum/lp pooling (reference: ``pooling.cc``)."""
    jax = _jax()
    jnp = _j()
    nd = data.ndim - 2
    if nd < 1:
        raise MXNetError("Pooling: data must be 3-D/4-D/5-D (N, C, "
                         "spatial...), got %d-D" % data.ndim)
    if global_pool:
        ax = tuple(range(2, data.ndim))
        if pool_type == "max":
            return jnp.max(data, axis=ax, keepdims=True)
        if pool_type == "avg":
            return jnp.mean(data, axis=ax, keepdims=True)
        if pool_type == "lp":
            # p-norm over the whole spatial extent, matching the
            # windowed lp branch below (reference pooling.cc)
            return jnp.power(
                jnp.sum(jnp.power(jnp.abs(data), p_value), axis=ax,
                        keepdims=True), 1.0 / p_value)
        return jnp.sum(data, axis=ax, keepdims=True)
    if not kernel:
        # reference pooling.cc requires the kernel for non-global
        # pooling; a defaulted empty kernel would silently reduce over
        # a 1x..x1 window (identity), whose select-and-scatter VJP is
        # additionally backend-divergent for degenerate windows
        raise MXNetError("Pooling: kernel is required unless "
                         "global_pool=True")
    kernel = _tup(kernel, nd)
    stride = _tup(stride or 1, nd)
    pad = _tup(pad or 0, nd)
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if pooling_convention == "full":
        # ceil-mode: add extra high padding so last window fits
        extra = []
        for i, (k_, s_, p_) in enumerate(zip(kernel, stride, pad)):
            size = data.shape[2 + i]
            out_full = -(-(size + 2 * p_ - k_) // s_) + 1
            needed = (out_full - 1) * s_ + k_ - size - p_
            extra.append(max(needed, p_))
        pads = ((0, 0), (0, 0)) + tuple(
            (p, e) for p, e in zip(pad, extra))
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else \
            jnp.iinfo(data.dtype).min
        return jax.lax.reduce_window(data, init, jax.lax.max, window,
                                     strides, pads)
    if pool_type in ("avg", "sum"):
        summed = jax.lax.reduce_window(data, 0.0, jax.lax.add, window,
                                       strides, pads)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = 1.0
            for k_ in kernel:
                denom *= k_
            return summed / denom
        ones = jnp.ones_like(data)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                       strides, pads)
        return summed / counts
    if pool_type == "lp":
        powd = jnp.power(jnp.abs(data), p_value)
        summed = jax.lax.reduce_window(powd, 0.0, jax.lax.add, window,
                                       strides, pads)
        return jnp.power(summed, 1.0 / p_value)
    raise MXNetError("unknown pool_type %r" % pool_type)


@register("UpSampling", variadic=True)
def upsampling(data, scale=1, sample_type="nearest", num_args=1,
               num_filter=0, multi_input_mode="concat", **kw):
    jnp = _j()
    outs = []
    for d in data:
        n, c, h, w = d.shape
        x = jnp.repeat(jnp.repeat(d, scale, axis=2), scale, axis=3)
        outs.append(x)
    if len(outs) == 1:
        return outs[0]
    if multi_input_mode == "sum":
        out = outs[0]
        for o in outs[1:]:
            out = out + o
        return out
    return jnp.concatenate(outs, axis=1)


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------

@register("Activation")
def activation(data, act_type="relu", **kw):
    jax = _jax()
    jnp = _j()
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return data / (1 + jnp.abs(data))
    raise MXNetError("unknown act_type %r" % act_type)


@register("LeakyReLU")
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334, _key=None, **kw):
    jax = _jax()
    jnp = _j()
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        g = gamma
        if g.ndim == 1 and data.ndim > 1:
            g = g.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, lam = 1.6732632423543772, 1.0507009873554805
        return lam * jnp.where(data >= 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, mid * data)
    raise MXNetError("unknown act_type %r" % act_type)


@register("softmax")
def softmax(data, axis=-1, temperature=None, length=None,
            use_length=False, dtype=None, **kw):
    jax = _jax()
    jnp = _j()
    x = data
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    if use_length and length is not None:
        steps = jnp.arange(x.shape[axis])
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        mask = steps.reshape(shape) < jnp.expand_dims(length, axis)
        x = jnp.where(mask, x, -jnp.inf)
    out = jax.nn.softmax(x, axis=axis)
    if use_length and length is not None:
        out = jnp.where(mask, out, 0.0)
    if dtype is not None:
        out = out.astype(_np.dtype(dtype).name)
    return out


@register("log_softmax")
def log_softmax(data, axis=-1, temperature=None, dtype=None, **kw):
    jax = _jax()
    x = data if not temperature or temperature == 1.0 else data / temperature
    out = jax.nn.log_softmax(x, axis=axis)
    if dtype is not None:
        out = out.astype(_np.dtype(dtype).name)
    return out


@register("softmin")
def softmin(data, axis=-1, **kw):
    return softmax(-data, axis=axis)


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label, **kw):
    jax = _jax()
    jnp = _j()
    logp = jax.nn.log_softmax(data, axis=-1)
    lab = label.astype("int32")
    picked = jnp.take_along_axis(logp, lab[:, None], axis=-1)
    return -jnp.sum(picked)


# ---------------------------------------------------------------------------
# Output heads with fused-loss gradients (reference semantics: the backward
# of SoftmaxOutput is (p - onehot)/N, not the gradient of its forward).
# Implemented with jax.custom_vjp to preserve those exact semantics.
# ---------------------------------------------------------------------------

@register("SoftmaxOutput", aliases=("Softmax",))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False,
                   preserve_shape=False, normalization="null",
                   out_grad=False, smooth_alpha=0.0, **kw):
    jax = _jax()
    jnp = _j()

    attrs = dict(grad_scale=grad_scale, ignore_label=ignore_label,
                 multi_output=multi_output, use_ignore=use_ignore,
                 normalization=normalization, smooth_alpha=smooth_alpha)

    @jax.custom_vjp
    def _so(x, lab):
        if multi_output:
            return jax.nn.softmax(x, axis=1)
        return jax.nn.softmax(x, axis=-1)

    def _fwd(x, lab):
        return _so(x, lab), (x, lab)

    def _bwd(res, g):
        x, lab = res
        axis = 1 if multi_output else -1
        p = jax.nn.softmax(x, axis=axis)
        k = x.shape[axis]
        labi = lab.astype("int32")
        oh = jax.nn.one_hot(labi, k, dtype=x.dtype, axis=axis)
        if smooth_alpha:
            oh = oh * (1 - smooth_alpha) + smooth_alpha / (k - 1) * (1 - oh)
        grad = p - oh
        if use_ignore:
            mask = (lab != ignore_label).astype(x.dtype)
            grad = grad * jnp.expand_dims(mask, axis)
        scale = grad_scale
        if normalization == "batch":
            scale = scale / x.shape[0]
        elif normalization == "valid":
            # reference: valid = count of non-ignored labels under
            # use_ignore, else every label position counts
            if use_ignore:
                valid = jnp.maximum(jnp.sum(lab != ignore_label), 1)
            else:
                valid = lab.size
            scale = scale / valid
        grad = grad * scale
        return (grad.astype(x.dtype), jnp.zeros_like(lab))

    _so.defvjp(_fwd, _bwd)
    return _so(data, label)


@register("LinearRegressionOutput")
def linear_regression_output(data, label, grad_scale=1.0, **kw):
    jax = _jax()
    jnp = _j()

    @jax.custom_vjp
    def _lro(x, lab):
        return x

    def _fwd(x, lab):
        return x, (x, lab)

    def _bwd(res, g):
        x, lab = res
        n = x.shape[0]
        grad = (x - lab.reshape(x.shape)) * (grad_scale / n)
        return (grad, jnp.zeros_like(lab))

    _lro.defvjp(_fwd, _bwd)
    return _lro(data, label)


@register("LogisticRegressionOutput")
def logistic_regression_output(data, label, grad_scale=1.0, **kw):
    jax = _jax()
    jnp = _j()

    @jax.custom_vjp
    def _lro(x, lab):
        return jax.nn.sigmoid(x)

    def _fwd(x, lab):
        return jax.nn.sigmoid(x), (x, lab)

    def _bwd(res, g):
        x, lab = res
        n = x.shape[0]
        grad = (jax.nn.sigmoid(x) - lab.reshape(x.shape)) * (grad_scale / n)
        return (grad, jnp.zeros_like(lab))

    _lro.defvjp(_fwd, _bwd)
    return _lro(data, label)


@register("MAERegressionOutput")
def mae_regression_output(data, label, grad_scale=1.0, **kw):
    jax = _jax()
    jnp = _j()

    @jax.custom_vjp
    def _mro(x, lab):
        return x

    def _fwd(x, lab):
        return x, (x, lab)

    def _bwd(res, g):
        x, lab = res
        n = x.shape[0]
        grad = jnp.sign(x - lab.reshape(x.shape)) * (grad_scale / n)
        return (grad, jnp.zeros_like(lab))

    _mro.defvjp(_fwd, _bwd)
    return _mro(data, label)


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------

@register("BatchNorm", aliases=("BatchNorm_v1",), mutate=(3, 4),
          training_aware=True)
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False,
               _training=False, **kw):
    """Batch normalization with running-stat mutation (reference:
    ``batch_norm.cc``; aux-state update is the mutate=(3,4) contract)."""
    jnp = _j()
    red_ax = tuple(i for i in range(data.ndim) if i != axis)
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]

    g = jnp.ones_like(gamma) if fix_gamma else gamma

    # cuDNN-BN-style mixed precision: low-precision (bf16/f16) I/O is
    # fine, but statistics and running-stat updates accumulate in f32 —
    # bf16's 8-bit mantissa rounds away small momentum updates.
    f32 = jnp.float32

    if _training and not use_global_stats:
        if data.dtype == f32:
            mean = jnp.mean(data, axis=red_ax)
            var = jnp.var(data, axis=red_ax)
        else:
            # Single-pass f32 moments with the cast fused into each
            # reduction (a shared materialized f32 copy of the
            # activations costs ~10% ResNet-50 train throughput).
            # Squares are computed in f32 — bf16 squares lose the
            # mantissa and f16 squares overflow — and the E[x²]−E[x]²
            # form is clamped: its f32 cancellation only becomes
            # visible for |mean|/std ≳ 300 (pathological for BN
            # inputs), degrading variance accuracy there, never NaN.
            mean = jnp.mean(data, axis=red_ax, dtype=f32)
            ex2 = jnp.mean(jnp.square(data.astype(f32)), axis=red_ax)
            var = jnp.maximum(ex2 - mean * mean, 0.0)
        new_mean = (moving_mean.astype(f32) * momentum
                    + mean * (1 - momentum)).astype(moving_mean.dtype)
        new_var = (moving_var.astype(f32) * momentum
                   + var * (1 - momentum)).astype(moving_var.dtype)
    else:
        mean, var = moving_mean.astype(f32), moving_var.astype(f32)
        new_mean, new_var = moving_mean, moving_var

    inv = 1.0 / jnp.sqrt(var + eps)
    scale = (g.astype(f32) * inv).reshape(bshape).astype(data.dtype)
    shift = (beta.astype(f32)
             - mean * g.astype(f32) * inv).reshape(bshape).astype(
                 data.dtype)
    out = data * scale + shift
    import jax
    return (out, jax.lax.stop_gradient(new_mean),
            jax.lax.stop_gradient(new_var))


@register("LayerNorm")
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False,
               **kw):
    jnp = _j()
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    inv = 1.0 / jnp.sqrt(var + eps)
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    out = (data - mean) * inv * gamma.reshape(bshape) + beta.reshape(bshape)
    return out


@register("InstanceNorm")
def instance_norm(data, gamma, beta, eps=1e-3, **kw):
    jnp = _j()
    ax = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.var(data, axis=ax, keepdims=True)
    out = (data - mean) / jnp.sqrt(var + eps)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("GroupNorm")
def group_norm(data, gamma, beta, num_groups=1, eps=1e-5, **kw):
    jnp = _j()
    n, c = data.shape[:2]
    rest = data.shape[2:]
    x = data.reshape((n, num_groups, c // num_groups) + rest)
    ax = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=ax, keepdims=True)
    var = jnp.var(x, axis=ax, keepdims=True)
    x = (x - mean) / jnp.sqrt(var + eps)
    x = x.reshape(data.shape)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(bshape) + beta.reshape(bshape)


# ---------------------------------------------------------------------------
# Dropout (training-aware, rng-threaded)
# ---------------------------------------------------------------------------

@register("Dropout", needs_rng=True, training_aware=True)
def dropout(key, data, p=0.5, mode="training", axes=(), cudnn_off=False,
            _training=False, **kw):
    import jax
    jnp = _j()
    if not _training and mode != "always":
        return data
    if p <= 0:
        return data
    shape = list(data.shape)
    if axes:
        for i in range(len(shape)):
            if i not in axes:
                shape[i] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, shape=tuple(shape))
    return jnp.where(mask, data / keep, 0.0).astype(data.dtype)


# ---------------------------------------------------------------------------
# Embedding
# ---------------------------------------------------------------------------

@register("Embedding")
def embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
              sparse_grad=False, **kw):
    jnp = _j()
    idx = data.astype("int32")
    return jnp.take(weight, idx, axis=0)


# ---------------------------------------------------------------------------
# CTC loss (reference: ``src/operator/nn/ctc_loss.cc``) via optax
# ---------------------------------------------------------------------------

@register("CTCLoss", aliases=("ctc_loss",))
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first", **kw):
    import optax
    jnp = _j()
    # data: (T, N, C) per MXNet; optax expects (N, T, C) logits
    logits = jnp.transpose(data, (1, 0, 2))
    N, T, C = logits.shape
    if blank_label == "first":
        blank_id = 0
        labels = label.astype("int32")
    else:
        blank_id = C - 1
        labels = label.astype("int32")
    if use_data_lengths and data_lengths is not None:
        t_ar = jnp.arange(T)[None, :]
        logitpad = (t_ar >= data_lengths[:, None].astype("int32")
                    ).astype("float32")
    else:
        logitpad = jnp.zeros((N, T), dtype="float32")
    L = labels.shape[1]
    if use_label_lengths and label_lengths is not None:
        l_ar = jnp.arange(L)[None, :]
        labpad = (l_ar >= label_lengths[:, None].astype("int32")
                  ).astype("float32")
    else:
        # MXNet convention: labels padded with 0 (when blank is 'last') or
        # -1; treat values < (1 if blank first else 0) as padding
        pad_val = 0 if blank_label == "first" else -1
        labpad = (labels <= pad_val).astype("float32") \
            if blank_label == "first" else (labels < 0).astype("float32")
    loss = optax.ctc_loss(logits, logitpad, labels, labpad,
                          blank_id=blank_id)
    return loss


@register("LRN")
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, **kw):
    """Local response normalization across channels (reference:
    ``src/operator/nn/lrn.cc``; AlexNet).  NCHW."""
    jax = _jax()
    jnp = _j()
    sq = jnp.square(data.astype("float32"))
    half = nsize // 2
    # sum over a channel window via padded cumulative trick
    padded = jnp.pad(sq, ((0, 0), (half, half)) + ((0, 0),) * (data.ndim - 2))
    win = sum(padded[:, i:i + data.shape[1]] for i in range(nsize))
    norm = jnp.power(knorm + alpha / nsize * win, beta)
    return (data.astype("float32") / norm).astype(data.dtype)


@register("log_sigmoid")
def log_sigmoid(data, **kw):
    """log(sigmoid(x)) (reference: ``mshadow_op.h`` log_sigmoid)."""
    return _jax().nn.log_sigmoid(data)


@register("mish")
def mish(data, **kw):
    """x * tanh(softplus(x)) (reference: ``mshadow_op.h`` mish)."""
    jax = _jax()
    jnp = _j()
    return data * jnp.tanh(jax.nn.softplus(data))


@register("masked_softmax")
def masked_softmax(data, mask, axis=-1, temperature=1.0,
                   normalize=True, **kw):
    """Softmax over positions where ``mask`` is true; masked positions
    output 0 (reference: ``src/operator/nn/softmax.cc``
    masked_softmax)."""
    jax = _jax()
    jnp = _j()
    x = data
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    neg = jnp.asarray(-_np.inf, x.dtype)
    masked = jnp.where(mask.astype(bool), x, neg)
    out = jax.nn.softmax(masked, axis=axis)
    return jnp.where(mask.astype(bool), out, 0.0).astype(data.dtype)


@register("softmax_activation")
def softmax_activation(data, mode="instance", **kw):
    """Deprecated alias of softmax (reference:
    ``softmax_activation.cc``): mode='instance' softmaxes the trailing
    dim, mode='channel' softmaxes dim 1."""
    jax = _jax()
    return jax.nn.softmax(data, axis=1 if mode == "channel" else -1)


@register("im2col")
def im2col(data, kernel=None, stride=None, dilate=None, pad=None, **kw):
    """Rearrange conv patches into a matrix (reference:
    ``src/operator/tensor/im2col.cc``): (N, C, *spatial) →
    (N, C*prod(kernel), prod(out_spatial))."""
    jax = _jax()
    nd_ = _conv_dims(kernel)
    kernel = _tup(kernel, nd_)
    stride = _tup(stride or 1, nd_)
    dilate = _tup(dilate or 1, nd_)
    pad = _tup(pad or 0, nd_)
    patches = jax.lax.conv_general_dilated_patches(
        data, filter_shape=kernel, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate)
    n = patches.shape[0]
    return patches.reshape((n, patches.shape[1], -1))


@register("col2im")
def col2im(data, output_size=None, kernel=None, stride=None, dilate=None,
           pad=None, **kw):
    """Scatter-add inverse of im2col (reference: ``im2col.cc``) —
    implemented as the transpose (vjp) of ``im2col``, which is exactly
    its mathematical definition."""
    jax = _jax()
    jnp = _j()
    nd_ = _conv_dims(kernel)
    out_sp = tuple(int(s) for s in output_size)[-nd_:]
    C = data.shape[1] // int(_np.prod(_tup(kernel, nd_)))
    ref_shape = (data.shape[0], C) + out_sp
    ref = jnp.zeros(ref_shape, data.dtype)
    _, vjp = jax.vjp(
        lambda x: im2col(x, kernel=kernel, stride=stride, dilate=dilate,
                         pad=pad), ref)
    return vjp(data)[0]


@register("Crop")
def crop_v1(*inputs, offset=(0, 0), h_w=(0, 0), center_crop=False,
            num_args=None, **kw):
    """Legacy spatial Crop (reference: ``src/operator/crop.cc``):
    crop inputs[0] to ``h_w`` (or to inputs[1]'s spatial shape) at
    ``offset`` or centered."""
    data = inputs[0]
    if len(inputs) > 1:
        th, tw = inputs[1].shape[2], inputs[1].shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    H, W = data.shape[2], data.shape[3]
    if center_crop:
        y0, x0 = (H - th) // 2, (W - tw) // 2
    else:
        y0, x0 = int(offset[0]), int(offset[1])
    return data[:, :, y0:y0 + th, x0:x0 + tw]


@register("IdentityAttachKLSparseReg")
def identity_attach_kl_sparse_reg(data, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9, **kw):
    """Identity forward; backward adds the KL-sparsity penalty gradient
    computed from the batch mean activation (reference:
    ``src/operator/identity_attach_KL_sparse_reg.cc``, which expects
    post-sigmoid inputs in (0, 1) and adds
    ``penalty * (-rho/rho_hat + (1-rho)/(1-rho_hat))`` to the gradient).
    ``rho_hat`` is the PER-UNIT mean over the batch axis (axis 0), as
    in the reference.  Divergence: the reference keeps ``rho_hat`` as a
    ``momentum`` moving-average aux state; this functional op uses the
    current batch mean (momentum accepted for signature parity,
    unused)."""
    jax = _jax()
    jnp = _j()
    rho = sparseness_target

    @jax.custom_vjp
    def _f(x):
        return x

    def _fwd(x):
        return x, x

    def _bwd(x, g):
        rho_hat = jnp.clip(jnp.mean(x, axis=0, keepdims=True),
                           1e-6, 1 - 1e-6)
        kl_grad = penalty * (-rho / rho_hat + (1 - rho) / (1 - rho_hat))
        return (g + jnp.broadcast_to(kl_grad, x.shape),)

    _f.defvjp(_fwd, _bwd)
    return _f(data)
