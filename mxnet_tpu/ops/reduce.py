"""Reduction / sorting / indexing-reduction kernels.

Reference: ``src/operator/tensor/broadcast_reduce_op_value.cc``,
``ordering_op.cc``, ``matrix_op.cc`` reductions (SURVEY.md §2.1).
MXNet reduction semantics preserved: ``axis=None`` reduces all, ``keepdims``,
``exclude`` inverts the axis set.
"""
from __future__ import annotations

import numpy as _np

from .registry import register


def _j():
    import jax.numpy as jnp
    return jnp


def _norm_axis(axis, ndim, exclude=False):
    if axis is None:
        ax = None
    elif isinstance(axis, int):
        ax = (axis,)
    else:
        ax = tuple(axis)
    if ax is not None:
        ax = tuple(a % ndim for a in ax)
    if exclude:
        all_ax = set(range(ndim))
        ax = tuple(sorted(all_ax - set(ax or ())))
    return ax


def _reduce(name, fn, aliases=(), no_grad=False):
    @register(name, aliases=aliases, no_grad=no_grad)
    def impl(data, axis=None, keepdims=False, exclude=False, **kw):
        ax = _norm_axis(axis, data.ndim, exclude)
        return fn(_j(), data, ax, keepdims)
    impl.__name__ = name
    return impl


_reduce("sum", lambda jnp, x, ax, kd: jnp.sum(x, axis=ax, keepdims=kd),
        aliases=("sum_axis",))
_reduce("mean", lambda jnp, x, ax, kd: jnp.mean(x, axis=ax, keepdims=kd))
_reduce("prod", lambda jnp, x, ax, kd: jnp.prod(x, axis=ax, keepdims=kd))
_reduce("max", lambda jnp, x, ax, kd: jnp.max(x, axis=ax, keepdims=kd),
        aliases=("max_axis",))
_reduce("min", lambda jnp, x, ax, kd: jnp.min(x, axis=ax, keepdims=kd),
        aliases=("min_axis",))
_reduce("nansum", lambda jnp, x, ax, kd: jnp.nansum(x, axis=ax, keepdims=kd))
_reduce("nanprod",
        lambda jnp, x, ax, kd: jnp.nanprod(x, axis=ax, keepdims=kd))


@register("argmax", no_grad=True)
def argmax(data, axis=None, keepdims=False, **kw):
    jnp = _j()
    out = jnp.argmax(data, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype("float32")


@register("argmin", no_grad=True)
def argmin(data, axis=None, keepdims=False, **kw):
    jnp = _j()
    out = jnp.argmin(data, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype("float32")


@register("argmax_channel", no_grad=True)
def argmax_channel(data, **kw):
    return _j().argmax(data, axis=1).astype("float32")


@register("norm")
def norm(data, ord=2, axis=None, keepdims=False, **kw):
    jnp = _j()
    ax = _norm_axis(axis, data.ndim)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=ax, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims))


@register("L2Normalization")
def l2_normalization(data, eps=1e-10, mode="instance", **kw):
    jnp = _j()
    if mode == "instance":
        ax = tuple(range(1, data.ndim))
    elif mode == "channel":
        ax = (1,)
    elif mode == "spatial":
        ax = tuple(range(2, data.ndim))
    else:
        ax = tuple(range(1, data.ndim))
    denom = jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=True) + eps)
    return data / denom


@register("sort")
def sort(data, axis=-1, is_ascend=True, **kw):
    jnp = _j()
    if axis is None:
        data = data.reshape(-1)
        axis = 0
    out = jnp.sort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort", no_grad=True)
def argsort(data, axis=-1, is_ascend=True, dtype="float32", **kw):
    jnp = _j()
    if axis is None:
        data = data.reshape(-1)
        axis = 0
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(_np.dtype(dtype).name)


def _topk_mask_compute(data, k, axis, is_ascend):
    import jax
    jnp = _j()
    neg = data if not is_ascend else -data
    moved = jnp.moveaxis(neg, axis, -1)               # (..., N)
    _, idx = jax.lax.top_k(moved, k)                  # (..., k)
    oh = jax.nn.one_hot(idx, data.shape[axis],
                        dtype=data.dtype)             # (..., k, N)
    m = jnp.sum(oh, axis=-2)                          # (..., N)
    return jnp.moveaxis(m, -1, axis)


_TOPK_MASK_VJP = None


def _topk_mask(data, k, axis, is_ascend):
    """topk ret_typ='mask' with the reference scatter backward: out_grad
    flows to the selected positions (grad = g * mask), matching upstream
    TopKImpl's backward rather than the all-zero gradient of
    one_hot(stop_grad(idx))."""
    global _TOPK_MASK_VJP
    if _TOPK_MASK_VJP is None:
        import jax
        from functools import partial

        @partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
        def fn(data, k, axis, is_ascend):
            return _topk_mask_compute(data, k, axis, is_ascend)

        def fwd(data, k, axis, is_ascend):
            m = _topk_mask_compute(data, k, axis, is_ascend)
            return m, m

        def bwd(k, axis, is_ascend, m, g):
            return (g * m,)

        fn.defvjp(fwd, bwd)
        _TOPK_MASK_VJP = fn
    return _TOPK_MASK_VJP(data, k, axis, is_ascend)


@register("topk", num_outputs=-1,
          no_grad=lambda attrs: attrs.get("ret_typ",
                                          "indices") == "indices")
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False,
         dtype="float32", **kw):
    import jax
    jnp = _j()
    axis = axis if axis is not None else -1
    if ret_typ == "mask":
        ax = axis if axis >= 0 else data.ndim + axis
        return _topk_mask(data, k, ax, is_ascend)
    neg = data if not is_ascend else -data
    moved = jnp.moveaxis(neg, axis, -1)
    vals, idx = jax.lax.top_k(moved, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(_np.dtype(dtype).name)
    if ret_typ == "indices":
        return idx
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return (vals, idx)
    raise ValueError("unknown ret_typ %r" % ret_typ)


@register("cumsum")
def cumsum(data, axis=None, dtype=None, **kw):
    jnp = _j()
    if axis is None:
        data = data.reshape(-1)
        axis = 0
    out = jnp.cumsum(data, axis=axis)
    if dtype is not None:
        out = out.astype(_np.dtype(dtype).name)
    return out
