"""Contrib long-tail operators: interleaved attention matmuls, masked
softmax variants, count-sketch, and small utility ops.

Reference: ``src/operator/contrib/transformer.cc`` (interleaved_matmul_*
— the GluonNLP fused-attention entry points), ``krprod.cc``,
``count_sketch.cc``, ``quadratic_op.cc``, ``gradient_multiplier_op.cc``,
``allclose_op.cc`` — SURVEY.md §2.1 operator library (contrib rows).

TPU-native notes: the interleaved matmuls exist upstream to hit cuBLAS
strided-batch gemm; here they are einsum contractions, which XLA maps
straight onto the MXU — the op surface is kept for GluonNLP script
parity, while flash attention (``kernels/flash_attention.py``) remains
the recommended long-sequence path."""
from __future__ import annotations

import numpy as _np

from .registry import register


def _j():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# interleaved attention matmuls (GluonNLP fused transformer ops)
# ---------------------------------------------------------------------------

def _split_qkv_interleaved(qkv, heads, parts):
    """(L, B, H*parts*dh) interleaved per head → tuple of (B*H, L, dh)."""
    jnp = _j()
    L, B, D = qkv.shape
    dh = D // (heads * parts)
    x = qkv.reshape(L, B, heads, parts, dh)
    outs = []
    for p in range(parts):
        t = x[:, :, :, p]                       # (L, B, H, dh)
        outs.append(t.transpose(1, 2, 0, 3).reshape(B * heads, L, dh))
    return tuple(outs)


@register("_contrib_interleaved_matmul_selfatt_qk")
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1, **kw):
    """(L, B, H*3*dh) interleaved qkv → attention scores (B*H, L, L),
    scaled by 1/sqrt(dh) like the reference gemm alpha."""
    jnp = _j()
    q, k, _ = _split_qkv_interleaved(queries_keys_values, int(heads), 3)
    scale = 1.0 / _np.sqrt(q.shape[-1])
    return jnp.einsum("nld,nmd->nlm", q * scale, k)


@register("_contrib_interleaved_matmul_selfatt_valatt")
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention,
                                      heads=1, **kw):
    """((L, B, H*3*dh), (B*H, L, L)) → context (L, B, H*dh)."""
    jnp = _j()
    heads = int(heads)
    _, _, v = _split_qkv_interleaved(queries_keys_values, heads, 3)
    ctx = jnp.einsum("nlm,nmd->nld", attention, v)   # (B*H, L, dh)
    BH, L, dh = ctx.shape
    B = BH // heads
    return ctx.reshape(B, heads, L, dh).transpose(2, 0, 1, 3) \
        .reshape(L, B, heads * dh)


@register("_contrib_interleaved_matmul_encdec_qk")
def interleaved_matmul_encdec_qk(queries, keys_values, heads=1, **kw):
    """q (Lq, B, H*dh) + interleaved kv (Lk, B, H*2*dh) →
    (B*H, Lq, Lk)."""
    jnp = _j()
    heads = int(heads)
    Lq, B, D = queries.shape
    dh = D // heads
    q = queries.reshape(Lq, B, heads, dh).transpose(1, 2, 0, 3) \
        .reshape(B * heads, Lq, dh)
    k, _ = _split_qkv_interleaved(keys_values, heads, 2)
    scale = 1.0 / _np.sqrt(dh)
    return jnp.einsum("nld,nmd->nlm", q * scale, k)


@register("_contrib_interleaved_matmul_encdec_valatt")
def interleaved_matmul_encdec_valatt(keys_values, attention, heads=1,
                                     **kw):
    """(interleaved kv (Lk, B, H*2*dh), att (B*H, Lq, Lk)) →
    (Lq, B, H*dh)."""
    jnp = _j()
    heads = int(heads)
    _, v = _split_qkv_interleaved(keys_values, heads, 2)
    ctx = jnp.einsum("nlm,nmd->nld", attention, v)
    BH, Lq, dh = ctx.shape
    B = BH // heads
    return ctx.reshape(B, heads, Lq, dh).transpose(2, 0, 1, 3) \
        .reshape(Lq, B, heads * dh)


@register("_contrib_div_sqrt_dim", aliases=("div_sqrt_dim",))
def div_sqrt_dim(data, **kw):
    """data / sqrt(last_dim) (reference: transformer.cc DivSqrtDim)."""
    return data / _np.sqrt(data.shape[-1])


@register("masked_log_softmax")
def masked_log_softmax(data, mask, axis=-1, temperature=1.0, **kw):
    """log_softmax over unmasked positions; masked positions get -inf
    (reference: masked_log_softmax in softmax op family)."""
    import jax
    jnp = _j()
    neg = jnp.finfo(data.dtype).min
    x = jnp.where(mask.astype(bool), data / temperature, neg)
    out = jax.nn.log_softmax(x, axis=axis)
    return jnp.where(mask.astype(bool), out, -jnp.inf)


# ---------------------------------------------------------------------------
# small contrib utilities
# ---------------------------------------------------------------------------

@register("_contrib_quadratic", aliases=("quadratic",))
def quadratic(data, a=0.0, b=0.0, c=0.0, **kw):
    """a*x^2 + b*x + c (reference: quadratic_op.cc — the tutorial op)."""
    return a * data * data + b * data + c


def _grad_mult_vjp():
    import jax
    from functools import partial

    @partial(jax.custom_vjp, nondiff_argnums=(1,))
    def fn(data, scalar):
        return data

    def fwd(data, scalar):
        return data, None

    def bwd(scalar, _, g):
        return (g * scalar,)

    fn.defvjp(fwd, bwd)
    return fn


_GRAD_MULT = None


@register("_contrib_gradientmultiplier", aliases=("gradientmultiplier",))
def gradientmultiplier(data, scalar=1.0, **kw):
    """Identity forward; backward scales the gradient by ``scalar``
    (reference: gradient_multiplier_op.cc — gradient-reversal trick when
    scalar < 0)."""
    global _GRAD_MULT
    if _GRAD_MULT is None:
        _GRAD_MULT = _grad_mult_vjp()
    return _GRAD_MULT(data, float(scalar))


@register("_contrib_allclose", aliases=("allclose",), no_grad=True)
def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False, **kw):
    jnp = _j()
    return jnp.allclose(a, b, rtol=rtol, atol=atol,
                        equal_nan=equal_nan).astype("float32")


@register("_contrib_getnnz", aliases=("getnnz",), no_grad=True)
def getnnz(data, axis=None, **kw):
    jnp = _j()
    # int32: jax truncates int64 (and warns) unless x64 is enabled
    return jnp.count_nonzero(data, axis=axis).astype("int32")


@register("_contrib_count_sketch", aliases=("count_sketch",),
          no_grad=True)
def count_sketch(data, h, s, out_dim=1, **kw):
    """Count sketch projection (reference: count_sketch.cc): out[n, h[i]]
    += s[i] * data[n, i] — a random feature hash, expressed as a
    segment-sum so XLA lowers it to one scatter-add."""
    import jax
    jnp = _j()
    out_dim = int(out_dim)
    idx = h.astype("int32").ravel()
    sign = s.ravel()

    def one(row):
        return jax.ops.segment_sum(row * sign, idx,
                                   num_segments=out_dim)

    flat = data.reshape(-1, data.shape[-1])
    out = jax.vmap(one)(flat)
    return out.reshape(data.shape[:-1] + (out_dim,))


@register("_contrib_SyncBatchNorm", aliases=("SyncBatchNorm",),
          mutate=(3, 4), training_aware=True)
def sync_batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                    momentum=0.9, fix_gamma=True, use_global_stats=False,
                    output_mean_var=False, ndev=1, key=None,
                    _training=False, **kw):
    """Cross-device synchronized BatchNorm (reference:
    ``contrib/sync_batch_norm.cc``).

    TPU-native: under pjit with the batch sharded over ``dp``, the mean/
    var reductions in BatchNorm are GLOBAL-batch reductions already —
    GSPMD inserts the psum that the reference implemented by hand with
    a cross-GPU key-value barrier.  So the op is the standard BatchNorm
    kernel; ``ndev``/``key`` are accepted for script parity."""
    from .nn import batch_norm
    return batch_norm(data, gamma, beta, moving_mean, moving_var,
                      eps=eps, momentum=momentum, fix_gamma=fix_gamma,
                      use_global_stats=use_global_stats,
                      output_mean_var=output_mean_var, axis=1,
                      _training=_training)
