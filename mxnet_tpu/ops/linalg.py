"""Linear-algebra kernels (reference: ``src/operator/tensor/la_op.cc`` —
the ``linalg_*`` family, SURVEY.md §2.1).  Lowers to jax.scipy /
lax.linalg, which XLA maps to MXU-friendly blocked algorithms."""
from __future__ import annotations

from .registry import register


def _j():
    import jax.numpy as jnp
    return jnp


@register("_linalg_gemm", aliases=("linalg_gemm",))
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2, **kw):
    jnp = _j()
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("_linalg_gemm2", aliases=("linalg_gemm2",))
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0,
                 axis=-2, **kw):
    jnp = _j()
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("_linalg_potrf", aliases=("linalg_potrf",))
def linalg_potrf(A, **kw):
    import jax
    return jax.scipy.linalg.cholesky(A, lower=True)


@register("_linalg_potri", aliases=("linalg_potri",))
def linalg_potri(A, **kw):
    import jax
    jnp = _j()
    # A is the Cholesky factor L; potri returns (L L^T)^{-1}
    n = A.shape[-1]
    eye = jnp.broadcast_to(jnp.eye(n, dtype=A.dtype), A.shape)
    linv = jax.scipy.linalg.solve_triangular(A, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register("_linalg_trsm", aliases=("linalg_trsm",))
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0, **kw):
    import jax
    jnp = _j()
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    lo = lower != transpose
    if rightside:
        # X A = alpha B  ->  A^T X^T = alpha B^T
        xt = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(a, -1, -2), jnp.swapaxes(alpha * B, -1, -2),
            lower=not lo)
        return jnp.swapaxes(xt, -1, -2)
    return jax.scipy.linalg.solve_triangular(a, alpha * B, lower=lo)


@register("_linalg_trmm", aliases=("linalg_trmm",))
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0, **kw):
    jnp = _j()
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    tri = jnp.tril(A) if lower else jnp.triu(A)
    tri = jnp.swapaxes(tri, -1, -2) if transpose else tri
    if rightside:
        return alpha * jnp.matmul(B, tri)
    return alpha * jnp.matmul(tri, B)


@register("_linalg_syrk", aliases=("linalg_syrk",))
def linalg_syrk(A, transpose=False, alpha=1.0, **kw):
    jnp = _j()
    at = jnp.swapaxes(A, -1, -2)
    if transpose:
        return alpha * jnp.matmul(at, A)
    return alpha * jnp.matmul(A, at)


@register("_linalg_syevd", aliases=("linalg_syevd",), num_outputs=2)
def linalg_syevd(A, **kw):
    jnp = _j()
    w, v = jnp.linalg.eigh(A)
    # MXNet returns (U, L) with rows of U the eigenvectors: A = U^T diag(L) U
    return jnp.swapaxes(v, -1, -2), w


@register("_linalg_gelqf", aliases=("linalg_gelqf",), num_outputs=2)
def linalg_gelqf(A, **kw):
    jnp = _j()
    # LQ decomposition via QR of A^T: A = L Q
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("_linalg_sumlogdiag", aliases=("linalg_sumlogdiag",))
def linalg_sumlogdiag(A, **kw):
    jnp = _j()
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("_linalg_extractdiag", aliases=("linalg_extractdiag",))
def linalg_extractdiag(A, offset=0, **kw):
    return _j().diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("_linalg_makediag", aliases=("linalg_makediag",))
def linalg_makediag(A, offset=0, **kw):
    jnp = _j()
    n = A.shape[-1] + abs(offset)
    out = jnp.zeros(A.shape[:-1] + (n, n), dtype=A.dtype)
    idx = jnp.arange(A.shape[-1])
    if offset >= 0:
        return out.at[..., idx, idx + offset].set(A)
    return out.at[..., idx - offset, idx].set(A)


@register("_linalg_extracttrian", aliases=("linalg_extracttrian",))
def linalg_extracttrian(A, offset=0, lower=True, **kw):
    import numpy as np
    jnp = _j()
    n = A.shape[-1]
    if lower:
        ii, jj = np.tril_indices(n, k=offset)
    else:
        ii, jj = np.triu_indices(n, k=offset)
    return A[..., ii, jj]


@register("_linalg_inverse", aliases=("linalg_inverse", "inverse"))
def linalg_inverse(A, **kw):
    return _j().linalg.inv(A)


@register("_linalg_det", aliases=("linalg_det", "det"))
def linalg_det(A, **kw):
    return _j().linalg.det(A)


@register("_linalg_slogdet", aliases=("linalg_slogdet", "slogdet"),
          num_outputs=2)
def linalg_slogdet(A, **kw):
    sign, logdet = _j().linalg.slogdet(A)
    return sign, logdet


@register("moments", num_outputs=2)
def moments(data, axes=None, keepdims=False, **kw):
    jnp = _j()
    ax = tuple(axes) if axes is not None else None
    return (jnp.mean(data, axis=ax, keepdims=keepdims),
            jnp.var(data, axis=ax, keepdims=keepdims))


@register("_contrib_fft", aliases=("fft",))
def contrib_fft(data, compute_size=128, **kw):
    """1-D FFT over the last axis; complex output packed as interleaved
    re/im (the reference's memory layout, ``contrib/fft.cc``)."""
    jnp = _j()
    out = jnp.fft.fft(data.astype("float32"), axis=-1)
    packed = jnp.stack([out.real, out.imag], axis=-1)
    return packed.reshape(data.shape[:-1] + (2 * data.shape[-1],)) \
        .astype("float32")


@register("_contrib_ifft", aliases=("ifft",))
def contrib_ifft(data, compute_size=128, **kw):
    """Inverse of ``_contrib_fft`` — consumes interleaved re/im, emits
    the real part scaled by N (the reference's convention)."""
    jnp = _j()
    n = data.shape[-1] // 2
    c = data.reshape(data.shape[:-1] + (n, 2)).astype("float32")
    comp = c[..., 0] + 1j * c[..., 1]
    return (jnp.fft.ifft(comp, axis=-1).real * n).astype("float32")
