"""Vision / detection operator kernels.

Reference: ``src/operator/contrib/`` (``bounding_box.cc`` box_iou/box_nms,
``multibox_prior.cc`` / ``multibox_target.cc`` / ``multibox_detection.cc``
SSD ops, ``roi_align.cc``, ``bilinear_resize.cc``,
``adaptive_avg_pooling.cc``), ``src/operator/roi_pooling.cc``,
``src/operator/spatial_transformer.cc`` / ``bilinear_sampler.cc`` /
``grid_generator.cc``, ``src/operator/correlation.cc``,
``src/operator/svm_output.cc`` (SURVEY.md §2.1 "Operator library").

TPU-native design: every op here is static-shape and branch-free so it
jits cleanly — NMS keeps the input rank and marks suppressed entries
instead of compacting (which is also the reference's output contract),
ROIAlign samples fixed per-bin grids via vectorized bilinear gathers
(no dynamic slicing), and adaptive pooling reduces via an integral
image so arbitrary output sizes stay one fused XLA computation.
"""
from __future__ import annotations

import numpy as _np

from .registry import register
from ..base import MXNetError


def _j():
    import jax.numpy as jnp
    return jnp


def _jax():
    import jax
    return jax


def _to_corner(box, fmt):
    """(..., 4) boxes → corner (xmin, ymin, xmax, ymax)."""
    jnp = _j()
    if fmt == "corner":
        return box
    # center: (cx, cy, w, h)
    cx, cy, w, h = (box[..., 0], box[..., 1], box[..., 2], box[..., 3])
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


def _bilinear_gather(img, y, x, border="clamp"):
    """Bilinear interpolation of ``img`` (C, H, W) at sample coords
    ``y``/``x`` (any matching shape, in pixel units) → (C, *y.shape).

    ``border='clamp'``: coordinates clamp to the edge (ROIAlign
    convention); ``border='zero'``: samples outside the image read 0
    (BilinearSampler convention).  The single blend implementation
    backing ROIAlign, BilinearSampler and BilinearResize2D."""
    jnp = _j()
    C, H, W = img.shape
    if border == "clamp":
        y = jnp.clip(y, 0.0, H - 1.0)
        x = jnp.clip(x, 0.0, W - 1.0)
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy = y - y0
    wx = x - x0

    def g(yi, xi):
        yc = jnp.clip(yi, 0, H - 1).astype("int32")
        xc = jnp.clip(xi, 0, W - 1).astype("int32")
        v = img[:, yc, xc]
        if border == "zero":
            inside = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            v = jnp.where(inside, v, 0.0)
        return v

    v00 = g(y0, x0)
    v01 = g(y0, x0 + 1)
    v10 = g(y0 + 1, x0)
    v11 = g(y0 + 1, x0 + 1)
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
            v10 * wy * (1 - wx) + v11 * wy * wx)


def _pairwise_iou(lhs, rhs):
    """IoU between (..., A, 4) and (..., B, 4) corner boxes → (..., A, B)."""
    jnp = _j()
    lt = jnp.maximum(lhs[..., :, None, :2], rhs[..., None, :, :2])
    rb = jnp.minimum(lhs[..., :, None, 2:], rhs[..., None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_l = jnp.maximum(lhs[..., 2] - lhs[..., 0], 0.0) * \
        jnp.maximum(lhs[..., 3] - lhs[..., 1], 0.0)
    area_r = jnp.maximum(rhs[..., 2] - rhs[..., 0], 0.0) * \
        jnp.maximum(rhs[..., 3] - rhs[..., 1], 0.0)
    union = area_l[..., :, None] + area_r[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("_contrib_box_iou", aliases=("box_iou",))
def box_iou(lhs, rhs, format="corner", **kw):
    """Pairwise IoU over the outer product of the two boxes' leading dims
    (reference: ``bounding_box.cc`` BoxOverlap)."""
    jnp = _j()
    lf = _to_corner(lhs, format).reshape((-1, 4))
    rf = _to_corner(rhs, format).reshape((-1, 4))
    out = _pairwise_iou(lf, rf)
    return out.reshape(lhs.shape[:-1] + rhs.shape[:-1])


def _nms_keep(boxes, scores, valid, thresh, force_suppress, ids):
    """Greedy NMS over score-descending boxes.  Returns the keep mask in
    the SORTED order.  O(N²) data-parallel formulation: a box is kept iff
    no higher-scoring *kept* box overlaps it — computed with a scan over
    rows of the pairwise-IoU matrix (static shapes, jit-safe)."""
    jax = _jax()
    jnp = _j()
    n = boxes.shape[0]
    iou = _pairwise_iou(boxes, boxes)
    same_class = (ids[:, None] == ids[None, :]) if not force_suppress \
        else jnp.ones((n, n), bool)
    suppress = (iou > thresh) & same_class

    def body(keep, i):
        # i suppressed by any kept higher-scoring j < i
        sup = jnp.any(keep & (jnp.arange(n) < i) & suppress[:, i])
        k = valid[i] & ~sup
        keep = keep.at[i].set(k)
        return keep, ()

    keep0 = jnp.zeros((n,), bool)
    keep, _ = jax.lax.scan(body, keep0, jnp.arange(n))
    return keep


@register("_contrib_box_nms", aliases=("box_nms",))
def box_nms(data, overlap_thresh=0.5, valid_thresh=0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner", out_format="corner",
            **kw):
    """Non-maximum suppression (reference: ``bounding_box.cc`` BoxNMS).

    Output keeps the input shape; suppressed/invalid entries have their
    score set to -1 (the reference's contract).  Entries are re-ordered
    score-descending within each batch."""
    jnp = _j()
    shape = data.shape
    flat = data.reshape((-1,) + shape[-2:])  # (B, N, K)
    B, N, K = flat.shape

    def one(rec):
        scores = rec[:, score_index]
        valid = scores > valid_thresh
        if background_id >= 0 and id_index >= 0:
            valid = valid & (rec[:, id_index] != background_id)
        order = jnp.argsort(-scores)
        rec_s = rec[order]
        valid_s = valid[order]
        if topk > 0:
            # top-k among VALID boxes only (reference: invalid/background
            # rows don't consume k slots)
            valid_rank = jnp.cumsum(valid_s.astype("int32")) - 1
            valid_s = valid_s & (valid_rank < topk)
        boxes = _to_corner(
            rec_s[:, coord_start:coord_start + 4], in_format)
        ids_s = rec_s[:, id_index] if id_index >= 0 \
            else jnp.zeros((N,), rec.dtype)
        keep = _nms_keep(boxes, rec_s[:, score_index], valid_s,
                         overlap_thresh, force_suppress, ids_s)
        out = rec_s
        if out_format != in_format:
            if out_format == "corner":
                conv = boxes
            else:
                x0, y0, x1, y1 = (boxes[..., 0], boxes[..., 1],
                                  boxes[..., 2], boxes[..., 3])
                conv = jnp.stack([(x0 + x1) / 2, (y0 + y1) / 2,
                                  x1 - x0, y1 - y0], axis=-1)
            out = out.at[:, coord_start:coord_start + 4].set(
                conv.astype(out.dtype))
        out = out.at[:, score_index].set(
            jnp.where(keep, out[:, score_index], -1.0))
        return out

    out = _jax().vmap(one)(flat)
    return out.reshape(shape)


@register("MultiBoxPrior", aliases=("_contrib_MultiBoxPrior",))
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5), **kw):
    """SSD anchor generation (reference: ``multibox_prior.cc``).  For an
    (N, C, H, W) feature map emits (1, H*W*(S+R-1), 4) corner anchors."""
    jnp = _j()
    sizes = tuple(float(s) for s in (sizes if not isinstance(sizes, (int, float)) else (sizes,)))
    ratios = tuple(float(r) for r in (ratios if not isinstance(ratios, (int, float)) else (ratios,)))
    H, W = data.shape[-2], data.shape[-1]
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H, dtype="float32") + offsets[0]) * step_y
    cx = (jnp.arange(W, dtype="float32") + offsets[1]) * step_x
    cxg, cyg = jnp.meshgrid(cx, cy)  # (H, W)

    ws, hs = [], []
    # anchors: (size_i, ratio_0) for all sizes, then (size_0, ratio_j>0)
    for s in sizes:
        ws.append(s * _np.sqrt(ratios[0]))
        hs.append(s / _np.sqrt(ratios[0]))
    for r in ratios[1:]:
        ws.append(sizes[0] * _np.sqrt(r))
        hs.append(sizes[0] / _np.sqrt(r))
    ws = jnp.asarray(ws, "float32")  # (A,)
    hs = jnp.asarray(hs, "float32")
    cxg = cxg[..., None]  # (H, W, 1)
    cyg = cyg[..., None]
    anchors = jnp.stack([cxg - ws / 2, cyg - hs / 2,
                         cxg + ws / 2, cyg + hs / 2], axis=-1)
    anchors = anchors.reshape((1, -1, 4))
    if clip:
        anchors = jnp.clip(anchors, 0.0, 1.0)
    return anchors


def _encode_loc(anchor, gt, variances):
    """Corner anchor + matched corner gt → SSD regression target."""
    jnp = _j()
    aw = anchor[..., 2] - anchor[..., 0]
    ah = anchor[..., 3] - anchor[..., 1]
    acx = (anchor[..., 0] + anchor[..., 2]) / 2
    acy = (anchor[..., 1] + anchor[..., 3]) / 2
    gw = jnp.maximum(gt[..., 2] - gt[..., 0], 1e-12)
    gh = jnp.maximum(gt[..., 3] - gt[..., 1], 1e-12)
    gcx = (gt[..., 0] + gt[..., 2]) / 2
    gcy = (gt[..., 1] + gt[..., 3]) / 2
    return jnp.stack([
        (gcx - acx) / jnp.maximum(aw, 1e-12) / variances[0],
        (gcy - acy) / jnp.maximum(ah, 1e-12) / variances[1],
        jnp.log(gw / jnp.maximum(aw, 1e-12)) / variances[2],
        jnp.log(gh / jnp.maximum(ah, 1e-12)) / variances[3],
    ], axis=-1)


@register("MultiBoxTarget", aliases=("_contrib_MultiBoxTarget",),
          num_outputs=3, no_grad=True)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5,
                    minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2), **kw):
    """SSD training-target assignment (reference: ``multibox_target.cc``).

    anchor (1, A, 4) corner; label (B, M, 5) rows [cls, x0, y0, x1, y1]
    padded with cls = -1; cls_pred (B, C+1, A) (used for hard-negative
    mining when ``negative_mining_ratio`` > 0).  Outputs: loc_target
    (B, A*4), loc_mask (B, A*4), cls_target (B, A) where class 0 is
    background and gt class c maps to c+1."""
    jax = _jax()
    jnp = _j()
    A = anchor.shape[1]
    anc = anchor.reshape((A, 4))

    def one(lab, cpred):
        M = lab.shape[0]
        gt_valid = lab[:, 0] >= 0                      # (M,)
        gt_box = lab[:, 1:5]
        iou = _pairwise_iou(anc, gt_box)               # (A, M)
        iou = jnp.where(gt_valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)              # (A,)
        best_iou = jnp.max(iou, axis=1)
        matched = best_iou >= overlap_threshold
        # bipartite stage: each valid gt claims its best anchor
        best_anchor = jnp.argmax(iou, axis=0)          # (M,)
        # padded (cls = -1) rows must not participate in the scatter at
        # all — at[].set with duplicate indices is order-undefined, so an
        # invalid gt aliasing a valid gt's anchor could clobber it.
        # Route invalid gts to out-of-range index A with mode='drop'.
        scatter_idx = jnp.where(gt_valid, best_anchor, A)
        forced = jnp.zeros((A,), bool)
        forced = forced.at[scatter_idx].set(True, mode="drop")
        forced_gt = jnp.zeros((A,), "int32")
        forced_gt = forced_gt.at[scatter_idx].set(
            jnp.arange(M, dtype="int32"), mode="drop")
        use_gt = jnp.where(forced, forced_gt, best_gt.astype("int32"))
        pos = matched | forced
        gt_for_anchor = gt_box[use_gt]                 # (A, 4)
        cls_for_anchor = lab[use_gt, 0].astype("int32") + 1
        cls_target = jnp.where(pos, cls_for_anchor, 0)
        if negative_mining_ratio > 0:
            # hard-negative mining: keep the highest-background-loss
            # negatives up to ratio * npos, rest -> ignore_label
            bg_prob = jax.nn.softmax(cpred, axis=0)[0]  # (A,)
            neg_score = jnp.where(pos | (best_iou >= negative_mining_thresh),
                                  jnp.inf, bg_prob)
            order = jnp.argsort(neg_score)             # hardest first
            rank = jnp.zeros((A,), "int32").at[order].set(
                jnp.arange(A, dtype="int32"))
            n_neg = jnp.maximum(
                (negative_mining_ratio * jnp.sum(pos)).astype("int32"),
                minimum_negative_samples)
            keep_neg = rank < n_neg
            cls_target = jnp.where(pos, cls_target,
                                   jnp.where(keep_neg, 0,
                                             int(ignore_label)))
        loc_t = _encode_loc(anc, gt_for_anchor, variances)
        loc_t = jnp.where(pos[:, None], loc_t, 0.0)
        loc_m = jnp.where(pos[:, None], 1.0, 0.0) * jnp.ones((A, 4))
        return (loc_t.reshape(-1), loc_m.reshape(-1),
                cls_target.astype("float32"))

    loc_t, loc_m, cls_t = jax.vmap(one)(label, cls_pred)
    return loc_t, loc_m, cls_t


@register("MultiBoxDetection", aliases=("_contrib_MultiBoxDetection",),
          no_grad=True)
def multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                       threshold=0.01, background_id=0, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1, **kw):
    """SSD detection decode + per-class NMS (reference:
    ``multibox_detection.cc``).  cls_prob (B, C+1, A), loc_pred (B, A*4),
    anchor (1, A, 4) → (B, A, 6) rows [class_id, score, x0, y0, x1, y1],
    suppressed rows get class_id -1."""
    jax = _jax()
    jnp = _j()
    B, C1, A = cls_prob.shape
    anc = anchor.reshape((A, 4))
    aw = anc[:, 2] - anc[:, 0]
    ah = anc[:, 3] - anc[:, 1]
    acx = (anc[:, 0] + anc[:, 2]) / 2
    acy = (anc[:, 1] + anc[:, 3]) / 2

    def one(cp, lp):
        loc = lp.reshape((A, 4))
        cx = loc[:, 0] * variances[0] * aw + acx
        cy = loc[:, 1] * variances[1] * ah + acy
        w = jnp.exp(loc[:, 2] * variances[2]) * aw
        h = jnp.exp(loc[:, 3] * variances[3]) * ah
        box = jnp.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2, cy + h / 2], axis=-1)
        if clip:
            box = jnp.clip(box, 0.0, 1.0)
        # best non-background class per anchor
        fg = jnp.concatenate([cp[:background_id],
                              cp[background_id + 1:]], axis=0)  # (C, A)
        best = jnp.argmax(fg, axis=0)                            # (A,)
        score = jnp.max(fg, axis=0)
        # the fg row index IS the original 0-based gt class (reference
        # emits channel-1 for background_id 0: gt class c trains channel
        # c+1 in MultiBoxTarget, detection undoes the shift)
        cls_id = jnp.where(score > threshold, best.astype("float32"),
                           -1.0)
        score = jnp.where(score > threshold, score, -1.0)
        rec = jnp.concatenate([cls_id[:, None], score[:, None], box],
                              axis=-1)                           # (A, 6)
        out = box_nms(rec[None], overlap_thresh=nms_threshold,
                      valid_thresh=0.0, topk=nms_topk, coord_start=2,
                      score_index=1, id_index=0, background_id=-1,
                      force_suppress=force_suppress)[0]
        # reference marks suppressed rows via class_id = -1
        return out.at[:, 0].set(
            jnp.where(out[:, 1] < 0, -1.0, out[:, 0]))

    return jax.vmap(one)(cls_prob, loc_pred)


# ---------------------------------------------------------------------------
# ROI ops
# ---------------------------------------------------------------------------

@register("ROIPooling")
def roi_pooling(data, rois, pooled_size=None, spatial_scale=1.0, **kw):
    """Max pooling over quantized ROI bins (reference:
    ``roi_pooling.cc``).  data (N, C, H, W); rois (R, 5) rows
    [batch_index, x0, y0, x1, y1] in image coords."""
    jax = _jax()
    jnp = _j()
    PH, PW = pooled_size
    N, C, H, W = data.shape

    ys = jnp.arange(H, dtype="float32")
    xs = jnp.arange(W, dtype="float32")

    def one(roi):
        b = roi[0].astype("int32")
        x0 = jnp.round(roi[1] * spatial_scale)
        y0 = jnp.round(roi[2] * spatial_scale)
        x1 = jnp.round(roi[3] * spatial_scale)
        y1 = jnp.round(roi[4] * spatial_scale)
        rw = jnp.maximum(x1 - x0 + 1, 1.0)
        rh = jnp.maximum(y1 - y0 + 1, 1.0)
        bin_h = rh / PH
        bin_w = rw / PW
        img = data[b]                                   # (C, H, W)
        ph = jnp.arange(PH, dtype="float32")
        pw = jnp.arange(PW, dtype="float32")
        hstart = jnp.floor(ph * bin_h) + y0             # (PH,)
        hend = jnp.ceil((ph + 1) * bin_h) + y0
        wstart = jnp.floor(pw * bin_w) + x0             # (PW,)
        wend = jnp.ceil((pw + 1) * bin_w) + x0
        ymask = (ys[None, :] >= hstart[:, None]) & \
            (ys[None, :] < hend[:, None])               # (PH, H)
        xmask = (xs[None, :] >= wstart[:, None]) & \
            (xs[None, :] < wend[:, None])               # (PW, W)
        m = ymask[:, None, :, None] & xmask[None, :, None, :]
        neg = jnp.asarray(-_np.inf, data.dtype)
        masked = jnp.where(m[None], img[:, None, None, :, :], neg)
        out = jnp.max(masked, axis=(3, 4))              # (C, PH, PW)
        return jnp.where(jnp.isneginf(out), 0.0, out).astype(data.dtype)

    return jax.vmap(one)(rois)


@register("_contrib_ROIAlign", aliases=("ROIAlign",))
def roi_align(data, rois, pooled_size=None, spatial_scale=1.0,
              sample_ratio=-1, position_sensitive=False, aligned=False,
              **kw):
    """ROIAlign with fixed per-bin bilinear sample grids (reference:
    ``contrib/roi_align.cc``; Mask R-CNN).  Static shapes: every
    (roi, bin) samples ``sample_ratio²`` points (default 2²) via
    vectorized bilinear gathers — no dynamic slicing."""
    jax = _jax()
    jnp = _j()
    if position_sensitive:
        raise MXNetError(
            "_contrib_ROIAlign: position_sensitive=True (PS-ROIAlign) "
            "is not implemented")
    PH, PW = pooled_size
    S = sample_ratio if sample_ratio > 0 else 2
    N, C, H, W = data.shape
    offset = 0.5 if aligned else 0.0

    def one(roi):
        b = roi[0].astype("int32")
        x0 = roi[1] * spatial_scale - offset
        y0 = roi[2] * spatial_scale - offset
        x1 = roi[3] * spatial_scale - offset
        y1 = roi[4] * spatial_scale - offset
        rw = x1 - x0
        rh = y1 - y0
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / PH
        bin_w = rw / PW
        ph = jnp.arange(PH, dtype="float32")
        pw = jnp.arange(PW, dtype="float32")
        sy = (jnp.arange(S, dtype="float32") + 0.5) / S
        sx = (jnp.arange(S, dtype="float32") + 0.5) / S
        yy = y0 + ph[:, None] * bin_h + sy[None, :] * bin_h  # (PH, S)
        xx = x0 + pw[:, None] * bin_w + sx[None, :] * bin_w  # (PW, S)
        Y = yy[:, None, :, None]                        # (PH, 1, S, 1)
        X = xx[None, :, None, :]                        # (1, PW, 1, S)
        Yb = jnp.broadcast_to(Y, (PH, PW, S, S))
        Xb = jnp.broadcast_to(X, (PH, PW, S, S))
        vals = _bilinear_gather(data[b], Yb, Xb)        # (C, PH, PW, S, S)
        return jnp.mean(vals, axis=(3, 4)).astype(data.dtype)

    return jax.vmap(one)(rois)


@register("_contrib_mrcnn_mask_target", num_outputs=2, no_grad=True)
def mrcnn_mask_target(rois, gt_masks, matches, cls_targets,
                      num_rois=None, num_classes=None, mask_size=(14, 14),
                      sample_ratio=2, aligned=False, **kw):
    """Mask R-CNN mask-target generator (reference:
    ``src/operator/contrib/mrcnn_mask_target.cu``; consumed by the
    GluonCV-style Mask R-CNN training loop).

    Inputs:
      rois        (B, N, 4) corner-format proposals, image coords
      gt_masks    (B, M, H, W) binary instance masks
      matches     (B, N) int — index into M of each roi's matched gt
      cls_targets (B, N) int — sampled class per roi: 0 = background,
                  c >= 1 = foreground class c (mask-head channel c-1)

    Outputs (both (B, N, C, MSh, MSw), C = ``num_classes``):
      mask_targets — the matched gt mask ROIAligned to ``mask_size``,
                     written at channel ``cls-1`` for positive rois,
                     zero elsewhere
      mask_cls     — sigmoid-CE weights: 1 at channel ``cls-1`` of
                     positive rois, else 0

    TPU-native: static shapes throughout — each (roi, bin) samples a
    fixed ``sample_ratio²`` bilinear grid from the matched mask (the
    same vectorized-gather core as ``_contrib_ROIAlign``), and the
    class scatter is a one-hot product instead of a data-dependent
    write."""
    jax = _jax()
    jnp = _j()
    if num_classes is None:
        raise MXNetError("_contrib_mrcnn_mask_target: num_classes "
                         "is required")
    C = int(num_classes)
    try:
        MH, MW = mask_size
    except TypeError:
        MH = MW = int(mask_size)

    def one(rois_b, masks_b, match_b, cls_b):
        # batch-index column = matched gt index: ROIAlign then crops
        # each roi straight out of ITS matched instance mask
        full = jnp.concatenate(
            [match_b.astype("float32")[:, None],
             rois_b.astype("float32")], axis=1)        # (N, 5)
        crop = roi_align(masks_b[:, None].astype("float32"), full,
                         pooled_size=(MH, MW), spatial_scale=1.0,
                         sample_ratio=sample_ratio,
                         aligned=aligned)              # (N, 1, MH, MW)
        cls = cls_b.astype("int32")
        onehot = ((jnp.arange(C, dtype="int32")[None, :]
                   == cls[:, None] - 1)
                  & (cls[:, None] > 0)).astype("float32")  # (N, C)
        w = onehot[:, :, None, None]
        return crop * w, jnp.broadcast_to(w, (w.shape[0], C, MH, MW))

    targets, weights = jax.vmap(one)(rois, gt_masks, matches,
                                     cls_targets)
    return targets, weights


# ---------------------------------------------------------------------------
# Spatial transformer family
# ---------------------------------------------------------------------------

def _bilinear_sample_nchw(data, grid_x, grid_y):
    """data (C, H, W); normalized grid in [-1, 1]; outside → 0
    (reference: ``bilinear_sampler.cc`` border handling = zero pad)."""
    C, H, W = data.shape
    x = (grid_x + 1.0) * (W - 1) / 2.0
    y = (grid_y + 1.0) * (H - 1) / 2.0
    return _bilinear_gather(data, y, x, border="zero")


@register("BilinearSampler")
def bilinear_sampler(data, grid, **kw):
    """Sample data at grid locations (reference:
    ``bilinear_sampler.cc``).  data (B, C, H, W); grid (B, 2, Ho, Wo)
    with grid[:, 0] = x, grid[:, 1] = y in [-1, 1]."""
    jax = _jax()

    def one(img, g):
        return _bilinear_sample_nchw(img, g[0], g[1]).astype(img.dtype)

    return jax.vmap(one)(data, grid)


@register("GridGenerator")
def grid_generator(data, transform_type="affine", target_shape=(0, 0),
                   **kw):
    """Generate a sampling grid (reference: ``grid_generator.cc``).

    affine: data (B, 6) row-major 2x3 θ → grid (B, 2, H, W);
    warp: data (B, 2, H, W) pixel flow → normalized grid."""
    jnp = _j()
    if transform_type == "affine":
        H, W = target_shape
        B = data.shape[0]
        ys = jnp.linspace(-1.0, 1.0, H)
        xs = jnp.linspace(-1.0, 1.0, W)
        xg, yg = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(xg)
        src = jnp.stack([xg, yg, ones], axis=0).reshape((3, -1))  # (3, HW)
        theta = data.reshape((B, 2, 3))
        out = theta @ src                                         # (B,2,HW)
        return out.reshape((B, 2, H, W)).astype(data.dtype)
    if transform_type == "warp":
        B, _, H, W = data.shape
        ys = jnp.arange(H, dtype="float32")
        xs = jnp.arange(W, dtype="float32")
        xg, yg = jnp.meshgrid(xs, ys)
        x = (data[:, 0] + xg) * 2.0 / max(W - 1, 1) - 1.0
        y = (data[:, 1] + yg) * 2.0 / max(H - 1, 1) - 1.0
        return jnp.stack([x, y], axis=1).astype(data.dtype)
    raise MXNetError("GridGenerator: unknown transform_type %r"
                     % transform_type)


@register("SpatialTransformer")
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type="affine", sampler_type="bilinear",
                        **kw):
    """Affine spatial transformer network layer (reference:
    ``spatial_transformer.cc`` — GridGenerator + BilinearSampler)."""
    grid = grid_generator(loc, transform_type=transform_type,
                          target_shape=tuple(target_shape))
    return bilinear_sampler(data, grid)


# ---------------------------------------------------------------------------
# Resize / adaptive pooling
# ---------------------------------------------------------------------------

@register("_contrib_BilinearResize2D", aliases=("BilinearResize2D",))
def bilinear_resize_2d(data, like=None, height=1, width=1,
                       scale_height=None, scale_width=None,
                       mode="size", **kw):
    """Bilinear up/downsampling with align_corners=True semantics
    (reference: ``contrib/bilinear_resize.cc``)."""
    jnp = _j()
    B, C, H, W = data.shape
    if mode == "like" and like is not None:
        Ho, Wo = like.shape[-2], like.shape[-1]
    elif mode == "scale" or (scale_height is not None
                             and scale_width is not None):
        Ho, Wo = int(H * scale_height), int(W * scale_width)
    elif mode == "size":
        Ho, Wo = int(height), int(width)
    else:
        raise MXNetError(
            "_contrib_BilinearResize2D: unsupported mode %r "
            "(supported: size, scale, like)" % mode)
    ys = jnp.linspace(0.0, H - 1.0, Ho)
    xs = jnp.linspace(0.0, W - 1.0, Wo)
    yg = jnp.broadcast_to(ys[:, None], (Ho, Wo))
    xg = jnp.broadcast_to(xs[None, :], (Ho, Wo))
    out = _jax().vmap(lambda img: _bilinear_gather(img, yg, xg))(data)
    return out.astype(data.dtype)


@register("_contrib_AdaptiveAvgPooling2D",
          aliases=("AdaptiveAvgPooling2D",))
def adaptive_avg_pooling_2d(data, output_size=(1, 1), **kw):
    """Adaptive average pooling to an arbitrary output size (reference:
    ``contrib/adaptive_avg_pooling.cc``).  Exact bin averaging via an
    integral image — one fused XLA computation for any size."""
    jnp = _j()
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    OH, OW = output_size
    B, C, H, W = data.shape
    # integral image with a leading zero row/col
    ii = jnp.cumsum(jnp.cumsum(data.astype("float32"), axis=2), axis=3)
    ii = jnp.pad(ii, ((0, 0), (0, 0), (1, 0), (1, 0)))
    hs = (jnp.arange(OH) * H) // OH
    he = ((jnp.arange(OH) + 1) * H + OH - 1) // OH
    ws = (jnp.arange(OW) * W) // OW
    we = ((jnp.arange(OW) + 1) * W + OW - 1) // OW
    s = (ii[:, :, he][:, :, :, we] - ii[:, :, hs][:, :, :, we]
         - ii[:, :, he][:, :, :, ws] + ii[:, :, hs][:, :, :, ws])
    area = ((he - hs)[:, None] * (we - ws)[None, :]).astype("float32")
    return (s / area).astype(data.dtype)


# ---------------------------------------------------------------------------
# Correlation (optical flow) and SVMOutput
# ---------------------------------------------------------------------------

@register("Correlation")
def correlation(data1, data2, kernel_size=1, max_displacement=1,
                stride1=1, stride2=1, pad_size=0, is_multiply=True, **kw):
    """Cost-volume correlation between two feature maps (reference:
    ``correlation.cc``; FlowNet).  Output (B, D², Ho, Wo) where
    D = 2*(max_displacement/stride2)+1 — computed as D² shifted
    patch products averaged over channels and the K×K kernel window
    (static unrolled shifts; XLA fuses the stack)."""
    jnp = _j()
    B, C, H, W = data1.shape
    d = max_displacement // stride2
    K = kernel_size
    pad = pad_size
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    Hp, Wp = H + 2 * pad, W + 2 * pad
    bord = max_displacement + (K - 1) // 2
    ys = _np.arange(bord, Hp - bord, stride1)
    xs = _np.arange(bord, Wp - bord, stride1)
    kr = _np.arange(K) - (K - 1) // 2  # kernel window offsets
    outs = []
    for dy in range(-d, d + 1):
        for dx in range(-d, d + 1):
            oy, ox = dy * stride2, dx * stride2
            acc = 0.0
            for ky in kr:
                for kx in kr:
                    a = p1[:, :, ys + ky][:, :, :, xs + kx]
                    b = p2[:, :, ys + oy + ky][:, :, :, xs + ox + kx]
                    if is_multiply:
                        acc = acc + jnp.sum(a * b, axis=1)
                    else:
                        acc = acc + jnp.sum(jnp.abs(a - b), axis=1)
            outs.append(acc / (C * K * K))
    return jnp.stack(outs, axis=1).astype(data1.dtype)


@register("SVMOutput")
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False, **kw):
    """SVM output head (reference: ``svm_output.cc``): forward is
    identity; backward is the (squared-)hinge-loss gradient."""
    jax = _jax()
    jnp = _j()

    @jax.custom_vjp
    def _svm(x, lab):
        return x

    def _fwd(x, lab):
        return x, (x, lab)

    def _bwd(res, g):
        x, lab = res
        k = x.shape[-1]
        oh = jax.nn.one_hot(lab.astype("int32"), k, dtype=x.dtype)
        sgn = 2 * oh - 1                       # +1 for target, -1 rest
        viol = (margin - sgn * x) > 0
        if use_linear:
            grad = jnp.where(viol, -sgn, 0.0)
        else:
            grad = jnp.where(viol, -2.0 * (margin - sgn * x) * sgn, 0.0)
        grad = grad * regularization_coefficient
        return (grad.astype(x.dtype), jnp.zeros_like(lab))

    _svm.defvjp(_fwd, _bwd)
    return _svm(data, label)
