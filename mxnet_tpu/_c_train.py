"""Python side of the C training ABI (``native/src/c_train_api.cc``).

Reference: the reference keeps ALL training semantics below the C ABI
(``src/c_api/c_api_ndarray.cc`` MXImperativeInvokeEx +
``c_api_autograd``); here the execution stack is Python/XLA, so the C
entry points drive this module through embedded CPython — the same
architecture as ``_c_predict`` (SURVEY.md §2.1 "C API" row).

Handle model: every NDArray/optimizer lives in ``_HANDLES`` under an
integer id; the C side only ever sees ids and flat float32 buffers, so
the ABI stays flat and language-agnostic (a non-C++ binding needs only
``dlopen``).
"""
from __future__ import annotations

import itertools
import json
from typing import Dict, List

import numpy as np

from . import nd, autograd, optimizer as opt_mod
from .ops import registry

_HANDLES: Dict[int, object] = {}
_NEXT = itertools.count(1)


def _reg(obj) -> int:
    h = next(_NEXT)
    _HANDLES[h] = obj
    return h


def _get(h: int):
    return _HANDLES[int(h)]


def free(h: int) -> None:
    _HANDLES.pop(int(h), None)


# -- ndarray ---------------------------------------------------------------

def ndarray_from_bytes(shape: List[int], data: bytes) -> int:
    a = np.frombuffer(data, dtype="<f4").reshape(tuple(shape)).copy()
    return _reg(nd.array(a))


def ndarray_zeros(shape: List[int]) -> int:
    return _reg(nd.zeros(tuple(shape)))


def ndarray_to_bytes(h: int):
    a = _get(h).asnumpy().astype("<f4")
    return list(a.shape), a.tobytes()


def ndarray_shape(h: int) -> List[int]:
    return list(_get(h).shape)


def attach_grad(h: int) -> None:
    _get(h).attach_grad()


def grad_of(h: int) -> int:
    g = _get(h).grad
    if g is None:
        raise ValueError("no gradient attached/computed for handle %d"
                         % h)
    return _reg(g)


# -- imperative op invoke (the MXImperativeInvokeEx analog) ---------------

def op_invoke(name: str, in_handles: List[int], attrs_json: str):
    attrs = json.loads(attrs_json) if attrs_json else {}
    # JSON carries lists where MXNet attrs want tuples (kernel=(3,3))
    attrs = {k: tuple(v) if isinstance(v, list) else v
             for k, v in attrs.items()}
    op = registry.get_op(name)
    inputs = [_get(h) for h in in_handles]
    out = registry.invoke(op, inputs, (), attrs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    return [_reg(o) for o in outs]


# -- autograd --------------------------------------------------------------

_RECORD_CTX = []


def record_start() -> None:
    ctx = autograd.record()
    ctx.__enter__()
    _RECORD_CTX.append(ctx)


def record_stop() -> None:
    if _RECORD_CTX:
        _RECORD_CTX.pop().__exit__(None, None, None)


def backward(h: int) -> None:
    _get(h).backward()


# -- optimizer -------------------------------------------------------------

def optimizer_create(name: str, params_json: str) -> int:
    kwargs = json.loads(params_json) if params_json else {}
    optimizer = opt_mod.create(name, **kwargs)
    return _reg({"updater": opt_mod.get_updater(optimizer)})


def optimizer_update(opt_h: int, index: int, weight_h: int,
                     grad_h: int) -> None:
    _get(opt_h)["updater"](int(index), _get(grad_h), _get(weight_h))


# -- scalar convenience ----------------------------------------------------

def ndarray_scalar(h: int) -> float:
    return float(_get(h).asnumpy().reshape(-1)[0])
