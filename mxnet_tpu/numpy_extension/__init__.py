"""``mx.npx`` — operator extensions beyond the NumPy standard.

Reference: ``python/mxnet/ndarray/numpy_extension/`` (the ``npx``
namespace: neural-net ops, framework utilities, and the ``set_np`` switch
re-exported for convenience).  Each function dispatches through the shared
op registry, so results are ``mx.np.ndarray`` and autograd/AMP/hybridize
apply as usual.
"""
from __future__ import annotations

from ..ops.registry import get_op, invoke
from ..numpy import _as_np, _to_input
from ..util import set_np, reset_np, is_np_array, use_np  # noqa: F401


def _apply(op_name, *inputs, **attrs):
    ins = [_to_input(i) for i in inputs]
    return _as_np(invoke(get_op(op_name), ins, (), attrs))


# ------------------------------------------------------------- nn activations

def relu(x):
    return _apply("relu", x)


def sigmoid(x):
    return _apply("sigmoid", x)


def softmax(x, axis=-1):
    return _apply("softmax", x, axis=axis)


def log_softmax(x, axis=-1):
    return _apply("log_softmax", x, axis=axis)


def leaky_relu(x, slope=0.25):
    return _apply("LeakyReLU", x, act_type="leaky", slope=slope)


def gelu(x):
    return _apply("LeakyReLU", x, act_type="gelu")


def activation(x, act_type="relu"):
    return _apply("Activation", x, act_type=act_type)


# --------------------------------------------------------------- nn layers

def fully_connected(x, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    if bias is None:
        no_bias = True
        return _apply("FullyConnected", x, weight, num_hidden=num_hidden,
                      no_bias=True, flatten=flatten)
    return _apply("FullyConnected", x, weight, bias, num_hidden=num_hidden,
                  no_bias=no_bias, flatten=flatten)


def convolution(x, weight, bias=None, **attrs):
    if bias is None:
        return _apply("Convolution", x, weight, no_bias=True, **attrs)
    return _apply("Convolution", x, weight, bias, **attrs)


def pooling(x, **attrs):
    return _apply("Pooling", x, **attrs)


def batch_norm(x, gamma, beta, running_mean, running_var, **attrs):
    return _apply("BatchNorm", x, gamma, beta, running_mean, running_var,
                  **attrs)


def layer_norm(x, gamma, beta, axis=-1, eps=1e-5):
    return _apply("LayerNorm", x, gamma, beta, axis=axis, eps=eps)


def dropout(x, p=0.5, **attrs):
    return _apply("Dropout", x, p=p, **attrs)


def embedding(x, weight, input_dim=None, output_dim=None, **attrs):
    return _apply("Embedding", x, weight, input_dim=input_dim,
                  output_dim=output_dim, **attrs)


def one_hot(x, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    return _apply("one_hot", x, depth=depth, on_value=on_value,
                  off_value=off_value, dtype=dtype)


def pick(x, index, axis=-1, mode="clip", keepdims=False):
    return _apply("pick", x, index, axis=axis, mode=mode, keepdims=keepdims)


def topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False):
    return _apply("topk", x, axis=axis, k=k, ret_typ=ret_typ,
                  is_ascend=is_ascend)


def batch_dot(a, b, transpose_a=False, transpose_b=False):
    return _apply("batch_dot", a, b, transpose_a=transpose_a,
                  transpose_b=transpose_b)


def gamma(x):
    return _apply("gamma", x)


def gammaln(x):
    return _apply("gammaln", x)


def erf(x):
    return _apply("erf", x)


def erfinv(x):
    return _apply("erfinv", x)


def reshape_like(a, b):
    return _apply("reshape_like", a, b)


def arange_like(a, start=0.0, step=1.0, axis=None):
    import jax.numpy as jnp
    from ..numpy import arange
    n = a.shape[axis] if axis is not None else a.size
    return arange(start, start + step * n, step)


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    if sequence_length is None:
        return _apply("SequenceMask", data, value=value, axis=axis,
                      use_sequence_length=use_sequence_length)
    return _apply("SequenceMask", data, sequence_length, value=value,
                  axis=axis, use_sequence_length=use_sequence_length)


# ----------------------------------------------------------------- utilities

def waitall():
    from ..ndarray import waitall as w
    w()


def seed(s):
    from .. import random
    random.seed(s)


def cpu(device_id=0):
    from ..context import cpu as _cpu
    return _cpu(device_id)


def gpu(device_id=0):
    from ..context import gpu as _gpu
    return _gpu(device_id)


def tpu(device_id=0):
    from ..context import tpu as _tpu
    return _tpu(device_id)


def num_gpus():
    from ..context import num_gpus as n
    return n()


def current_device():
    from ..context import current_context
    return current_context()


# module-level conveniences the reference exposes on npx
from ..ndarray import load, save  # noqa: E402,F401
from ..context import current_context  # noqa: E402,F401
