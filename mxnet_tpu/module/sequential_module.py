"""SequentialModule — chain Modules so each consumes the previous
module's outputs (reference: ``python/mxnet/module/sequential_module.py``).
"""
from __future__ import annotations

import logging
from typing import List

from ..base import MXNetError
from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    """A container chaining sub-modules head-to-tail.

    ``add(module, take_labels=True)`` marks the module that receives the
    training labels (typically the last, loss-bearing module).  Binding
    wires each module's data shapes to the previous module's output
    shapes, as the reference does with ``auto_wiring``."""

    META_TAKE_LABELS = "take_labels"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules: List[BaseModule] = []
        self._metas = []
        self._label_module = None
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    def add(self, module, **kwargs):
        self._modules.append(module)
        self._metas.append(dict(kwargs))
        if kwargs.get(self.META_TAKE_LABELS, False):
            self._label_module = module
        return self

    # ------------------------------------------------------------------
    @property
    def data_names(self):
        if not self._modules:
            return []
        return self._modules[0].data_names

    @property
    def output_names(self):
        if not self._modules:
            return []
        return self._modules[-1].output_names

    @property
    def label_shapes(self):
        return (self._label_module.label_shapes
                if self._label_module is not None else [])

    @property
    def data_shapes(self):
        return self._modules[0].data_shapes

    @property
    def output_shapes(self):
        return self._modules[-1].output_shapes

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, **kwargs):
        if not self._modules:
            raise MXNetError("SequentialModule.bind: no modules added")
        cur_shapes = data_shapes
        for i, mod in enumerate(self._modules):
            take_labels = self._metas[i].get(self.META_TAKE_LABELS, False)
            mod.bind(cur_shapes,
                     label_shapes if take_labels else None,
                     for_training=for_training,
                     inputs_need_grad=inputs_need_grad or i > 0)
            out_shapes = mod.output_shapes
            # next module's data inputs are this module's outputs, in
            # its own data_names order
            if i + 1 < len(self._modules):
                nxt = self._modules[i + 1]
                if len(nxt.data_names) != len(out_shapes):
                    raise MXNetError(
                        "SequentialModule: module %d emits %d outputs "
                        "but module %d takes %d inputs"
                        % (i, len(out_shapes), i + 1,
                           len(nxt.data_names)))
                cur_shapes = [(n, s[1]) for n, s in
                              zip(nxt.data_names, out_shapes)]
        self.binded = True
        return self

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, **kwargs):
        # each sub-module owns a subset of arg_params, so per-module
        # missing keys are expected; validate the caller's contract
        # across the WHOLE chain instead
        if not allow_missing and arg_params:
            known = set()
            for mod in self._modules:
                known.update(mod._param_names)
            missing = [k for k in known if k not in arg_params]
            if missing:
                raise MXNetError(
                    "SequentialModule.init_params: arg_params missing "
                    "%s (pass allow_missing=True to initialize them)"
                    % missing)
        for mod in self._modules:
            mod.init_params(initializer=initializer,
                            arg_params=arg_params, aux_params=aux_params,
                            allow_missing=True, force_init=force_init)
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        for mod in self._modules:
            mod.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                               optimizer_params=optimizer_params,
                               force_init=force_init)
        self.optimizer_initialized = True

    def get_params(self):
        args, auxs = {}, {}
        for mod in self._modules:
            a, x = mod.get_params()
            args.update(a)
            auxs.update(x)
        return args, auxs

    # ------------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        from ..io.io import DataBatch
        batch = data_batch
        for i, mod in enumerate(self._modules):
            take_labels = self._metas[i].get(self.META_TAKE_LABELS, False)
            mod.forward(DataBatch(
                data=batch.data,
                label=data_batch.label if take_labels else None),
                is_train=is_train)
            if i + 1 < len(self._modules):
                batch = DataBatch(data=mod.get_outputs(),
                                  label=data_batch.label)

    def backward(self, out_grads=None):
        grads = out_grads
        for i in range(len(self._modules) - 1, -1, -1):
            mod = self._modules[i]
            mod.backward(out_grads=grads)
            if i > 0:
                grads = mod.get_input_grads()

    def update(self):
        for mod in self._modules:
            mod.update()

    def get_outputs(self):
        return self._modules[-1].get_outputs()

    def get_input_grads(self):
        return self._modules[0].get_input_grads()

    def update_metric(self, eval_metric, labels):
        for i, mod in enumerate(self._modules):
            if self._metas[i].get(self.META_TAKE_LABELS, False):
                mod.update_metric(eval_metric, labels)
