"""``mx.mod`` — the symbolic Module training API.

Reference: ``python/mxnet/module/`` (SURVEY.md §2.2 "Module (legacy)").
"""
from .base_module import BaseModule
from .module import Module
from .bucketing_module import BucketingModule
from .sequential_module import SequentialModule
