"""Module — symbolic training on a bound executor.

Reference: ``python/mxnet/module/module.py`` + ``executor_group.py``
(SURVEY.md §3.6).  TPU-native multi-device: instead of the reference's
``DataParallelExecutorGroup`` (one executor per GPU + kvstore reduce),
a multi-context Module shards the batch over a 1-axis device mesh with
``jax.sharding`` and lets GSPMD insert the gradient all-reduce over ICI —
the executor's single jit computation is the whole data-parallel step
(SURVEY.md §2.4 row "Data parallel, single-node multi-device").
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError
from ..context import Context, cpu
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray
from .base_module import BaseModule

__all__ = ["Module"]


def _shape_list(shapes):
    """Normalize [(name, shape)] / DataDesc list / dict → list of tuples."""
    if shapes is None:
        return []
    out = []
    for s in shapes:
        if isinstance(s, tuple) and len(s) == 2 and isinstance(s[0], str):
            out.append((s[0], tuple(s[1])))
        elif hasattr(s, "name") and hasattr(s, "shape"):
            out.append((s.name, tuple(s.shape)))
        else:
            raise MXNetError("bad data_shapes entry: %r" % (s,))
    return out


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        if context is None:
            context = cpu()
        self._context = list(context) if isinstance(
            context, (list, tuple)) else [context]
        self._fixed_param_names = list(fixed_param_names or [])

        arg_names = symbol.list_arguments()
        input_names = set(self._data_names) | set(self._label_names)
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()

        self._exec = None
        self._optimizer = None
        self._opt_states: Dict[str, object] = {}
        self._data_shapes = None
        self._label_shapes = None
        self._mesh = None

    # ------------------------------------------------------------------

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        shapes = dict(self._data_shapes + (self._label_shapes or []))
        _, out_shapes, _ = self._symbol.infer_shape(**shapes)
        return list(zip(self.output_names, out_shapes))

    # ------------------------------------------------------------------

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, grad_req="write"):
        if self.binded and not force_rebind:
            return
        self._data_shapes = _shape_list(data_shapes)
        self._label_shapes = _shape_list(label_shapes)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

        shapes = dict(self._data_shapes + self._label_shapes)
        reqs = {}
        for n in self._symbol.list_arguments():
            if n in self._param_names and n not in self._fixed_param_names:
                reqs[n] = grad_req if for_training else "null"
            elif inputs_need_grad and n in self._data_names:
                reqs[n] = grad_req
            else:
                reqs[n] = "null"
        old_params = None
        if self._exec is not None:
            old_params = self.get_params()
        self._exec = self._symbol.simple_bind(
            ctx=self._context[0], grad_req=reqs, **shapes)
        if old_params is not None:
            self.set_params(*old_params, allow_missing=True,
                            force_init=True, allow_extra=True)
            self.params_initialized = True
        self.binded = True

        if len(self._context) > 1:
            from ..parallel import make_mesh
            self._mesh = make_mesh(
                {"dp": len(self._context)},
                devices=[c.jax_device for c in self._context])

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("init_params: call bind first")
        if arg_params is None and getattr(self, "_preloaded", None):
            arg_params, aux_params = self._preloaded
            allow_missing = True
        if initializer is None:
            from ..initializer import Uniform
            initializer = Uniform(0.01)
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arr._set_data(arg_params[name]._data
                              if isinstance(arg_params[name], NDArray)
                              else arg_params[name])
            else:
                if arg_params is not None and not allow_missing:
                    raise MXNetError("init_params: %s missing" % name)
                initializer(name, arr)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                arr._set_data(aux_params[name]._data
                              if isinstance(aux_params[name], NDArray)
                              else aux_params[name])
            else:
                initializer(name, arr)
        self.params_initialized = True

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=True,
                         force_init=force_init, allow_extra=allow_extra)

    def get_params(self):
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    # ------------------------------------------------------------------

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        from .. import optimizer as opt
        if isinstance(optimizer, str):
            optimizer = opt.create(optimizer, **dict(optimizer_params))
        self._optimizer = optimizer
        self._opt_states = {}
        for i, name in enumerate(self._param_names):
            if self._exec.grad_req.get(name, "null") != "null":
                self._opt_states[name] = optimizer.create_state(
                    i, self._exec.arg_dict[name])
        self.optimizer_initialized = True

    # ------------------------------------------------------------------

    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feeds[name] = arr
        if self._label_names and data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                feeds[name] = arr
        if self._mesh is not None:
            feeds = self._shard_feeds(feeds)
        self._exec.forward(is_train=is_train, **feeds)

    def _shard_feeds(self, feeds):
        """Batch-shard input arrays over the dp mesh; GSPMD handles the
        rest of the data-parallel step (≡ executor_group split_and_load +
        kvstore reduce in the reference)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        sharded = {}
        for k, v in feeds.items():
            data = v._data if isinstance(v, NDArray) else v
            sharded[k] = jax.device_put(
                data, NamedSharding(self._mesh, P("dp")))
        return sharded

    def backward(self, out_grads=None):
        self._exec.backward(out_grads)

    def update(self):
        if not self.optimizer_initialized:
            raise MXNetError("update: call init_optimizer first")
        for i, name in enumerate(self._param_names):
            if name not in self._opt_states:
                continue
            w = self._exec.arg_dict[name]
            g = self._exec.grad_dict[name]
            self._optimizer.update(i, w, g, self._opt_states[name])

    def get_outputs(self, merge_multi_context=True):
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        return [self._exec.grad_dict[n] for n in self._data_names
                if n in self._exec.grad_dict]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    # ------------------------------------------------------------------

    def install_monitor(self, monitor):
        monitor.install(self._exec)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        from ..model import save_checkpoint
        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg, aux)
        if save_optimizer_states:
            self.save_optimizer_states("%s-%04d.states" % (prefix, epoch))

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint
        sym, arg, aux = load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._preloaded = (arg, aux)
        return mod

    def save_optimizer_states(self, fname):
        import pickle
        flat = {}
        for name, st in self._opt_states.items():
            flat[name] = _states_to_numpy(st)
        with open(fname, "wb") as f:
            pickle.dump(flat, f)

    def load_optimizer_states(self, fname):
        import pickle
        with open(fname, "rb") as f:
            flat = pickle.load(f)
        for name, st in flat.items():
            if name in self._opt_states:
                self._opt_states[name] = _states_from_numpy(st)


def _states_to_numpy(st):
    if st is None:
        return None
    if isinstance(st, (tuple, list)):
        return type(st)(_states_to_numpy(s) for s in st)
    if isinstance(st, NDArray):
        return st.asnumpy()
    return st


def _states_from_numpy(st):
    import numpy as np
    if st is None:
        return None
    if isinstance(st, (tuple, list)):
        return type(st)(_states_from_numpy(s) for s in st)
    if isinstance(st, np.ndarray):
        return nd.array(st)
    return st
